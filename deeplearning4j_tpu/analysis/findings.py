"""Typed findings + the rule catalog for graftcheck (docs/STATIC_ANALYSIS.md).

Every rule has a STABLE id (``GC<family><nn>``) — baselines and inline
pragmas key on it, so ids are append-only: retire a rule by deleting its
checker, never by reusing its number.  Families:

- ``GC0xx`` — meta (suppression hygiene: the analyzer analyzing its own
  pragmas/baseline)
- ``GC1xx`` — JIT purity (host effects inside traced code)
- ``GC2xx`` — determinism (wall clock / global RNG / hash-seed
  dependence on paths that back the bit-identity gates)
- ``GC3xx`` — thread safety (21+ ``threading.Thread`` spawn sites after
  PRs 6-9; lock discipline, teardown joins, acquisition order)
- ``GC4xx`` — repo contracts (span taxonomy, metric naming, nothing-
  stranded futures, justified exception suppression)
"""

from __future__ import annotations

from dataclasses import dataclass, field, asdict
from typing import Dict, Optional

ERROR = "error"
WARNING = "warning"


@dataclass(frozen=True)
class Rule:
    id: str
    name: str
    severity: str
    summary: str


# the catalog — ids are stable, see module docstring
RULES: Dict[str, Rule] = {r.id: r for r in [
    # meta
    Rule("GC001", "unknown-pragma-rule", WARNING,
         "a `# graftcheck: disable=` pragma names a rule id that does "
         "not exist — the suppression does nothing"),
    Rule("GC002", "pragma-missing-justification", ERROR,
         "a suppression pragma has no `(reason)` — every accepted "
         "finding must say why it is accepted"),
    Rule("GC003", "unused-pragma", WARNING,
         "a suppression pragma matched no finding — stale suppressions "
         "hide future regressions"),
    # JIT purity
    Rule("GC101", "host-sync-in-traced-code", ERROR,
         "`.item()`/`.tolist()`/`.block_until_ready()`/`float()/int()/"
         "bool()` on a traced value inside traced code forces a device "
         "sync per call (or fails under jit)"),
    Rule("GC102", "impure-call-in-traced-code", ERROR,
         "`print`/`time.*`/`random`/`np.random`/env/file I/O inside "
         "traced code runs at TRACE time only — silently frozen into "
         "the compiled program"),
    Rule("GC103", "state-mutation-in-traced-code", ERROR,
         "assigning `self.*`/`global` state inside traced code mutates "
         "host state at trace time, not per step — stale after the "
         "first compile"),
    Rule("GC104", "jit-in-loop", WARNING,
         "`jax.jit(...)` constructed inside a loop body builds a fresh "
         "callable (new cache) per iteration — a recompile hazard"),
    # determinism
    Rule("GC201", "wall-clock", WARNING,
         "`time.time()`/`datetime.now()` is nondeterministic; on a "
         "step/replay/export path it breaks the bit-identity gates — "
         "inject a clock or pragma-tag the site as a wall-anchor"),
    Rule("GC202", "global-rng", WARNING,
         "`random.*`/`np.random.*` global-state RNG (or unseeded "
         "`default_rng()`) is process-lifetime nondeterministic — "
         "thread a seeded generator instead"),
    Rule("GC203", "seed-dependent-hash", WARNING,
         "builtin `hash()` of a str/bytes varies per process "
         "(PYTHONHASHSEED) — never stable across workers or replays"),
    # thread safety
    Rule("GC301", "unlocked-shared-mutation", ERROR,
         "read-modify-write of an attribute shared between a Thread "
         "target and other methods without holding a common lock"),
    Rule("GC302", "non-daemon-thread-without-join", ERROR,
         "a non-daemon thread with no join() on any teardown path "
         "keeps the process alive after main exits"),
    Rule("GC303", "lock-order-cycle", ERROR,
         "two locks are acquired in opposite nesting orders on "
         "different paths — a deadlock waiting for the right schedule"),
    # contracts
    Rule("GC401", "span-name-not-in-taxonomy", ERROR,
         "a span()/instant() name is missing from the "
         "docs/OBSERVABILITY.md taxonomy table — pod timelines become "
         "unreadable and the docs rot"),
    Rule("GC402", "metric-name-convention", ERROR,
         "metric names must be snake_case; counters on the GLOBAL "
         "registry end in `_total` (docs/OBSERVABILITY.md schema)"),
    Rule("GC403", "future-resolution-not-guaranteed", WARNING,
         "a function that resolves futures has an exception path that "
         "neither resolves nor re-raises — the serving \"nothing "
         "stranded\" invariant cannot be shown to hold"),
    Rule("GC404", "silent-exception-swallow", ERROR,
         "`except Exception: pass` (or broader) drops the failure on "
         "the floor — narrow the type, record an obs instant/counter, "
         "or pragma with a justification"),
]}

FAMILIES = {"meta": ("GC0",), "purity": ("GC1",), "determinism": ("GC2",),
            "threads": ("GC3",), "contracts": ("GC4",)}


@dataclass(frozen=True)
class Finding:
    """One analyzer hit.  ``symbol`` is the dotted in-module qualname of
    the enclosing function/class ("" at module level) — baselines match
    on (rule, path, symbol) so they survive line drift."""

    rule: str
    path: str          # repo-relative posix path
    line: int
    col: int
    symbol: str
    message: str
    context: str = ""  # e.g. "traced via jax.jit at nn/multilayer.py:418"

    @property
    def severity(self) -> str:
        r = RULES.get(self.rule)
        return r.severity if r else ERROR

    def key(self):
        return (self.rule, self.path, self.symbol)

    def to_dict(self) -> dict:
        d = asdict(self)
        d["severity"] = self.severity
        return d

    def format(self) -> str:
        ctx = f"  [{self.context}]" if self.context else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"({self.severity}) {self.message}{ctx}")
