"""graftcheck runner: parse -> call graph -> rules -> suppressions.

Suppression surfaces, both requiring a justification:

- inline pragma on the flagged line (or the comment line directly
  above): ``# graftcheck: disable=GC201 (wall-anchor: dashboard ts)``
- a reviewed entry in ``analysis/baseline.json`` matching the finding's
  (rule, path, symbol) key — line-number independent, so baselines
  survive unrelated edits.

Suppression hygiene is itself analyzed: unknown rule ids (GC001),
missing justifications (GC002), and pragmas/baseline entries that no
longer match anything (GC003) are findings too.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import contracts, determinism, purity, threads
from .callgraph import CallGraph, load_package
from .findings import Finding, RULES

_PRAGMA = re.compile(
    r"#\s*graftcheck:\s*disable=([A-Za-z0-9,\s]+?)"
    r"(?:\s*\((?P<reason>.*)\))?\s*$")

_PKG_DIR = "deeplearning4j_tpu"


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def default_taxonomy_path() -> str:
    return os.path.join(repo_root(), "docs", "OBSERVABILITY.md")


@dataclass
class Pragma:
    path: str
    line: int           # line the pragma is written on (1-based)
    applies_to: int     # line findings must be on to match
    rules: Tuple[str, ...]
    reason: str
    used: bool = False


@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)   # unsuppressed
    suppressed: List[Tuple[Finding, str]] = field(default_factory=list)
    graph: Optional[CallGraph] = None
    n_files: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "files": self.n_files,
            "rules": sorted(RULES),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [dict(f.to_dict(), suppressed_by=how)
                           for f, how in self.suppressed],
            "summary": {
                "unsuppressed": len(self.findings),
                "suppressed": len(self.suppressed),
            },
        }


def _scan_pragmas(mod) -> List[Pragma]:
    out: List[Pragma] = []
    for i, raw in enumerate(mod.lines, start=1):
        m = _PRAGMA.search(raw)
        if m is None:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = (m.group("reason") or "").strip()
        stripped = raw.strip()
        applies = i
        if stripped.startswith("#"):
            # comment-only line: applies to the next non-comment line
            j = i
            while j < len(mod.lines):
                nxt = mod.lines[j].strip()
                if nxt and not nxt.startswith("#"):
                    applies = j + 1
                    break
                j += 1
        out.append(Pragma(mod.relpath, i, applies, rules, reason))
    return out


def _load_baseline(path: Optional[str]) -> Tuple[List[dict], List[Finding]]:
    if path is None or not os.path.exists(path):
        return [], []
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("entries", [])
    problems: List[Finding] = []
    rel = os.path.relpath(path, repo_root()).replace(os.sep, "/")
    for i, e in enumerate(entries):
        if not str(e.get("justification", "")).strip():
            problems.append(Finding(
                "GC002", rel, 0, 0, e.get("rule", "?"),
                f"baseline entry #{i} ({e.get('rule')} {e.get('path')}"
                f"::{e.get('symbol')}) has no justification"))
        if e.get("rule") not in RULES:
            problems.append(Finding(
                "GC001", rel, 0, 0, e.get("rule", "?"),
                f"baseline entry #{i} names unknown rule "
                f"'{e.get('rule')}'"))
    return entries, problems


def run_analysis(root: Optional[str] = None,
                 paths: Optional[Sequence[str]] = None,
                 baseline_path: Optional[str] = "<default>",
                 taxonomy_path: Optional[str] = "<default>",
                 ) -> AnalysisResult:
    """Analyze the package (or explicit ``paths`` for fixture runs).

    ``baseline_path`` / ``taxonomy_path``: ``"<default>"`` resolves to
    the repo files; ``None`` disables the baseline / the GC401 taxonomy
    check respectively.
    """
    root = root or repo_root()
    if baseline_path == "<default>":
        baseline_path = default_baseline_path()
    if taxonomy_path == "<default>":
        taxonomy_path = default_taxonomy_path()

    if paths:
        files = []
        for p in paths:
            full = p if os.path.isabs(p) else os.path.join(root, p)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            modkey = os.path.splitext(os.path.basename(full))[0]
            with open(full, "r", encoding="utf-8") as f:
                files.append((rel, modkey, f.read()))
    else:
        files = load_package(root, _PKG_DIR)

    graph = CallGraph.build(files)

    taxonomy = None
    if taxonomy_path and os.path.exists(taxonomy_path):
        with open(taxonomy_path, "r", encoding="utf-8") as f:
            taxonomy = contracts.parse_taxonomy(f.read())

    raw: List[Finding] = []
    raw.extend(purity.check_purity(graph))
    raw.extend(determinism.check_determinism(graph))
    raw.extend(threads.check_threads(graph))
    raw.extend(contracts.run_contracts(graph, taxonomy))

    # -- suppression ---------------------------------------------------
    pragmas: Dict[str, List[Pragma]] = {}
    meta: List[Finding] = []
    for mod in graph.modules.values():
        ps = _scan_pragmas(mod)
        pragmas[mod.relpath] = ps
        for p in ps:
            for r in p.rules:
                if r not in RULES:
                    meta.append(Finding(
                        "GC001", p.path, p.line, 0, "",
                        f"pragma names unknown rule '{r}'"))
            if not p.reason:
                meta.append(Finding(
                    "GC002", p.path, p.line, 0, "",
                    "suppression pragma has no (justification)"))

    entries, baseline_problems = _load_baseline(baseline_path)
    meta.extend(baseline_problems)
    used_entries: Set[int] = set()

    result = AnalysisResult(graph=graph, n_files=len(files))
    for f in raw:
        suppressed_by = None
        for p in pragmas.get(f.path, ()):
            if f.rule in p.rules and p.reason and \
                    f.line in (p.line, p.applies_to):
                p.used = True
                suppressed_by = f"pragma@{p.path}:{p.line} ({p.reason})"
                break
        if suppressed_by is None:
            for i, e in enumerate(entries):
                if (e.get("rule"), e.get("path"), e.get("symbol")) == \
                        f.key() and str(e.get("justification", "")).strip():
                    used_entries.add(i)
                    suppressed_by = f"baseline#{i} ({e['justification']})"
                    break
        if suppressed_by is not None:
            result.suppressed.append((f, suppressed_by))
        else:
            result.findings.append(f)

    # suppression hygiene
    for ps in pragmas.values():
        for p in ps:
            if not p.used and p.reason and \
                    all(r in RULES for r in p.rules):
                meta.append(Finding(
                    "GC003", p.path, p.line, 0, "",
                    f"pragma disable={','.join(p.rules)} matched no "
                    "finding — remove it or the rule regressed"))
    if entries:
        rel = os.path.relpath(baseline_path,
                              repo_root()).replace(os.sep, "/")
        for i, e in enumerate(entries):
            if i not in used_entries and e.get("rule") in RULES and \
                    str(e.get("justification", "")).strip():
                meta.append(Finding(
                    "GC003", rel, 0, 0, e.get("symbol") or "",
                    f"baseline entry #{i} ({e.get('rule')} "
                    f"{e.get('path')}::{e.get('symbol')}) matched no "
                    "finding — remove it"))
    result.findings.extend(meta)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return result


def update_baseline(result: AnalysisResult, baseline_path: str,
                    justification: str) -> int:
    """Append every currently-unsuppressed finding to the baseline with
    ``justification``.  Refuses (ValueError) without one."""
    if not justification or not justification.strip():
        raise ValueError(
            "--baseline-update requires --justification: every accepted "
            "finding must say WHY it is accepted")
    if os.path.exists(baseline_path):
        with open(baseline_path, "r", encoding="utf-8") as f:
            data = json.load(f)
    else:
        data = {"version": 1, "entries": []}
    keys = {(e.get("rule"), e.get("path"), e.get("symbol"))
            for e in data["entries"]}
    added = 0
    for f in result.findings:
        if f.rule in ("GC001", "GC002", "GC003"):
            continue   # fix suppression hygiene, never baseline it
        if f.key() in keys:
            continue
        keys.add(f.key())
        data["entries"].append({
            "rule": f.rule, "path": f.path, "symbol": f.symbol,
            "message": f.message, "justification": justification.strip(),
        })
        added += 1
    data["entries"].sort(key=lambda e: (e["path"], e["rule"], e["symbol"]))
    with open(baseline_path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1)
        f.write("\n")
    return added
