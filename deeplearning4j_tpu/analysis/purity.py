"""GC1xx — JIT purity rules over the traced-code set.

Everything here runs only inside functions the jit-boundary pass marked
traced (callgraph.CallGraph.traced): host syncs, host side effects, and
host-state mutation are legal in eager code, so the traced set is what
keeps these rules quiet where they should be.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .callgraph import CallGraph, FunctionInfo, dotted
from .findings import Finding

# calls that force a device->host sync (or outright fail) on traced values
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# builtins that coerce a traced value to a host scalar
_COERCERS = {"float", "int", "bool", "complex"}
# numpy entry points that materialize a traced value on host
_NP_MATERIALIZE = {"asarray", "array", "copy", "save", "savez"}

# dotted prefixes whose call is a host side effect frozen at trace time
_IMPURE_CALLS = {
    "print", "input", "open",
    "time.time", "time.time_ns", "time.monotonic", "time.perf_counter",
    "time.sleep", "time.process_time",
    "os.getenv", "os.urandom", "os.system",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today",
}
_IMPURE_PREFIXES = ("random.", "np.random.", "numpy.random.", "logging.",
                    "logger.", "warnings.")
# jax.debug.* is the sanctioned way to print from traced code
_ALLOWED_PREFIXES = ("jax.debug.",)


def _body_nodes(fn: FunctionInfo):
    """Walk fn's body, NOT descending into nested function defs (they
    are separate FunctionInfos, checked iff themselves traced)."""
    stack = list(ast.iter_child_nodes(fn.node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _tainted_names(fn: FunctionInfo) -> Set[str]:
    """First-order taint: parameters + names assigned from expressions
    mentioning a tainted name.  Iterates to a fixed point (bodies are
    small)."""
    tainted = set(fn.params)
    changed = True
    while changed:
        changed = False
        for node in _body_nodes(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                value = node.value
                if value is None:
                    continue
                rhs_names = {n.id for n in ast.walk(value)
                             if isinstance(n, ast.Name)}
                if not (rhs_names & tainted):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) and n.id not in tainted:
                            tainted.add(n.id)
                            changed = True
            elif isinstance(node, ast.For):
                it_names = {n.id for n in ast.walk(node.iter)
                            if isinstance(n, ast.Name)}
                if it_names & tainted:
                    for n in ast.walk(node.target):
                        if isinstance(n, ast.Name) and n.id not in tainted:
                            tainted.add(n.id)
                            changed = True
    return tainted


def _is_tainted_expr(expr: ast.AST, tainted: Set[str]) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in tainted:
            return True
    return False


def check_purity(graph: CallGraph) -> List[Finding]:
    out: List[Finding] = []
    for fi in graph.functions.values():
        if graph.is_traced(fi):
            out.extend(_check_traced_fn(graph, fi))
        out.extend(_check_jit_in_loop(fi))
    return out


def _check_traced_fn(graph: CallGraph, fi: FunctionInfo) -> List[Finding]:
    out: List[Finding] = []
    reason = graph.traced.get(fi.gid, "")
    tainted = _tainted_names(fi)
    rel = fi.module.relpath
    for node in _body_nodes(fi):
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            # GC101: host syncs
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SYNC_METHODS:
                out.append(Finding(
                    "GC101", rel, node.lineno, node.col_offset, fi.qual,
                    f".{node.func.attr}() inside traced code forces a "
                    "host sync (or fails under jit)", reason))
            elif name in _COERCERS and node.args and \
                    _is_tainted_expr(node.args[0], tainted):
                out.append(Finding(
                    "GC101", rel, node.lineno, node.col_offset, fi.qual,
                    f"{name}() of a traced value inside traced code "
                    "forces a host sync", reason))
            elif name is not None and name.split(".")[0] in ("np", "numpy") \
                    and name.split(".")[-1] in _NP_MATERIALIZE \
                    and node.args and _is_tainted_expr(node.args[0], tainted):
                out.append(Finding(
                    "GC101", rel, node.lineno, node.col_offset, fi.qual,
                    f"{name}() materializes a traced value on host",
                    reason))
            # GC102: host side effects
            elif name is not None and \
                    not name.startswith(_ALLOWED_PREFIXES) and \
                    (name in _IMPURE_CALLS
                     or name.startswith(_IMPURE_PREFIXES)):
                out.append(Finding(
                    "GC102", rel, node.lineno, node.col_offset, fi.qual,
                    f"{name}() inside traced code runs at trace time "
                    "only — its effect/value is frozen into the "
                    "compiled program", reason))
        # GC103: host-state mutation
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    out.append(Finding(
                        "GC103", rel, t.lineno, t.col_offset, fi.qual,
                        f"assignment to self.{t.attr} inside traced "
                        "code mutates host state at trace time — it "
                        "will not re-run per step", reason))
        elif isinstance(node, ast.Global):
            out.append(Finding(
                "GC103", rel, node.lineno, node.col_offset, fi.qual,
                "`global` declaration inside traced code — host-state "
                "mutation at trace time", reason))
    return out


def _check_jit_in_loop(fi: FunctionInfo) -> List[Finding]:
    """GC104: jax.jit(...) constructed lexically inside a loop body."""
    out: List[Finding] = []
    rel = fi.module.relpath

    def scan(node: ast.AST, in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # a def in a loop builds once per call, not here
            child_in_loop = in_loop
            if isinstance(child, (ast.For, ast.While, ast.AsyncFor)):
                child_in_loop = True
            if in_loop and isinstance(child, ast.Call):
                name = dotted(child.func)
                norm = fi.module.normalize(name) if name else None
                leaf = norm.split(".")[-1] if norm else ""
                if leaf == "jit" and (("jax" in norm) or name == "jit"):
                    out.append(Finding(
                        "GC104", rel, child.lineno, child.col_offset,
                        fi.qual,
                        "jax.jit(...) constructed inside a loop body — "
                        "a fresh callable (and jit cache) per "
                        "iteration; hoist it out of the loop"))
            scan(child, child_in_loop)

    scan(fi.node, False)
    return out
