"""GC3xx — thread-safety rules.

PRs 6-9 grew the repo to 20+ ``threading.Thread`` spawn sites (serving
replicas/supervisor, prefetch producers, watchdogs, heartbeats, HTTP
servers, pub/sub pumps).  These rules encode the discipline those PRs
established by hand:

- GC301: an attribute mutated read-modify-write style from a thread
  target while also accessed from other methods must hold a common lock
  (`x += 1` is a LOAD/ADD/STORE interleaving hazard even under the GIL).
- GC302: a non-daemon thread must have a ``join()`` on some teardown
  path, or the process never exits.
- GC303: nested ``with lockA: with lockB`` orders must be globally
  consistent — an opposite nesting anywhere is a latent deadlock.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, FunctionInfo, ModuleInfo, dotted
from .findings import Finding

_LOCKISH_TYPES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}
_LOCKISH_NAME = re.compile(r"lock|cond|mutex|mu$", re.IGNORECASE)


def _is_thread_ctor(node: ast.Call) -> bool:
    name = dotted(node.func)
    return name in ("threading.Thread", "Thread")


def _kwarg(node: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


class _ThreadSite:
    __slots__ = ("call", "fn", "target_expr", "daemon", "assigned_to")

    def __init__(self, call: ast.Call, fn: Optional[FunctionInfo]):
        self.call = call
        self.fn = fn
        self.target_expr = _kwarg(call, "target")
        d = _kwarg(call, "daemon")
        self.daemon = (isinstance(d, ast.Constant) and d.value is True)
        self.assigned_to: Optional[Tuple[str, ...]] = None  # ("self","_t") | ("t",)


def check_threads(graph: CallGraph) -> List[Finding]:
    out: List[Finding] = []
    for mod in graph.modules.values():
        sites = _collect_sites(graph, mod)
        out.extend(_check_joins(mod, sites))
        out.extend(_check_shared_mutation(graph, mod, sites))
        out.extend(_check_lock_order(graph, mod))
    return out


# -- site collection ---------------------------------------------------

def _collect_sites(graph: CallGraph, mod: ModuleInfo) -> List[_ThreadSite]:
    node_to_fn: Dict[int, FunctionInfo] = {
        id(fi.node): fi for fi in mod.functions.values()}
    sites: List[_ThreadSite] = []

    def walk(node: ast.AST, fn: Optional[FunctionInfo]) -> None:
        for child in ast.iter_child_nodes(node):
            child_fn = node_to_fn.get(id(child), fn)
            if isinstance(child, ast.Assign) and \
                    isinstance(child.value, ast.Call) and \
                    _is_thread_ctor(child.value):
                site = _ThreadSite(child.value, fn)
                t = child.targets[0]
                if isinstance(t, ast.Name):
                    site.assigned_to = (t.id,)
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name):
                    site.assigned_to = (t.value.id, t.attr)
                sites.append(site)
            elif isinstance(child, ast.Call) and _is_thread_ctor(child):
                sites.append(_ThreadSite(child, fn))
            walk(child, child_fn)

    walk(mod.tree, None)
    # de-dup: the Assign case visits the Call child again
    seen: Set[int] = set()
    uniq = []
    for s in sites:
        if id(s.call) in seen:
            continue
        seen.add(id(s.call))
        uniq.append(s)
    # prefer the assigned variant when both were recorded
    by_call: Dict[int, _ThreadSite] = {}
    for s in uniq:
        prev = by_call.get(id(s.call))
        if prev is None or (prev.assigned_to is None and s.assigned_to):
            by_call[id(s.call)] = s
    return list(by_call.values())


# -- GC302: non-daemon thread without join -----------------------------

def _check_joins(mod: ModuleInfo, sites: List[_ThreadSite]) -> List[Finding]:
    out: List[Finding] = []
    for s in sites:
        if s.daemon:
            continue
        call, fn = s.call, s.fn
        # `t.daemon = True` before start() in the same function?
        if s.assigned_to and fn is not None and \
                _sets_daemon(fn.node, s.assigned_to):
            continue
        if _has_join(mod, fn, s):
            continue
        out.append(Finding(
            "GC302", mod.relpath, call.lineno, call.col_offset,
            fn.qual if fn else "",
            "non-daemon Thread with no join() on any teardown path — "
            "the process cannot exit while it runs (pass daemon=True "
            "or join it in close()/stop()/shutdown())"))
    return out


def _sets_daemon(scope: ast.AST, target: Tuple[str, ...]) -> bool:
    for n in ast.walk(scope):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                        and _attr_path(t.value) == target:
                    return isinstance(n.value, ast.Constant) and \
                        n.value.value is True
    return False


def _attr_path(node: ast.AST) -> Optional[Tuple[str, ...]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _has_join(mod: ModuleInfo, fn: Optional[FunctionInfo],
              s: _ThreadSite) -> bool:
    tgt = s.assigned_to
    if tgt is None:
        # anonymous `Thread(...).start()` — no handle, nothing can join
        return False
    if tgt[0] == "self" and len(tgt) == 2:
        # teardown usually lives in another method: search the module
        # for `<anything>.<attr>.join(`
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "join":
                path = _attr_path(n.func.value)
                if path and path[-1] == tgt[1]:
                    return True
        return False
    # local handle: join must happen in the same function, unless the
    # handle escapes (appended/stored/returned) — then any join() on an
    # iteration over a container is accepted module-wide
    name = tgt[0]
    scope = fn.node if fn else mod.tree
    escapes = False
    for n in ast.walk(scope):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            if n.func.attr == "join":
                path = _attr_path(n.func.value)
                if path and path[0] == name:
                    return True
            if n.func.attr in ("append", "add", "put") and any(
                    isinstance(a, ast.Name) and a.id == name
                    for a in n.args):
                escapes = True
        elif isinstance(n, (ast.Return, ast.Yield)) and \
                isinstance(getattr(n, "value", None), ast.Name) and \
                n.value.id == name:
            escapes = True
        elif isinstance(n, ast.Assign) and \
                isinstance(n.value, ast.Name) and n.value.id == name:
            escapes = True
        elif isinstance(n, ast.Subscript) and \
                isinstance(n.ctx, ast.Store):
            escapes = True
    if escapes:
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "join":
                return True
    return False


# -- GC301: unlocked shared mutation -----------------------------------

def _lock_attrs(mod: ModuleInfo, class_name: str) -> Set[str]:
    attrs: Set[str] = set()
    for fi in mod.functions.values():
        if fi.class_name != class_name:
            continue
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                tname = dotted(n.value.func) or ""
                if tname.split(".")[-1] in _LOCKISH_TYPES:
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            attrs.add(t.attr)
    return attrs


def _lockish(expr: ast.AST, lock_attrs: Set[str]) -> Optional[str]:
    """Lock identity string for a with-item, or None."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        if expr.attr in lock_attrs or _LOCKISH_NAME.search(expr.attr):
            return f"self.{expr.attr}"
    elif isinstance(expr, ast.Name) and _LOCKISH_NAME.search(expr.id):
        return expr.id
    return None


class _AccessWalker:
    """Per-function walk recording self.<attr> accesses with whether a
    lock-ish `with` was held, plus RMW (read-modify-write) sites."""

    def __init__(self, fi: FunctionInfo, lock_attrs: Set[str]):
        self.fi = fi
        self.lock_attrs = lock_attrs
        self.reads: Set[str] = set()
        self.writes: Set[str] = set()
        self.unlocked_rmw: List[Tuple[str, ast.AST]] = []
        self._depth = 0
        self._walk(fi.node)

    def _walk(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and child is not self.fi.node:
                continue
            if isinstance(child, ast.With):
                held = [ _lockish(item.context_expr, self.lock_attrs)
                         for item in child.items ]
                n_held = sum(1 for h in held if h)
                self._depth += n_held
                self._walk(child)
                self._depth -= n_held
                continue
            self._record(child)
            self._walk(child)

    def _record(self, node: ast.AST) -> None:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            if isinstance(node.ctx, ast.Store):
                self.writes.add(node.attr)
            elif isinstance(node.ctx, ast.Load):
                self.reads.add(node.attr)
        if isinstance(node, ast.AugAssign) and \
                _self_attr(node.target) and self._depth == 0:
            self.unlocked_rmw.append((node.target.attr, node))
        elif isinstance(node, ast.Assign) and self._depth == 0:
            for t in node.targets:
                if _self_attr(t) and _mentions_self_attr(node.value, t.attr):
                    self.unlocked_rmw.append((t.attr, node))


def _self_attr(node: ast.AST) -> bool:
    return isinstance(node, ast.Attribute) and \
        isinstance(node.value, ast.Name) and node.value.id == "self"


def _mentions_self_attr(expr: ast.AST, attr: str) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and n.attr == attr and \
                isinstance(n.value, ast.Name) and n.value.id == "self":
            return True
    return False


def _thread_context_fns(graph: CallGraph, mod: ModuleInfo,
                        class_name: str,
                        sites: List[_ThreadSite]) -> Set[str]:
    """gids of class-local functions that run on a spawned thread
    (targets + their class-local transitive callees)."""
    entries: Set[str] = set()
    for s in sites:
        if s.fn is None or s.fn.class_name != class_name:
            continue
        t = s.target_expr
        gid = None
        if isinstance(t, ast.Name):
            gid = graph._resolve(mod, s.fn, ("name", t.id))
        elif isinstance(t, ast.Attribute) and \
                isinstance(t.value, ast.Name) and t.value.id == "self":
            gid = graph._resolve(mod, s.fn, ("self", t.attr))
        if gid is not None:
            entries.add(gid)
    # transitive closure within the class
    work = list(entries)
    while work:
        gid = work.pop()
        fi = graph.functions.get(gid)
        if fi is None:
            continue
        for callee in graph.edges_of(fi):
            cf = graph.functions.get(callee)
            if cf is not None and cf.module is mod and \
                    cf.class_name == class_name and callee not in entries:
                entries.add(callee)
                work.append(callee)
    return entries


def _check_shared_mutation(graph: CallGraph, mod: ModuleInfo,
                           sites: List[_ThreadSite]) -> List[Finding]:
    out: List[Finding] = []
    for class_name in mod.classes:
        class_fns = [fi for fi in mod.functions.values()
                     if fi.class_name == class_name]
        if not class_fns:
            continue
        thread_ctx = _thread_context_fns(graph, mod, class_name, sites)
        spawns = any(s.fn is not None and s.fn.class_name == class_name
                     for s in sites)
        if not spawns:
            continue
        lock_attrs = _lock_attrs(mod, class_name)
        walkers = {fi.gid: _AccessWalker(fi, lock_attrs)
                   for fi in class_fns}
        # attr -> contexts that touch it (excluding __init__)
        touched: Dict[str, Set[bool]] = {}
        for fi in class_fns:
            if fi.qual.split(".")[-1] == "__init__":
                continue
            w = walkers[fi.gid]
            for attr in (w.reads | w.writes):
                touched.setdefault(attr, set()).add(fi.gid in thread_ctx)
        for fi in class_fns:
            if fi.qual.split(".")[-1] == "__init__":
                continue
            for attr, node in walkers[fi.gid].unlocked_rmw:
                ctxs = touched.get(attr, set())
                if len(ctxs) < 2:   # not shared across thread boundary
                    continue
                where = "a thread target" if fi.gid in thread_ctx \
                    else "outside the thread"
                out.append(Finding(
                    "GC301", mod.relpath, node.lineno, node.col_offset,
                    fi.qual,
                    f"read-modify-write of self.{attr} without a lock "
                    f"in {where}, but self.{attr} is shared across the "
                    f"thread boundary of {class_name} — wrap in the "
                    "class lock"))
    return out


# -- GC303: lock acquisition order -------------------------------------

def _check_lock_order(graph: CallGraph, mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    # per class AND module level: ordered acquisition edges
    scopes: Dict[str, List[FunctionInfo]] = {}
    for fi in mod.functions.values():
        scopes.setdefault(fi.class_name or "", []).append(fi)
    for class_name, fns in scopes.items():
        lock_attrs = _lock_attrs(mod, class_name) if class_name else set()
        edges: Dict[Tuple[str, str], ast.AST] = {}

        def collect(node: ast.AST, held: List[str], fi: FunctionInfo):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) and \
                        child is not fi.node:
                    continue
                if isinstance(child, ast.With):
                    got = [_lockish(i.context_expr, lock_attrs)
                           for i in child.items]
                    got = [g for g in got if g]
                    for g in got:
                        for h in held:
                            if h != g and (h, g) not in edges:
                                edges[(h, g)] = child
                    collect(child, held + got, fi)
                else:
                    collect(child, held, fi)

        for fi in fns:
            collect(fi.node, [], fi)
        # cycle detection over the little digraph
        adj: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
        for (a, b), site in sorted(edges.items(),
                                   key=lambda kv: kv[1].lineno):
            # is there a path b -> a?
            seen, work = set(), [b]
            found = False
            while work:
                n = work.pop()
                if n == a:
                    found = True
                    break
                if n in seen:
                    continue
                seen.add(n)
                work.extend(adj.get(n, ()))
            if found:
                out.append(Finding(
                    "GC303", mod.relpath, site.lineno, site.col_offset,
                    class_name,
                    f"lock order {a} -> {b} here, but the opposite "
                    "order exists elsewhere in this scope — a latent "
                    "deadlock; pick one global order"))
    return out
