"""graftcheck — repo-native static analysis (docs/STATIC_ANALYSIS.md).

AST-based (stdlib ``ast``, zero dependencies) checks for the invariants
the repo's hard gates rest on: JIT purity inside the inferred traced
set, determinism of step/replay/export paths, thread-safety discipline
at the 20+ spawn sites, and the span-taxonomy / metric-naming /
nothing-stranded contracts.

Run it::

    python -m deeplearning4j_tpu.analysis            # whole package
    python -m deeplearning4j_tpu check               # same, via the CLI
    python scripts/graftcheck.py --format=json       # machine output

``tests/test_static_analysis.py`` runs the analyzer over the package as
a tier-1 test — any unsuppressed finding fails CI, so every future PR
passes the analyzer by construction.
"""

from .callgraph import CallGraph, load_package
from .findings import Finding, Rule, RULES
from .runner import (AnalysisResult, run_analysis, update_baseline,
                     default_baseline_path, default_taxonomy_path)

__all__ = [
    "CallGraph", "load_package", "Finding", "Rule", "RULES",
    "AnalysisResult", "run_analysis", "update_baseline",
    "default_baseline_path", "default_taxonomy_path", "main",
]


def main(argv=None) -> int:
    """CLI entry shared by ``python -m deeplearning4j_tpu.analysis``,
    the ``check`` CLI subcommand, and ``scripts/graftcheck.py``."""
    import argparse
    import json as _json
    import sys

    p = argparse.ArgumentParser(
        prog="graftcheck",
        description="repo-native static analysis "
                    "(docs/STATIC_ANALYSIS.md)")
    p.add_argument("paths", nargs="*",
                   help="specific .py files (default: the whole "
                   "deeplearning4j_tpu package)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default="<default>",
                   help="baseline json (default: analysis/baseline.json; "
                   "'none' disables)")
    p.add_argument("--baseline-update", action="store_true",
                   help="append current unsuppressed findings to the "
                   "baseline (REQUIRES --justification)")
    p.add_argument("--justification", default="",
                   help="why the baselined findings are accepted")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also list suppressed findings (text mode)")
    args = p.parse_args(argv)

    baseline = None if args.baseline == "none" else args.baseline
    result = run_analysis(paths=args.paths or None, baseline_path=baseline)

    if args.baseline_update:
        try:
            bp = default_baseline_path() if baseline == "<default>" \
                else baseline
            added = update_baseline(result, bp, args.justification)
        except ValueError as e:
            print(f"graftcheck: error: {e}", file=sys.stderr)
            return 2
        print(f"graftcheck: baselined {added} finding(s) into {bp}")
        return 0

    if args.format == "json":
        print(_json.dumps(result.to_dict(), indent=1))
    else:
        for f in result.findings:
            print(f.format())
        if args.show_suppressed:
            for f, how in result.suppressed:
                print(f"[suppressed by {how}] {f.format()}")
        print(f"graftcheck: {len(result.findings)} finding(s), "
              f"{len(result.suppressed)} suppressed, "
              f"{result.n_files} file(s), {len(RULES)} rules")
    return 0 if result.ok else 1
