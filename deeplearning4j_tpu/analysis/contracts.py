"""GC4xx — repo contract rules.

These encode cross-artifact invariants the soaks only catch indirectly:

- GC401: every ``span()``/``instant()``/``complete_at()`` name must
  appear in the docs/OBSERVABILITY.md taxonomy table (wildcard rows
  like ``launcher/*`` cover f-string names).  The golden test in
  tests/test_static_analysis.py checks the reverse direction too, so
  the docs cannot rot.
- GC402: metric names are snake_case; counters created on the GLOBAL
  registry (``get_registry()``) end in ``_total``; histograms carry a
  unit suffix.  (Per-engine serving counters keep the PR-4 legacy
  snapshot keys — those registries are private, so the ``_total`` rule
  does not apply to them.)
- GC403: in a function that resolves futures, an exception path that
  neither resolves nor re-raises cannot uphold the serving "nothing
  stranded" invariant.  The race-guard idiom (``try: fut.set_result``
  / ``except InvalidStateError: pass``) is recognized and exempt.
- GC404: silent exception swallows (``except Exception: pass`` or
  broader) must narrow the type, record telemetry, or carry a
  justified pragma.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from typing import List, Optional, Sequence, Set

from .callgraph import CallGraph, FunctionInfo, dotted
from .findings import Finding

_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")
_HIST_SUFFIXES = ("_ms", "_seconds", "_s", "_bytes", "_rows", "_ratio")
_RESOLUTION_LEAVES = {"set_result", "set_exception", "cancel"}
_RESOLUTION_HELPERS = re.compile(r"fail|resolve|_set_safe")


# -- taxonomy ----------------------------------------------------------

def parse_taxonomy(markdown: str) -> Set[str]:
    """Span/instant names from the `## Taxonomy table` section of
    docs/OBSERVABILITY.md: first column of each table row."""
    names: Set[str] = set()
    in_section = False
    for line in markdown.splitlines():
        if line.startswith("## "):
            in_section = line.strip().lower() == "## taxonomy table"
            continue
        if not in_section or not line.lstrip().startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if not cells or not cells[0] or set(cells[0]) <= {"-", ":", " "}:
            continue
        name = cells[0].strip("`")
        if name.lower() in ("name", "span / instant"):
            continue
        names.add(name)
    return names


def _span_name_matches(name: str, taxonomy: Set[str]) -> bool:
    if name in taxonomy:
        return True
    probe = name.replace("*", "x")  # f-string holes become a literal
    return any("*" in t and fnmatch.fnmatch(probe, t) for t in taxonomy)


def _literal_span_names(arg: ast.AST) -> Optional[List[str]]:
    """All statically-known names an emission site can produce: handles
    str literals, f-strings (holes become '*'), and conditional
    expressions whose branches are themselves literal."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return [arg.value]
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for v in arg.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        return ["".join(parts)]
    if isinstance(arg, ast.IfExp):
        a = _literal_span_names(arg.body)
        b = _literal_span_names(arg.orelse)
        if a is not None and b is not None:
            return a + b
    return None


def collect_span_emissions(graph: CallGraph):
    """(module, call, name_pattern, literal?) for every obs-trace
    span/instant/complete_at emission in the package."""
    out = []
    for mod in graph.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            if fname is None:
                continue
            leaf = fname.split(".")[-1]
            if leaf not in ("span", "instant", "complete_at"):
                continue
            norm = mod.normalize(fname)
            if "obs" not in norm and "trace" not in norm.split(".")[0]:
                continue
            if not node.args:
                continue
            names = _literal_span_names(node.args[0])
            out.append((mod, node, names))
    return out


def check_span_taxonomy(graph: CallGraph,
                        taxonomy: Optional[Set[str]]) -> List[Finding]:
    if taxonomy is None:
        return []
    out: List[Finding] = []
    for mod, node, names in collect_span_emissions(graph):
        symbol = _enclosing_symbol(graph, mod, node)
        if names is None:
            out.append(Finding(
                "GC401", mod.relpath, node.lineno, node.col_offset,
                symbol,
                "span/instant name is not a (f-)string literal — the "
                "taxonomy cannot be checked; use a literal or an "
                "f-string matching a wildcard taxonomy row"))
            continue
        for name in names:
            if not _span_name_matches(name, taxonomy):
                out.append(Finding(
                    "GC401", mod.relpath, node.lineno, node.col_offset,
                    symbol,
                    f"span/instant name '{name}' is not in the "
                    "docs/OBSERVABILITY.md taxonomy table — add a row "
                    "(or a wildcard row) so pod timelines stay "
                    "documented"))
    return out


def _enclosing_symbol(graph: CallGraph, mod, node: ast.AST) -> str:
    best = ""
    best_span = None
    for fi in mod.functions.values():
        n = fi.node
        end = getattr(n, "end_lineno", n.lineno)
        if n.lineno <= node.lineno <= end:
            span = end - n.lineno
            if best_span is None or span < best_span:
                best, best_span = fi.qual, span
    return best


# -- metric naming -----------------------------------------------------

def check_metric_names(graph: CallGraph) -> List[Finding]:
    out: List[Finding] = []
    for mod in graph.modules.values():
        # names assigned from get_registry() per function scope
        for fi in list(mod.functions.values()) + [None]:
            tree = fi.node if fi is not None else mod.tree
            symbol = fi.qual if fi is not None else ""
            global_regs = {"get_registry"}
            aliases: Set[str] = set()
            for n in ast.walk(tree):
                if fi is None and isinstance(
                        n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(n, ast.Assign) and \
                        isinstance(n.value, ast.Call):
                    vn = dotted(n.value.func)
                    if vn and vn.split(".")[-1] == "get_registry":
                        for t in n.targets:
                            if isinstance(t, ast.Name):
                                aliases.add(t.id)
            for n in ast.walk(tree):
                if not isinstance(n, ast.Call) or \
                        not isinstance(n.func, ast.Attribute):
                    continue
                kind = n.func.attr
                if kind not in ("counter", "gauge", "histogram"):
                    continue
                recv = n.func.value
                recv_name = dotted(recv)
                is_registry = False
                is_global = False
                if isinstance(recv, ast.Call):
                    rn = dotted(recv.func)
                    if rn and rn.split(".")[-1] == "get_registry":
                        is_registry = is_global = True
                elif recv_name is not None:
                    leaf = recv_name.split(".")[-1]
                    if "registry" in leaf or leaf == "reg":
                        is_registry = True
                    if recv_name in aliases:
                        is_registry = is_global = True
                if not is_registry:
                    continue
                if not n.args or not isinstance(n.args[0], ast.Constant) \
                        or not isinstance(n.args[0].value, str):
                    continue   # dynamic names are adopted elsewhere
                name = n.args[0].value
                if not _SNAKE.match(name):
                    out.append(Finding(
                        "GC402", mod.relpath, n.lineno, n.col_offset,
                        symbol,
                        f"metric name '{name}' is not snake_case"))
                elif kind == "counter" and is_global and \
                        not name.endswith("_total"):
                    out.append(Finding(
                        "GC402", mod.relpath, n.lineno, n.col_offset,
                        symbol,
                        f"global-registry counter '{name}' must end in "
                        "'_total' (docs/OBSERVABILITY.md schema)"))
                elif kind == "histogram" and \
                        not name.endswith(_HIST_SUFFIXES):
                    out.append(Finding(
                        "GC402", mod.relpath, n.lineno, n.col_offset,
                        symbol,
                        f"histogram '{name}' has no unit suffix "
                        f"({'/'.join(_HIST_SUFFIXES)})"))
    return out


# -- futures -----------------------------------------------------------

def _is_resolution_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted(node.func)
    if name is None:
        return False
    leaf = name.split(".")[-1]
    return leaf in _RESOLUTION_LEAVES or \
        bool(_RESOLUTION_HELPERS.search(leaf))


def _contains_resolution(node: ast.AST) -> bool:
    return any(_is_resolution_call(n) for n in ast.walk(node))


def _contains_raise(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(node))


def _own_nodes(fi: FunctionInfo) -> List[ast.AST]:
    """fi's body without nested defs (they are their own FunctionInfos)."""
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(fi.node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def check_future_paths(graph: CallGraph) -> List[Finding]:
    out: List[Finding] = []
    for fi in graph.functions.values():
        body_nodes = _own_nodes(fi)
        if not any(_is_resolution_call(n) for n in body_nodes):
            continue
        for n in body_nodes:
            if not isinstance(n, ast.Try):
                continue
            finally_resolves = any(_contains_resolution(s)
                                   for s in n.finalbody)
            # race-guard idiom: the try body IS the resolution
            body_is_resolution = all(
                (isinstance(s, ast.Expr) and _is_resolution_call(s.value))
                or isinstance(s, (ast.Return, ast.Pass))
                or (isinstance(s, ast.Assign)
                    and _is_resolution_call(s.value))
                for s in n.body) and any(
                _contains_resolution(s) for s in n.body)
            if finally_resolves or body_is_resolution:
                continue
            if not any(_contains_resolution(s) for s in n.body):
                continue   # this try doesn't dispatch on futures
            for h in n.handlers:
                if _contains_resolution(h) or _contains_raise(h):
                    continue
                out.append(Finding(
                    "GC403", fi.module.relpath, h.lineno, h.col_offset,
                    fi.qual,
                    "this except path neither resolves the in-flight "
                    "futures nor re-raises — an exception here can "
                    "strand them (serving 'nothing stranded' "
                    "invariant)"))
    return out


# -- silent swallow ----------------------------------------------------

_BROAD = {"Exception", "BaseException"}


def _handler_types(h: ast.ExceptHandler) -> List[str]:
    if h.type is None:
        return ["<bare>"]
    nodes = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    out = []
    for n in nodes:
        name = dotted(n)
        out.append(name.split(".")[-1] if name else "?")
    return out


def check_silent_swallow(graph: CallGraph) -> List[Finding]:
    out: List[Finding] = []
    for mod in graph.modules.values():
        for n in ast.walk(mod.tree):
            if not isinstance(n, ast.ExceptHandler):
                continue
            types = _handler_types(n)
            if not (set(types) & _BROAD) and types != ["<bare>"]:
                continue
            body_silent = all(
                isinstance(s, (ast.Pass, ast.Continue, ast.Break))
                or (isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant))
                for s in n.body)
            if not body_silent:
                continue
            shown = "bare except" if types == ["<bare>"] \
                else f"except {'/'.join(types)}"
            out.append(Finding(
                "GC404", mod.relpath, n.lineno, n.col_offset,
                _enclosing_symbol(graph, mod, n),
                f"{shown}: pass — the failure is dropped on the "
                "floor; narrow the exception type, record an obs "
                "instant()/counter, or pragma with a justification"))
    return out


def run_contracts(graph: CallGraph,
                  taxonomy: Optional[Set[str]]) -> List[Finding]:
    out = []
    out.extend(check_span_taxonomy(graph, taxonomy))
    out.extend(check_metric_names(graph))
    out.extend(check_future_paths(graph))
    out.extend(check_silent_swallow(graph))
    return out
