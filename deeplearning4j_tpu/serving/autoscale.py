"""Load-driven replica autoscaling: the control law, nothing else.

``ReplicaAutoscaler`` is a pure controller — it observes load signals
(queue depth, in-flight count, shed-counter delta) and answers
"+1 / 0 / -1 replicas".  It owns no threads and touches no engine state,
so it unit-tests with a fake clock and the Engine/DecodeEngine supervisor
loops can tick it from their existing cadence.  Actuation (replica
birth/retire) lives in the engines, which reuse the PR-7 respawn
machinery; it is only viable because a new replica now warms from the
persistent compile cache / warmup bundle instead of paying a fresh XLA
compile (see ``serving/warmcache.py``).

Control law (classic hysteresis + cooldown):

* load = (queue_depth + inflight) / replicas; a shed in the last tick
  counts as high load regardless (shedding means admission is already
  failing users — queue depth alone can look calm under ``shed`` mode).
* ``up_ticks`` consecutive high ticks → +1 (bounded by ``max_replicas``);
  ``down_ticks`` consecutive low ticks → -1 (bounded by
  ``min_replicas``).  Mid-band ticks reset both streaks.
* After any action, ``cooldown_s`` of enforced silence lets the new
  replica count actually absorb/free load before the next decision —
  without it a burst triggers a scale-up stampede and the tail of the
  burst immediately flaps back down.
"""
from __future__ import annotations

import time
from typing import Callable, Optional


class ReplicaAutoscaler:
    """Hysteresis + cooldown controller over serving load signals.

    The clock is injectable (GC201): tests drive cooldown with a fake
    monotonic clock instead of sleeping.
    """

    def __init__(
        self,
        min_replicas: int = 1,
        max_replicas: int = 4,
        up_load: float = 2.0,
        down_load: float = 0.25,
        up_ticks: int = 2,
        down_ticks: int = 5,
        cooldown_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if down_load >= up_load:
            raise ValueError("down_load must be < up_load (hysteresis band)")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.up_load = up_load
        self.down_load = down_load
        self.up_ticks = max(1, int(up_ticks))
        self.down_ticks = max(1, int(down_ticks))
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._hi = 0
        self._lo = 0
        self._last_action_t: Optional[float] = None

    def load(self, queue_depth: int, inflight: int, replicas: int) -> float:
        return (queue_depth + inflight) / max(1, replicas)

    def observe(
        self,
        queue_depth: int,
        inflight: int,
        replicas: int,
        shed_delta: int = 0,
    ) -> int:
        """One control tick.  Returns +1 (add a replica), -1 (retire one),
        or 0 (hold)."""
        now = self._clock()
        load = self.load(queue_depth, inflight, replicas)
        if shed_delta > 0 or load >= self.up_load:
            self._hi += 1
            self._lo = 0
        elif load <= self.down_load:
            self._lo += 1
            self._hi = 0
        else:
            self._hi = 0
            self._lo = 0
        if (
            self._last_action_t is not None
            and now - self._last_action_t < self.cooldown_s
        ):
            return 0
        if self._hi >= self.up_ticks and replicas < self.max_replicas:
            self._hi = self._lo = 0
            self._last_action_t = now
            return 1
        if self._lo >= self.down_ticks and replicas > self.min_replicas:
            self._hi = self._lo = 0
            self._last_action_t = now
            return -1
        return 0

    def reset(self) -> None:
        """Forget streaks and cooldown (e.g. after a model swap)."""
        self._hi = self._lo = 0
        self._last_action_t = None
