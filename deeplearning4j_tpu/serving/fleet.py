"""Fleet-level serving router (L5): cross-host dispatch, failover, and
rolling swap under live traffic.

One process serving one box was finished in PRs 4/7/12 (engine replicas,
crash/hang supervision, decode).  This module composes those per-host
engines into a FLEET — the availability shape of the TPU serving papers
(PAPERS.md: fleet-availability math of the TPU-supercomputer line): the
system keeps answering, within SLO, while hosts die, get preempted, or
straggle.

  FleetRouter   duck-types a serving engine (``output``/``output_async``/
                ``generate_async``/``current_tag``/``metrics_snapshot``/
                ``health_snapshot``), so ``UIServer.attach_engine(router)``
                puts a whole fleet behind one ``POST /predict``.
  FleetHost     one host: an ``Engine`` and/or ``DecodeEngine`` plus the
                router's view of its state (up/draining/down), live load,
                and consecutive-failure count.
  HttpHost      the same duck type over a remote UIServer
                (``serve --fleet host:port,...``): POST /predict on a
                small worker pool, /metrics + /healthz proxied.

Routing: least-loaded by router-tracked in-flight + the host's own
/metrics queue-depth snapshot (polled on a cadence — the PR-8 signal),
EXCEPT decode requests carrying a ``session`` key, which ride a
consistent-hash ring so a KV-cache never migrates while its host lives.

Failover (the PR-7 retry semantics, one level up): a host fault — replica
crash surfacing through the engine, an admission shed, a per-request
timeout, a dead heartbeat — retries the request on a surviving host,
bounded by ``max_retries`` and the request deadline, preferring hosts not
yet tried.  Delivery is at-most-once by construction: the caller future
is resolved first-writer-wins (``_set_safe``), so a straggler host
completing AFTER its request was re-routed becomes a counted
``late_discards``, never a double delivery.  Every future always
resolves — the engine invariant holds at fleet level.

Host death is detected three ways: the engine's own futures failing
(in-process kill → ``shutdown()`` resolves everything, the router
retries), per-request timeouts from the watchdog thread (the only
signal an unreachable HTTP host gives), and the PR-6 heartbeat ledger
(``membership=``): a process that stops beating is marked down, one
marked leaving (PR-9 SIGTERM notice) is drained first — stop dispatch,
let in-flight finish, peers absorb the load.

Rolling swap (``rolling_swap`` / ``promote``): a registry promote walks
the fleet host-by-host — drain one host, ``swap_model`` it, undrain,
next — so peers absorb each host's traffic and the fleet never has zero
capacity.  A mid-swap host kill marks that host down and rolls the
already-swapped survivors back to the old version: the fleet is never
left version-mixed.  ``promote`` moves the registry alias only after
every host swapped.

Clocks are injectable (``clock=``, monotonic-like) per the repo-wide
GC201 contract; the watchdog can be driven synchronously in tests via
``poke(now=...)``.  See docs/SERVING.md "Fleet serving".
"""

from __future__ import annotations

import bisect
import hashlib
import json
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import trace as obs_trace
from .batcher import DeadlineExceededError, OverloadedError
from .decode import PrefillHandoff
from .engine import (ModelNotLoadedError, PoisonInputError,
                     ServingUnavailableError, _fail_safe, _set_safe)
from .metrics import FleetMetrics
from .tenancy import TenantOverloadedError


class FleetTimeoutError(RuntimeError):
    """A dispatched attempt exceeded the per-request host timeout; the
    router re-routed it (or failed it typed if retries were spent)."""


# deterministic request errors: the same input fails the same way on any
# host, so burning a retry (and a peer's capacity) on them is waste.
# TenantOverloadedError is logical back-pressure on the TENANT's own
# quota, not host capacity — retrying it on a peer would let a bursting
# tenant launder its shed traffic through the retry budget.
_NON_RETRYABLE = (PoisonInputError, DeadlineExceededError, ValueError,
                  TypeError, KeyError, TenantOverloadedError)


def _hash64(s: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(s.encode(), digest_size=8).digest(), "big")


def _tag_of(engine) -> str:
    try:
        return str(engine.current_tag)
    except Exception:
        return ""


class FleetHost:
    """One serving host and the router's view of it.  ``engine`` handles
    predict traffic, ``decode`` generation; a host may carry either or
    both.  ``process_id`` links the host to a heartbeat-ledger row so
    the router can watch its liveness."""

    def __init__(self, host_id: str, engine=None, decode=None,
                 process_id: Optional[int] = None):
        if engine is None and decode is None:
            raise ValueError("FleetHost needs an engine and/or a decode "
                             "engine")
        self.host_id = str(host_id)
        self.engine = engine
        self.decode = decode
        self.process_id = process_id
        self.state = "up"              # up | draining | down
        self.planned = False           # down was a planned leave
        self.inflight = 0              # router-dispatched, not yet resolved
        self.failures = 0              # consecutive host faults
        self.last_error: Optional[str] = None
        self.cached_queue_depth = 0    # from the host's /metrics snapshot
        self.depth_read_at: Optional[float] = None
        self.cached_free_slots: Optional[int] = None   # decode-pool gauges,
        self.cached_free_pages: Optional[int] = None   # same poll cadence
        self.cached_pps = 0            # pages a full-length request needs

    def supports(self, kind: str) -> bool:
        return (self.decode if kind == "decode" else self.engine) is not None

    def places(self, model: Optional[str], kind: str = "predict") -> bool:
        """True when the host's engine for ``kind`` currently serves
        ``model`` (None = the default model, always placed).  Engines
        without a ``has_model`` (HTTP hosts on an old build, test
        fakes) place everything — routing degrades to pre-placement
        behavior instead of blackholing."""
        if model is None:
            return True
        eng = self.engine_for(kind)
        has = getattr(eng, "has_model", None)
        if has is None:
            return True
        try:
            return bool(has(model))
        except Exception:
            return False

    def placed_models(self) -> Dict[str, str]:
        """Union of placed model names -> tag over both engines."""
        out: Dict[str, str] = {}
        for eng in (self.engine, self.decode):
            pm = getattr(eng, "placed_models", None)
            if pm is None:
                continue
            try:
                out.update(pm())
            except (OSError, ValueError, KeyError, RuntimeError):
                # a dead/remote engine's view is just absent; the
                # router's health machinery owns reporting that host
                pass
        return out

    def engine_for(self, kind: str):
        return self.decode if kind == "decode" else self.engine

    def decode_role(self) -> str:
        """``unified`` | ``prefill`` | ``decode`` — engines predating
        disaggregation default to unified."""
        if self.decode is None:
            return "unified"
        return getattr(self.decode, "role", "unified")

    def read_decode_pressure(self) -> None:
        """Refresh the decode engine's free-capacity gauges (free slots
        + free KV pages) from its own /metrics snapshot."""
        if self.decode is None:
            return
        try:
            snap = self.decode.metrics_snapshot()
        except Exception as exc:
            self.last_error = f"{type(exc).__name__}: {exc}"
            return
        fs, fp = snap.get("free_slots"), snap.get("free_pages")
        self.cached_free_slots = int(fs) if fs is not None else None
        self.cached_free_pages = int(fp) if fp is not None else None
        self.cached_pps = int(snap.get("pages_per_slot", 0) or 0)

    def decode_pressure(self) -> int:
        """Score penalty from the host's own pool gauges: +1 when no
        decode slot is free (the next admit waits a step), +1 when the
        free list cannot hold one more full-length request.  Hosts that
        never reported gauges (HTTP hosts on an old build) score 0 —
        the pre-disaggregation ordering is unchanged."""
        p = 0
        if self.cached_free_slots is not None and self.cached_free_slots <= 0:
            p += 1
        if (self.cached_free_pages is not None and self.cached_pps
                and self.cached_free_pages < self.cached_pps):
            p += 1
        return p

    def read_queue_depth(self) -> int:
        """The host's own occupancy signal: ``queue_depth`` out of its
        /metrics snapshot (both engine kinds export it)."""
        depth = 0
        for eng in (self.engine, self.decode):
            if eng is None:
                continue
            try:
                depth += int(eng.metrics_snapshot().get("queue_depth", 0))
            except Exception as exc:  # unreachable host: stale depth kept
                self.last_error = f"{type(exc).__name__}: {exc}"
        return depth


class _FleetRequest:
    __slots__ = ("kind", "payload", "session", "slo_ms", "deadline",
                 "future", "tried", "retries", "t_submit", "model",
                 "tenant")

    def __init__(self, kind, payload, session, slo_ms, deadline, future,
                 t_submit, model=None, tenant=None):
        self.kind = kind
        self.payload = payload
        self.session = session
        self.slo_ms = slo_ms
        self.deadline = deadline
        self.future = future
        self.tried: set = set()
        self.retries = 0
        self.t_submit = t_submit
        self.model = model
        self.tenant = tenant


class _Attempt:
    __slots__ = ("aid", "spec", "host", "t_dispatch", "timeout_at",
                 "settled")

    def __init__(self, aid, spec, host, t_dispatch, timeout_at):
        self.aid = aid
        self.spec = spec
        self.host = host
        self.t_dispatch = t_dispatch
        self.timeout_at = timeout_at
        self.settled = False


class FleetRouter:
    """Cross-host router over ``FleetHost``s.  See the module docstring
    for the routing/failover/swap semantics; docs/SERVING.md for the
    operator view."""

    def __init__(self, hosts: Sequence[FleetHost] = (), *,
                 max_retries: int = 1,
                 request_timeout_s: Optional[float] = None,
                 breaker_threshold: int = 3,
                 membership=None,
                 metrics: Optional[FleetMetrics] = None,
                 metrics_refresh_s: float = 0.05,
                 membership_refresh_s: float = 0.5,
                 virtual_nodes: int = 64,
                 watchdog_interval_s: float = 0.01,
                 start_watchdog: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        self.max_retries = int(max_retries)
        self.request_timeout_s = request_timeout_s
        self.breaker_threshold = int(breaker_threshold)
        self.metrics_refresh_s = float(metrics_refresh_s)
        self.membership_refresh_s = float(membership_refresh_s)
        self.virtual_nodes = int(virtual_nodes)
        self.watchdog_interval_s = float(watchdog_interval_s)
        self.clock = clock
        self.metrics = metrics or FleetMetrics()
        self._membership = membership
        self._lock = threading.Lock()
        self._idle_cv = threading.Condition(self._lock)
        self._hosts: Dict[str, FleetHost] = {}
        self._ring: List[Tuple[int, str]] = []
        self._outstanding: Dict[int, _Attempt] = {}
        self._aid = 0
        self._rr = 0
        self._shutdown = False
        self._draining = False
        self._last_depth_poll: Optional[float] = None
        self._last_member_poll: Optional[float] = None
        # per-model submit counts since the last drain ("" = default
        # model) — the placement controller's traffic signal
        self._model_traffic: Dict[str, int] = {}
        # placement hook: called (model, kind) when no up host places a
        # requested model — the controller demand-loads, then dispatch
        # re-picks once (serving/placement.py)
        self._on_model_miss: Optional[Callable[[str, str], bool]] = None
        self._stop = threading.Event()
        self._watchdog_thread: Optional[threading.Thread] = None
        for h in hosts:
            self.add_host(h)
        if start_watchdog:
            self._watchdog_thread = threading.Thread(
                target=self._watchdog_loop, name="fleet-watchdog",
                daemon=True)
            self._watchdog_thread.start()

    # -- membership of the fleet itself ---------------------------------

    def add_host(self, host, engine=None, decode=None,
                 process_id: Optional[int] = None) -> FleetHost:
        if not isinstance(host, FleetHost):
            host = FleetHost(host, engine=engine, decode=decode,
                             process_id=process_id)
        with self._lock:
            if host.host_id in self._hosts:
                raise ValueError(f"duplicate host_id {host.host_id!r}")
            self._hosts[host.host_id] = host
            self._rebuild_ring_locked()
            self._gauge_hosts_locked()
        return host

    def remove_host(self, host_id: str,
                    drain_timeout_s: Optional[float] = 5.0) -> None:
        self.drain_host(host_id, timeout_s=drain_timeout_s)
        with self._lock:
            self._hosts.pop(host_id, None)
            self._rebuild_ring_locked()
            self._gauge_hosts_locked()

    def hosts(self) -> Dict[str, str]:
        with self._lock:
            return {hid: h.state for hid, h in self._hosts.items()}

    def host(self, host_id: str) -> Optional[FleetHost]:
        """The live FleetHost record (None if unknown) — the placement
        controller's actuation handle."""
        with self._lock:
            return self._hosts.get(host_id)

    def mark_host_down(self, host_id: str, reason: str = "manual",
                       planned: bool = False) -> None:
        with self._lock:
            host = self._hosts.get(host_id)
            if host is None or host.state == "down":
                return
            host.state = "down"
            host.planned = planned
            self._gauge_hosts_locked()
            self._idle_cv.notify_all()   # unblock a drain waiting on it
        self.metrics.inc("host_down")
        obs_trace.instant("fleet/host_down", cat="fleet", host=host_id,
                          reason=reason, planned=planned)

    def mark_host_up(self, host_id: str) -> None:
        with self._lock:
            host = self._hosts.get(host_id)
            if host is None or host.state == "up":
                return
            host.state = "up"
            host.planned = False
            host.failures = 0
            host.last_error = None
            self._gauge_hosts_locked()
        self.metrics.inc("host_up")
        obs_trace.instant("fleet/host_up", cat="fleet", host=host_id)

    def _gauge_hosts_locked(self) -> None:
        self.metrics.hosts_total.set(len(self._hosts))
        self.metrics.hosts_up.set(
            sum(1 for h in self._hosts.values() if h.state == "up"))

    def _rebuild_ring_locked(self) -> None:
        ring = []
        for hid in self._hosts:
            for i in range(self.virtual_nodes):
                ring.append((_hash64(f"{hid}#{i}"), hid))
        ring.sort()
        self._ring = ring

    # -- the engine duck type -------------------------------------------

    def output(self, x, slo_ms: Optional[float] = None,
               model: Optional[str] = None,
               tenant: Optional[str] = None) -> np.ndarray:
        return self.output_async(x, slo_ms=slo_ms, model=model,
                                 tenant=tenant).result()

    def output_async(self, x, slo_ms: Optional[float] = None,
                     session=None, model: Optional[str] = None,
                     tenant: Optional[str] = None) -> Future:
        return self._submit("predict", np.asarray(x), session, slo_ms,
                            model=model, tenant=tenant)

    def generate_async(self, prompt_ids, *, session=None,
                       slo_ms: Optional[float] = None,
                       model: Optional[str] = None,
                       tenant: Optional[str] = None, **kw) -> Future:
        payload = dict(kw)
        payload["prompt_ids"] = prompt_ids
        return self._submit("decode", payload, session, slo_ms,
                            model=model, tenant=tenant)

    def generate(self, prompt_ids, **kw):
        return self.generate_async(prompt_ids, **kw).result()

    @property
    def current_tag(self) -> str:
        with self._lock:
            for h in self._hosts.values():
                if h.state != "down":
                    return _tag_of(h.engine or h.decode)
        return ""

    def tags(self, kind: str = "predict") -> Dict[str, str]:
        """Current model tag per non-down host — the fleet-consistency
        view (``current_tag`` reads only the FIRST up host, which lies
        mid-roll or after a canary host self-swapped ahead of the
        fleet).  A promotion controller re-rolls exactly when some up
        host's tag differs from the target."""
        with self._lock:
            hosts = [h for h in self._hosts.values()
                     if h.state != "down" and h.supports(kind)]
        return {h.host_id: _tag_of(h.engine_for(kind)) for h in hosts}

    def health_snapshot(self) -> dict:
        with self._lock:
            hosts = list(self._hosts.values())
        per: Dict[str, dict] = {}
        dispatchable = 0
        all_ok = bool(hosts)
        for h in hosts:
            if h.state == "down":
                per[h.host_id] = {"state": "down", "planned": h.planned,
                                  "last_error": h.last_error}
                all_ok = False
                continue
            entry: Dict[str, Any] = {"state": h.state,
                                     "inflight": h.inflight}
            ready = False
            for kind, eng in (("predict", h.engine), ("decode", h.decode)):
                if eng is None:
                    continue
                try:
                    snap = eng.health_snapshot()
                except Exception as exc:
                    snap = {"status": "unready", "ready": False,
                            "error": f"{type(exc).__name__}: {exc}"}
                entry[kind] = snap
                ready = ready or bool(snap.get("ready"))
                if snap.get("status") != "ok":
                    all_ok = False
            if h.state != "up":
                all_ok = False
            if ready and h.state == "up":
                dispatchable += 1
            per[h.host_id] = entry
        status = ("ok" if all_ok and dispatchable
                  else "degraded" if dispatchable else "unready")
        return {"status": status, "ready": dispatchable > 0,
                "kind": "fleet", "hosts": per,
                "model": self.current_tag}

    def metrics_snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        with self._lock:
            snap["hosts"] = {
                hid: {"state": h.state, "inflight": h.inflight,
                      "queue_depth": h.cached_queue_depth,
                      "failures": h.failures,
                      "role": h.decode_role(),
                      "free_slots": h.cached_free_slots,
                      "free_pages": h.cached_free_pages}
                for hid, h in self._hosts.items()}
            snap["queue_depth"] = sum(
                h.inflight for h in self._hosts.values())
            snap["model_traffic"] = dict(self._model_traffic)
        snap["model"] = self.current_tag
        snap["models"] = self.model_map()
        return snap

    # -- dispatch --------------------------------------------------------

    def _submit(self, kind, payload, session, slo_ms,
                model=None, tenant=None) -> Future:
        fut: Future = Future()
        now = self.clock()
        deadline = (now + slo_ms / 1000.0) if slo_ms else None
        spec = _FleetRequest(kind, payload, session, slo_ms, deadline, fut,
                             now, model=model, tenant=tenant)
        self.metrics.inc("requests", tenant=tenant)
        with self._lock:
            key = model if model is not None else ""
            self._model_traffic[key] = self._model_traffic.get(key, 0) + 1
        if self._shutdown:
            _fail_safe(fut, ServingUnavailableError(
                "fleet router is shut down"))
            return fut
        if self._draining:
            self.metrics.inc("shed", tenant=tenant)
            _fail_safe(fut, OverloadedError(
                "admission stopped: fleet is draining (preemption notice)"))
            return fut
        self._dispatch(spec)
        return fut

    def _pick_host_locked(self, spec,
                          sink: bool = False) -> Optional[FleetHost]:
        # disaggregated decode routes in two stages: a raw prompt goes
        # to a prefill/unified host (sink=False — decode-role hosts
        # cannot prefill), a PrefillHandoff to a decode-role sink
        cands = [h for h in self._hosts.values()
                 if h.state == "up" and h.supports(spec.kind)
                 and h.places(spec.model, spec.kind)
                 and (spec.kind != "decode"
                      or (h.decode_role() == "decode") == sink)]
        if not cands:
            return None
        if spec.session is not None:
            host = self._ring_lookup_locked(spec.session, spec.kind,
                                            spec.tried, sink,
                                            model=spec.model)
            if host is not None:
                self.metrics.inc("affinity_routed")
                return host
        fresh = [h for h in cands if h.host_id not in spec.tried] or cands
        score = {h.host_id: h.inflight + h.cached_queue_depth
                 + (h.decode_pressure() if spec.kind == "decode" else 0)
                 for h in fresh}
        best = min(score[h.host_id] for h in fresh)
        tied = [h for h in fresh if score[h.host_id] == best]
        self._rr += 1
        return tied[self._rr % len(tied)]

    def _ring_lookup_locked(self, key, kind, tried,
                            sink: bool = False,
                            model: Optional[str] = None
                            ) -> Optional[FleetHost]:
        if not self._ring:
            return None
        h = _hash64(str(key))
        idx = bisect.bisect_left(self._ring, (h, ""))
        n = len(self._ring)
        for allow_tried in (False, True):
            seen: set = set()
            for off in range(n):
                _, hid = self._ring[(idx + off) % n]
                if hid in seen:
                    continue
                seen.add(hid)
                host = self._hosts[hid]
                if (host.state == "up" and host.supports(kind)
                        and host.places(model, kind)
                        and (kind != "decode"
                             or (host.decode_role() == "decode") == sink)
                        and (allow_tried or hid not in tried)):
                    return host
        return None

    def set_model_miss_handler(
            self, handler: Optional[Callable[[str, str], bool]]) -> None:
        """Placement hook: ``handler(model, kind)`` runs (outside the
        router lock) when a request names a model no up host places.
        Return True to have dispatch re-pick once — the demand-reload
        path: eviction makes a cold model a routing miss, not an
        error."""
        with self._lock:
            self._on_model_miss = handler

    def model_traffic(self, reset: bool = False) -> Dict[str, int]:
        """Per-model submit counts since the last reset ("" = the
        default model) — the placement controller's demand signal."""
        with self._lock:
            out = dict(self._model_traffic)
            if reset:
                self._model_traffic = {}
            return out

    def model_map(self) -> Dict[str, Dict[str, str]]:
        """host_id -> {model name -> tag} over non-down hosts — the
        fleet's live placement view."""
        with self._lock:
            return {hid: h.placed_models()
                    for hid, h in self._hosts.items() if h.state != "down"}

    def _dispatch(self, spec) -> None:
        if self._shutdown:
            _fail_safe(spec.future, ServingUnavailableError(
                "fleet router is shut down"))
            return
        attempt = None
        for round_no in (0, 1):
            with self._lock:
                host = self._pick_host_locked(spec)
                if host is not None:
                    host.inflight += 1
                    self._aid += 1
                    timeout_at = (self.clock() + self.request_timeout_s
                                  if self.request_timeout_s else None)
                    attempt = _Attempt(self._aid, spec, host, self.clock(),
                                       timeout_at)
                    self._outstanding[attempt.aid] = attempt
                    break
                miss_cb = self._on_model_miss
            if (round_no == 0 and spec.model is not None
                    and miss_cb is not None):
                # no up host places this model: give the placement
                # controller one shot at a demand reload, then re-pick
                self.metrics.inc("model_misses")
                try:
                    if not miss_cb(spec.model, spec.kind):
                        break
                except Exception:
                    # a crashing miss handler degrades to the typed
                    # ModelNotLoadedError below, visibly
                    self.metrics.inc("model_miss_cb_errors")
                    break
            else:
                break
        if host is None:
            self.metrics.inc("shed", tenant=spec.tenant)
            if spec.model is not None:
                _fail_safe(spec.future, ModelNotLoadedError(
                    f"no up fleet host places model {spec.model!r} "
                    f"(kind={spec.kind!r})"))
            else:
                _fail_safe(spec.future, OverloadedError(
                    f"no dispatchable fleet host for kind={spec.kind!r}"))
            return
        self.metrics.inc("dispatched")
        try:
            eng = host.engine_for(spec.kind)
            kw = {}
            if spec.model is not None:
                kw["model"] = spec.model
            if spec.tenant is not None:
                kw["tenant"] = spec.tenant
            if spec.kind == "decode":
                try:
                    inner = eng.generate_async(slo_ms=spec.slo_ms, **kw,
                                               **spec.payload)
                except TypeError:
                    if not kw:
                        raise
                    # pre-tenancy engine (or a test fake): routing
                    # already honored placement; drop the tags
                    inner = eng.generate_async(slo_ms=spec.slo_ms,
                                               **spec.payload)
            else:
                try:
                    inner = eng.output_async(spec.payload,
                                             slo_ms=spec.slo_ms, **kw)
                except TypeError:
                    if not kw:
                        raise
                    inner = eng.output_async(spec.payload,
                                             slo_ms=spec.slo_ms)
        except BaseException as exc:
            # synchronous failure (admission shed, validation, shut-down
            # host): the attempt never reached the host's queue
            with self._lock:
                host.inflight = max(0, host.inflight - 1)
                attempt.settled = True
                self._outstanding.pop(attempt.aid, None)
                self._idle_cv.notify_all()
            self._handle_failure(spec, host, exc)
            return
        inner.add_done_callback(
            lambda f, a=attempt: self._on_inner_done(a, f))

    def _on_inner_done(self, attempt, inner: Future) -> None:
        try:
            host = attempt.host
            with self._lock:
                host.inflight = max(0, host.inflight - 1)
                won = not attempt.settled
                attempt.settled = True
                self._outstanding.pop(attempt.aid, None)
                self._idle_cv.notify_all()
            exc = inner.exception()
            if not won:
                # a timeout already re-routed this attempt — the late
                # result is discarded, never double-delivered
                if exc is None:
                    self.metrics.inc("late_discards")
                return
            if exc is None:
                result = inner.result()
                if (attempt.spec.kind == "decode"
                        and isinstance(result, PrefillHandoff)):
                    # stage 1 of a disaggregated generation: the
                    # prefill host handed back KV pages, not tokens
                    self._dispatch_decode_stage(attempt, result)
                else:
                    self._deliver(attempt, result)
            else:
                self._handle_failure(attempt.spec, host, exc)
        except BaseException as exc:
            _fail_safe(attempt.spec.future, exc)

    def _dispatch_decode_stage(self, attempt, handoff) -> None:
        """Stage 2 of a disaggregated generation: transfer the
        ``PrefillHandoff``'s packed KV pages to a ``role="decode"`` sink
        and chain its future to the caller's.  A failed (or absent) sink
        re-enters ``_handle_failure``, whose retry restarts from stage 1
        — seeded counter-based sampling makes the re-run bit-identical,
        so at-most-once delivery still holds via ``_set_safe``."""
        spec = attempt.spec
        t0 = self.clock()
        with self._lock:
            attempt.host.failures = 0      # stage 1 succeeded
            sink = self._pick_host_locked(spec, sink=True)
            if sink is not None:
                sink.inflight += 1
                self._aid += 1
                timeout_at = (self.clock() + self.request_timeout_s
                              if self.request_timeout_s else None)
                a2 = _Attempt(self._aid, spec, sink, self.clock(),
                              timeout_at)
                self._outstanding[a2.aid] = a2
        if sink is None:
            self.metrics.inc("shed")
            _fail_safe(spec.future, OverloadedError(
                "no decode-role sink host up for the prefill handoff"))
            return
        self.metrics.inc("dispatched")
        try:
            inner = sink.decode.continue_async(handoff, slo_ms=spec.slo_ms)
        except BaseException as exc:
            with self._lock:
                sink.inflight = max(0, sink.inflight - 1)
                a2.settled = True
                self._outstanding.pop(a2.aid, None)
                self._idle_cv.notify_all()
            self._handle_failure(spec, sink, exc)
            return
        self.metrics.inc("disagg_requests")
        self.metrics.inc("page_transfers")
        self.metrics.inc("transfer_bytes", len(handoff.pages))
        obs_trace.complete_at(
            "fleet/page_transfer", t0, self.clock(), cat="fleet",
            src=attempt.host.host_id, dst=sink.host_id,
            pages=int(handoff.n_pages), nbytes=len(handoff.pages))
        inner.add_done_callback(
            lambda f, a=a2: self._on_inner_done(a, f))

    def _deliver(self, attempt, result) -> None:
        spec, host = attempt.spec, attempt.host
        with self._lock:
            host.failures = 0
        if _set_safe(spec.future, result):
            done = self.clock()
            self.metrics.inc("delivered", tenant=spec.tenant)
            self.metrics.e2e.record((done - spec.t_submit) * 1000.0)
            obs_trace.complete_at("fleet/request", spec.t_submit, done,
                                  cat="fleet", host=host.host_id,
                                  kind=spec.kind, retries=spec.retries)
        else:
            self.metrics.inc("late_discards")

    def _handle_failure(self, spec, host, exc) -> None:
        try:
            retryable = not isinstance(exc, _NON_RETRYABLE)
            # an admission shed is back-pressure, not a sick host —
            # likewise a model the host merely doesn't place: route
            # around them but don't feed the circuit breaker
            if retryable and not isinstance(
                    exc, (OverloadedError, ModelNotLoadedError)):
                self._note_host_failure(host, exc)
            if spec.future.done():
                return
            if (retryable and spec.retries < self.max_retries
                    and not self._shutdown
                    and (spec.deadline is None
                         or self.clock() < spec.deadline)):
                spec.retries += 1
                spec.tried.add(host.host_id)
                self.metrics.inc("retries")
                obs_trace.instant("fleet/retry", cat="fleet",
                                  host=host.host_id, kind=spec.kind,
                                  retries=spec.retries,
                                  error=type(exc).__name__)
                self._dispatch(spec)
                return
            self.metrics.inc("failed", tenant=spec.tenant)
            _fail_safe(spec.future, exc)
        except BaseException as e:
            _fail_safe(spec.future, e)

    def _note_host_failure(self, host, exc) -> None:
        with self._lock:
            host.failures += 1
            host.last_error = f"{type(exc).__name__}: {exc}"
            trip = (self.breaker_threshold > 0
                    and host.failures >= self.breaker_threshold
                    and host.state != "down")
        self.metrics.inc("host_failures")
        if trip:
            self.mark_host_down(host.host_id, reason="breaker")

    # -- watchdog: timeouts, /metrics polls, heartbeat watch -------------

    def _watchdog_loop(self) -> None:
        while not self._stop.wait(self.watchdog_interval_s):
            try:
                self.poke()
            except Exception:
                # the watchdog must survive anything; count, don't die
                self.metrics.inc("watchdog_errors")

    def poke(self, now: Optional[float] = None) -> None:
        """One watchdog tick, callable synchronously from tests with an
        injected ``now``: expire per-request timeouts, refresh host
        queue-depth snapshots, reconcile the heartbeat ledger."""
        now = self.clock() if now is None else now
        expired: List[_Attempt] = []
        with self._lock:
            for a in list(self._outstanding.values()):
                if (a.timeout_at is not None and now >= a.timeout_at
                        and not a.settled):
                    a.settled = True
                    self._outstanding.pop(a.aid, None)
                    expired.append(a)
        for a in expired:
            self.metrics.inc("timeouts")
            self._handle_failure(
                a.spec, a.host,
                FleetTimeoutError(
                    f"host {a.host.host_id} exceeded "
                    f"{self.request_timeout_s}s for request dispatched at "
                    f"t={a.t_dispatch:.3f}"))
        if (self._last_depth_poll is None
                or now - self._last_depth_poll >= self.metrics_refresh_s):
            self._last_depth_poll = now
            self._poll_depths(now)
        if (self._membership is not None
                and (self._last_member_poll is None
                     or now - self._last_member_poll
                     >= self.membership_refresh_s)):
            self._last_member_poll = now
            self.refresh_membership()

    def _poll_depths(self, now: float) -> None:
        with self._lock:
            hosts = [h for h in self._hosts.values() if h.state != "down"]
        for h in hosts:
            depth = h.read_queue_depth()
            h.read_decode_pressure()
            with self._lock:
                h.cached_queue_depth = depth
                h.depth_read_at = now

    def refresh_membership(self) -> None:
        """Reconcile host state against the PR-6 heartbeat ledger: a
        process marked leaving (PR-9 preemption notice) is drained — stop
        dispatch, let in-flight finish; one that stopped beating is down."""
        if self._membership is None:
            return
        try:
            alive = set(self._membership.alive())
            leaving = set(self._membership.leaving())
        except Exception:
            # a torn ledger read: skip this tick, count it
            self.metrics.inc("membership_errors")
            return
        with self._lock:
            rows = [(h.host_id, h.process_id, h.state)
                    for h in self._hosts.values()
                    if h.process_id is not None]
        for hid, pid, state in rows:
            if state == "down":
                if pid in alive:
                    self.mark_host_up(hid)
                continue
            if pid in leaving:
                if state == "up":
                    with self._lock:
                        host = self._hosts.get(hid)
                        if host is not None and host.state == "up":
                            host.state = "draining"
                            self._gauge_hosts_locked()
                    self.metrics.inc("preempt_drains")
                    obs_trace.instant("fleet/drain", cat="fleet", host=hid,
                                      reason="leaving")
            elif pid not in alive:
                self.mark_host_down(hid, reason="heartbeat")

    # -- drain / preemption ----------------------------------------------

    def drain_host(self, host_id: str,
                   timeout_s: Optional[float] = None) -> bool:
        """Stop dispatching to ``host_id`` and wait until its in-flight
        count reaches zero (True) or ``timeout_s`` passes (False).  The
        host stays ``draining`` either way; ``undrain_host`` or
        ``mark_host_down`` decides its fate."""
        with obs_trace.span("fleet/drain", cat="fleet", host=host_id):
            deadline = (self.clock() + timeout_s
                        if timeout_s is not None else None)
            with self._lock:
                host = self._hosts.get(host_id)
                if host is None:
                    raise KeyError(f"unknown host {host_id!r}")
                if host.state == "up":
                    host.state = "draining"
                    self._gauge_hosts_locked()
                while host.inflight > 0 and host.state != "down":
                    remaining = (None if deadline is None
                                 else deadline - self.clock())
                    if remaining is not None and remaining <= 0:
                        return False
                    self._idle_cv.wait(
                        timeout=0.05 if remaining is None
                        else min(0.05, remaining))
            self.metrics.inc("drains")
            return True

    def undrain_host(self, host_id: str) -> None:
        with self._lock:
            host = self._hosts.get(host_id)
            if host is not None and host.state == "draining":
                host.state = "up"
                self._gauge_hosts_locked()

    def begin_drain(self) -> None:
        """Stop admission fleet-wide: every later submission is shed with
        :class:`OverloadedError` while already-dispatched requests keep
        running to completion.  The ``serve`` CLI calls this on a SIGTERM
        preemption notice so the router empties within the grace budget.
        Idempotent."""
        if self._draining:
            return
        self._draining = True
        self.metrics.inc("drains")
        obs_trace.instant("fleet/drain", cat="fleet", scope="router")

    def draining(self) -> bool:
        return self._draining

    def notify_preemption(self, host_id: str,
                          grace_s: Optional[float] = None) -> bool:
        """A host took a SIGTERM preemption notice (PR-9): drain it
        within the grace budget, then take it out of rotation as a
        planned leave.  Its traffic is re-placed on the surviving hosts
        by the normal dispatch path."""
        drained = self.drain_host(host_id, timeout_s=grace_s)
        self.metrics.inc("preempt_drains")
        self.mark_host_down(host_id, reason="preempt", planned=True)
        return drained

    # -- rolling swap -----------------------------------------------------

    def rolling_swap(self, model, tag: str, *, rollback_model=None,
                     rollback_tag: Optional[str] = None,
                     kind: str = "predict",
                     drain_timeout_s: float = 30.0,
                     warm_bundle: Optional[str] = None) -> dict:
        """Swap every up host to (``model``, ``tag``) one at a time under
        live traffic: drain the host (peers absorb its load), swap,
        undrain, move on.  If a host dies mid-swap it is marked down and
        the already-swapped survivors roll back to
        (``rollback_model``, ``rollback_tag``) — the fleet never serves
        two versions past the end of this call.

        ``warm_bundle`` is handed to each host's ``swap_model`` so the
        incoming version deserializes its executables instead of
        compiling (serving/warmcache.py) — the swap's drain window stays
        flat instead of absorbing a per-host cold compile.  Hosts whose
        engine does not take the keyword (remote ``HttpHost`` proxies)
        get the plain swap."""
        self.metrics.inc("rolling_swaps")
        report: Dict[str, Any] = {"ok": True, "tag": tag, "swapped": [],
                                  "rolled_back": False,
                                  "failed_host": None, "error": None}
        with obs_trace.span("fleet/rolling_swap", cat="fleet", tag=tag):
            with self._lock:
                order = [h for h in self._hosts.values()
                         if h.state == "up" and h.supports(kind)]
            swapped: List[FleetHost] = []
            for host in order:
                try:
                    if not self.drain_host(host.host_id,
                                           timeout_s=drain_timeout_s):
                        raise FleetTimeoutError(
                            f"drain of {host.host_id} timed out after "
                            f"{drain_timeout_s}s")
                    eng = host.engine_for(kind)
                    if warm_bundle is not None:
                        try:
                            eng.swap_model(model, tag,
                                           warm_bundle=warm_bundle)
                        except TypeError:
                            eng.swap_model(model, tag)
                    else:
                        eng.swap_model(model, tag)
                    swapped.append(host)
                    self.metrics.inc("swap_hosts")
                    obs_trace.instant("fleet/swap_host", cat="fleet",
                                      host=host.host_id, tag=tag)
                    self.undrain_host(host.host_id)
                except Exception as exc:
                    report["ok"] = False
                    report["failed_host"] = host.host_id
                    report["error"] = f"{type(exc).__name__}: {exc}"
                    self.mark_host_down(host.host_id, reason="swap_failed")
                    if rollback_model is not None and swapped:
                        self._rollback(swapped, rollback_model,
                                       rollback_tag or "rollback", kind,
                                       drain_timeout_s)
                        report["rolled_back"] = True
                    break
            report["swapped"] = [h.host_id for h in swapped]
        return report

    def _rollback(self, swapped, model, tag, kind,
                  drain_timeout_s) -> None:
        self.metrics.inc("rollbacks")
        obs_trace.instant("fleet/rollback", cat="fleet", tag=tag,
                          hosts=[h.host_id for h in swapped])
        for host in swapped:
            with self._lock:
                gone = host.state == "down"
            if gone:
                continue
            try:
                self.drain_host(host.host_id, timeout_s=drain_timeout_s)
                host.engine_for(kind).swap_model(model, tag)
                self.undrain_host(host.host_id)
            except Exception as exc:
                with self._lock:
                    host.last_error = f"{type(exc).__name__}: {exc}"
                self.mark_host_down(host.host_id,
                                    reason="rollback_failed")

    def promote(self, registry, name: str, version=None,
                alias: str = "prod", kind: str = "predict",
                drain_timeout_s: float = 30.0,
                warm_bundle: Optional[str] = None) -> dict:
        """Roll a registry promote through the fleet: resolve the new
        version once, remember the current alias target for rollback,
        swap host-by-host, and move the alias ONLY after every host
        swapped — a failed roll leaves both the fleet and the alias on
        the old version.

        When the version came off disk via ``registry.load``, its warmup
        bundle (``<checkpoint>.warm``, if present) is used automatically:
        ``warm_bundle`` overrides, else the checkpoint provenance the
        registry stamped on the model resolves it inside each engine's
        swap warmup."""
        new_version, new_model = registry.resolve(
            name, "latest" if version is None else version)
        try:
            old_version, old_model = registry.resolve(name, alias)
        except Exception:
            old_version, old_model = None, None
        report = self.rolling_swap(
            new_model, f"{name}:v{new_version}",
            rollback_model=old_model,
            rollback_tag=(f"{name}:v{old_version}"
                          if old_version is not None else None),
            kind=kind, drain_timeout_s=drain_timeout_s,
            warm_bundle=warm_bundle)
        report["version"] = new_version
        if report["ok"]:
            registry.set_alias(name, alias, new_version)
        return report

    # -- lifecycle --------------------------------------------------------

    def shutdown(self, timeout: float = 5.0,
                 shutdown_hosts: bool = False) -> None:
        """Deterministic shutdown: no new submissions, watchdog joined,
        every outstanding fleet future resolves (late host results become
        counted discards) — nothing is ever stranded."""
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
        self._stop.set()
        if self._watchdog_thread is not None:
            self._watchdog_thread.join(timeout=timeout)
        if shutdown_hosts:
            with self._lock:
                hosts = list(self._hosts.values())
            for h in hosts:
                for eng in (h.engine, h.decode):
                    if eng is None or not hasattr(eng, "shutdown"):
                        continue
                    try:
                        eng.shutdown()
                    except Exception as exc:
                        with self._lock:
                            h.last_error = f"{type(exc).__name__}: {exc}"
        with self._lock:
            pending = [a for a in self._outstanding.values()]
            self._outstanding.clear()
        for a in pending:
            _fail_safe(a.spec.future, ServingUnavailableError(
                "fleet router shut down"))


class HttpHost:
    """The engine duck type over a remote UIServer — the client half of
    ``serve --fleet host:port,...``.  ``output_async`` POSTs /predict on
    a small worker pool; /metrics and /healthz are proxied.  HTTP errors
    map back onto the typed serving exceptions so the router's retry
    classification is identical for local and remote hosts; transport
    failures (connection refused, read timeout) surface as retryable
    host faults."""

    _ERROR_CLASSES = {
        "overloaded": OverloadedError,
        "deadline_exceeded": DeadlineExceededError,
        "poison_input": PoisonInputError,
        "unavailable": ServingUnavailableError,
        "model_not_loaded": ModelNotLoadedError,
    }

    def __init__(self, base_url: str, timeout_s: float = 5.0,
                 workers: int = 4):
        if "://" not in base_url:
            base_url = "http://" + base_url
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self._pool = ThreadPoolExecutor(
            max_workers=workers,
            thread_name_prefix=f"fleet-http-{self.base_url.split('//')[-1]}")

    def _get_json(self, path: str) -> dict:
        with urllib.request.urlopen(self.base_url + path,
                                    timeout=self.timeout_s) as r:
            return json.loads(r.read().decode())

    def _predict(self, x, slo_ms, model=None, tenant=None):
        doc = {"inputs": np.asarray(x).tolist(), "slo_ms": slo_ms}
        if model is not None:
            doc["model"] = model
        if tenant is not None:
            doc["tenant"] = tenant
        body = json.dumps(doc).encode()
        req = urllib.request.Request(
            self.base_url + "/predict", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                out = json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read().decode())
            except Exception:
                payload = {}
            kind = payload.get("error_class")
            msg = payload.get("error", f"HTTP {e.code}")
            if kind == "tenant_overloaded":
                # rebuild the typed error so per-tenant attribution
                # survives the HTTP seam (429 body carries the fields)
                raise TenantOverloadedError(
                    msg, payload.get("tenant", tenant or ""),
                    payload.get("shed_count", 0),
                    reason=payload.get("reason", "quota")) from None
            cls = self._ERROR_CLASSES.get(kind, RuntimeError)
            raise cls(msg) from None
        return np.asarray(out["outputs"])

    def output_async(self, x, slo_ms: Optional[float] = None,
                     model: Optional[str] = None,
                     tenant: Optional[str] = None) -> Future:
        return self._pool.submit(self._predict, x, slo_ms, model, tenant)

    def output(self, x, slo_ms: Optional[float] = None,
               model: Optional[str] = None,
               tenant: Optional[str] = None):
        return self._predict(x, slo_ms, model, tenant)

    @property
    def current_tag(self) -> str:
        try:
            return str(self._get_json("/healthz").get("model", ""))
        except Exception:
            return ""

    def metrics_snapshot(self) -> dict:
        try:
            snap = self._get_json("/metrics")
        except Exception as exc:
            return {"queue_depth": 0,
                    "error": f"{type(exc).__name__}: {exc}"}
        depth = 0
        for s in snap.get("serving", []):
            d = s.get("queue_depth")
            if isinstance(d, (int, float)):
                depth += int(d)
        return {"queue_depth": depth, "remote": snap}

    def health_snapshot(self) -> dict:
        try:
            return self._get_json("/healthz")
        except Exception as exc:
            return {"status": "unready", "ready": False,
                    "error": f"{type(exc).__name__}: {exc}"}

    def shutdown(self, timeout: float = 5.0) -> None:
        self._pool.shutdown(wait=False)
