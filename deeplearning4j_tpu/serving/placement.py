"""Traffic-driven model placement: which host serves which model.

With multi-model engines (``Engine.add_model`` / ``DecodeEngine
.add_model``) and warm bundles making a model load a cheap, bounded
operation, WHERE a model runs becomes a scheduling decision instead of a
deployment.  ``PlacementController`` closes that loop over one
``FleetRouter``:

* **Demand signal**: per-model submit counts drained from
  ``router.model_traffic(reset=True)`` each tick, folded into an EWMA —
  the same smoothed-load idea as ``ReplicaAutoscaler``, generalized from
  replicas-per-engine to (model, host) placement.
* **Control law**: one :class:`ReplicaAutoscaler` PER MODEL answers
  "+1 / 0 / -1 hosts" from its EWMA demand vs. the replica count the
  model currently has.  Hot models widen (replicated onto more hosts),
  cooling models narrow, bounded by ``[min_hosts, max_hosts]``.
* **Actuation**: widening picks the least-crowded up host not yet
  placing the model and calls ``add_model_from_registry`` (warm bundles
  mean zero serve-time compiles); narrowing evicts from the
  most-crowded placing host via ``remove_model`` (which drains — no
  stranded futures, no version mixing).  A model idle longer than
  ``evict_idle_s`` is evicted everywhere — cold models cost nothing.
* **Demand reload**: the router's ``set_model_miss_handler`` hook calls
  :meth:`on_model_miss` when a request names a model no up host places
  (e.g. it was evicted, then traffic returned).  The controller loads
  it on the best host synchronously and tells dispatch to re-pick — an
  eviction turns a cold model into a one-request latency bump, not an
  error.

The controller owns no threads: call :meth:`tick` from any cadence
(bench soaks drive it inline; ``serve`` wires it to the watchdog
period).  Clocks are injectable (GC201).  The default model of each
engine is outside placement's authority — it can never be evicted, so a
single-model fleet behaves exactly as before this subsystem existed.

Observability (docs/OBSERVABILITY.md): every placement move emits a
``tenant/placement`` instant (add/evict, model, host); a demand reload
additionally emits ``tenant/demand_load``.  Fleet counters:
``placements``, ``placement_evictions``, ``demand_loads``,
``model_misses``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..obs import trace as obs_trace
from .autoscale import ReplicaAutoscaler


class PlacementController:
    """Maps (model, host) assignments from observed per-model traffic.

    ``registry`` supplies the inventory (``models_snapshot``) and the
    checkpoints/warm bundles; ``router`` supplies the fleet, the traffic
    signal, and the miss hook.  ``models`` restricts authority to an
    explicit set (default: every registry name) — the controller never
    touches a model it does not manage, and never an engine's default
    model.
    """

    def __init__(self, router, registry, *,
                 models: Optional[List[str]] = None,
                 kind: str = "predict",
                 ref: str = "prod",
                 min_hosts: int = 1,
                 max_hosts: Optional[int] = None,
                 up_load: float = 8.0,
                 down_load: float = 1.0,
                 up_ticks: int = 2,
                 down_ticks: int = 4,
                 cooldown_s: float = 2.0,
                 evict_idle_s: Optional[float] = None,
                 ewma_alpha: float = 0.5,
                 clock: Callable[[], float] = time.monotonic):
        if kind not in ("predict", "decode"):
            raise ValueError(f"kind must be predict or decode, got {kind!r}")
        if not 0.0 < ewma_alpha <= 1.0:
            raise ValueError(f"ewma_alpha must be in (0, 1], got {ewma_alpha}")
        self.router = router
        self.registry = registry
        self.kind = kind
        self.ref = ref
        self.min_hosts = int(min_hosts)
        self.max_hosts = max_hosts
        self.evict_idle_s = evict_idle_s
        self.ewma_alpha = float(ewma_alpha)
        self.clock = clock
        self._lock = threading.Lock()
        self._managed: List[str] = list(
            models if models is not None else registry.names())
        self._ewma: Dict[str, float] = {}
        self._scalers: Dict[str, ReplicaAutoscaler] = {}
        self._scaler_kw = dict(up_load=up_load, down_load=down_load,
                               up_ticks=up_ticks, down_ticks=down_ticks,
                               cooldown_s=cooldown_s)
        self._log: List[dict] = []
        router.set_model_miss_handler(self.on_model_miss)

    # -- views -----------------------------------------------------------

    def managed_models(self) -> List[str]:
        with self._lock:
            return list(self._managed)

    def manage(self, name: str) -> None:
        """Bring a (new) registry model under placement authority."""
        with self._lock:
            if name not in self._managed:
                self._managed.append(name)

    def placement(self) -> Dict[str, List[str]]:
        """model -> [host_id] for every managed model (live view from
        the fleet, not a shadow copy — restarts and manual add_model
        calls are always reflected)."""
        mm = self.router.model_map()
        with self._lock:
            managed = list(self._managed)
        out: Dict[str, List[str]] = {m: [] for m in managed}
        for hid, placed in mm.items():
            for m in placed:
                if m in out:
                    out[m].append(hid)
        return out

    def snapshot(self) -> dict:
        """Controller state for /metrics and the soak's assertions."""
        with self._lock:
            ewma = dict(self._ewma)
            log = list(self._log[-16:])
        return {"placement": self.placement(), "demand_ewma": ewma,
                "recent_moves": log}

    # -- control ---------------------------------------------------------

    def _scaler_for(self, name: str, n_hosts_up: int) -> ReplicaAutoscaler:
        s = self._scalers.get(name)
        cap = (self.max_hosts if self.max_hosts is not None
               else max(1, n_hosts_up))
        if s is None or s.max_replicas != cap:
            s = ReplicaAutoscaler(min_replicas=self.min_hosts,
                                  max_replicas=cap, clock=self.clock,
                                  **self._scaler_kw)
            self._scalers[name] = s
        return s

    def tick(self) -> List[dict]:
        """One control round: fold fresh traffic into the EWMA, run each
        managed model's control law, actuate at most one move per model.
        Returns the moves made (also kept in :meth:`snapshot`)."""
        traffic = self.router.model_traffic(reset=True)
        placement = self.placement()
        hosts_up = [hid for hid, st in self.router.hosts().items()
                    if st == "up"]
        now = self.clock()
        moves: List[dict] = []
        with self._lock:
            managed = list(self._managed)
            for m in managed:
                prev = self._ewma.get(m, 0.0)
                self._ewma[m] = (self.ewma_alpha * traffic.get(m, 0)
                                 + (1.0 - self.ewma_alpha) * prev)
        for m in managed:
            holders = placement.get(m, [])
            with self._lock:
                demand = self._ewma[m]
                scaler = self._scaler_for(m, len(hosts_up))
            if self._idle_evictable(m, holders, now):
                for hid in holders:
                    if self._evict(m, hid, reason="idle"):
                        moves.append({"op": "evict", "model": m,
                                      "host": hid, "reason": "idle"})
                continue
            # demand is request-rate-shaped; replicas = current holders.
            # queue_depth=demand / inflight=0 reuses the autoscaler's
            # (queue+inflight)/replicas law unchanged.
            verdict = scaler.observe(int(demand), 0, max(1, len(holders)))
            if verdict > 0 and len(holders) < len(hosts_up):
                hid = self._pick_target(m, holders, hosts_up)
                if hid is not None and self._load(m, hid):
                    moves.append({"op": "add", "model": m, "host": hid,
                                  "reason": "hot"})
            elif verdict < 0 and len(holders) > self.min_hosts:
                hid = self._pick_victim(m, holders)
                if hid is not None and self._evict(m, hid, reason="cool"):
                    moves.append({"op": "evict", "model": m, "host": hid,
                                  "reason": "cool"})
        if moves:
            with self._lock:
                self._log.extend(moves)
                if len(self._log) > 256:
                    del self._log[:128]
        return moves

    def _idle_evictable(self, m: str, holders: List[str],
                        now: float) -> bool:
        if self.evict_idle_s is None or not holders:
            return False
        with self._lock:
            if self._ewma.get(m, 0.0) > 0.5:
                return False
        for hid in holders:
            eng = self._engine_on(hid)
            lu = getattr(eng, "model_last_used", None)
            t = lu(m) if lu is not None else None
            if t is not None and now - t < self.evict_idle_s:
                return False
        return True

    # -- actuation -------------------------------------------------------

    def _engine_on(self, host_id: str):
        h = self.router.host(host_id)
        return h.engine_for(self.kind) if h is not None else None

    def _pick_target(self, m: str, holders: List[str],
                     hosts_up: List[str]) -> Optional[str]:
        """Least-crowded up host that supports the kind and does not
        already place the model."""
        best, best_n = None, None
        mm = self.router.model_map()
        for hid in hosts_up:
            if hid in holders:
                continue
            eng = self._engine_on(hid)
            if eng is None or not hasattr(eng, "add_model"):
                continue
            n = len(mm.get(hid, {}))
            if best_n is None or n < best_n:
                best, best_n = hid, n
        return best

    def _pick_victim(self, m: str, holders: List[str]) -> Optional[str]:
        """Most-crowded placing host gives the model up first."""
        mm = self.router.model_map()
        ranked = sorted(holders, key=lambda h: -len(mm.get(h, {})))
        return ranked[0] if ranked else None

    def _load(self, m: str, host_id: str, demand: bool = False) -> bool:
        eng = self._engine_on(host_id)
        if eng is None:
            return False
        try:
            if hasattr(eng, "add_model_from_registry"):
                eng.add_model_from_registry(self.registry, m, self.ref)
            else:
                _, model = self.registry.resolve(m, self.ref)
                eng.add_model(m, model)
        # graftcheck: disable=GC403 (registry.resolve is a model-version lookup, not a future resolution; a failed load is logged and the tick/miss path degrades typed)
        except Exception as exc:
            with self._lock:
                self._log.append({"op": "add_failed", "model": m,
                                  "host": host_id,
                                  "error": f"{type(exc).__name__}: {exc}"})
            return False
        self.router.metrics.inc("demand_loads" if demand else "placements")
        obs_trace.instant("tenant/placement", cat="fleet", op="add",
                          model=m, host=host_id, demand=demand)
        return True

    def _evict(self, m: str, host_id: str, reason: str = "cool") -> bool:
        eng = self._engine_on(host_id)
        if eng is None or not hasattr(eng, "remove_model"):
            return False
        try:
            ok = bool(eng.remove_model(m))
        except Exception as exc:
            with self._lock:
                self._log.append({"op": "evict_failed", "model": m,
                                  "host": host_id,
                                  "error": f"{type(exc).__name__}: {exc}"})
            return False
        if ok:
            self.router.metrics.inc("placement_evictions")
            obs_trace.instant("tenant/placement", cat="fleet", op="evict",
                              model=m, host=host_id, reason=reason)
        return ok

    # -- demand reload ----------------------------------------------------

    def on_model_miss(self, model: str, kind: str) -> bool:
        """Router hook: a request named a model no up host places.
        Load it on the best host NOW (warm-bundle path — bounded, no
        serve-time compiles) and return True so dispatch re-picks.
        Unmanaged/unknown models return False — the request fails typed
        rather than side-loading something placement does not own."""
        if kind != self.kind:
            return False
        with self._lock:
            if model not in self._managed:
                return False
        holders = self.placement().get(model, [])
        if holders:
            return True     # raced a concurrent load: just re-pick
        hosts_up = [hid for hid, st in self.router.hosts().items()
                    if st == "up"]
        hid = self._pick_target(model, holders, hosts_up)
        if hid is None:
            return False
        t0 = self.clock()
        if not self._load(model, hid, demand=True):
            return False
        obs_trace.instant("tenant/demand_load", cat="fleet", model=model,
                          host=hid, load_ms=(self.clock() - t0) * 1e3)
        with self._lock:
            self._log.append({"op": "demand_load", "model": model,
                              "host": hid})
        return True
