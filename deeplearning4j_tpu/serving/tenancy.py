"""Multi-tenant admission: SLO classes, fair-share weights, quotas.

One fleet serves N models for M tenants (docs/SERVING.md "Multi-tenant
serving").  The isolation contract has two halves, both implemented
here and consumed by ``serving/batcher.py``:

* **Quotas (admission)**: each tenant holds a concurrent-request cap
  and an optional QPS token bucket, checked-and-charged ATOMICALLY in
  :meth:`TenantTable.admit` (one critical section — no check-then-act
  window, so racing submits can never over-admit past the cap).  A
  tenant over its own quota sheds with :class:`TenantOverloadedError`,
  which carries the tenant and its shed counter so clients (and the
  429 path in ui/server.py) can tell "my quota" from "fleet overload".
* **Fair share (scheduling)**: each tenant carries a ``weight``; the
  batcher's per-tenant lanes are drained by stride scheduling — always
  the lane with the smallest virtual time ``served_rows / weight`` —
  so a bursting tenant's backlog cannot add queue delay to a victim
  tenant's requests (see the weighted-fair math in docs/SERVING.md).

A ``TenantConfig`` registers per ``(tenant, model)``; lookup falls
back from the exact pair to the tenant-wide row to the table default,
so one row can cover a tenant's whole zoo with a per-model override
where it matters.  Clocks are injectable (GC201): the QPS bucket and
last-activity stamps never read a wall clock.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from ..obs import trace as obs_trace
from .batcher import ADMISSION_POLICIES, OverloadedError


class TenantOverloadedError(OverloadedError):
    """This tenant's own quota (concurrent cap or QPS bucket) is spent —
    the fleet may be idle.  Carries the tenant and its running shed
    count so a 429 can say whose budget ran out."""

    def __init__(self, message: str, tenant: str, shed_count: int,
                 reason: str = "quota"):
        super().__init__(message)
        self.tenant = tenant
        self.shed_count = int(shed_count)
        self.reason = reason


class TenantConfig:
    """Admission/scheduling class for one tenant (optionally scoped to
    one model).  ``slo_ms`` is the default deadline budget for requests
    that do not pass their own; ``weight`` is the fair-share ratio
    (2.0 drains twice the rows per scheduling round of 1.0);
    ``quota_concurrent`` caps queued+in-flight requests;
    ``quota_qps`` refills a token bucket (burst = max(1, quota_qps)
    unless ``burst`` says otherwise).  ``admission`` is what happens at
    the cap: ``"shed"`` raises :class:`TenantOverloadedError`
    synchronously, ``"block"`` backpressures the submitter until a slot
    frees or the engine drains."""

    __slots__ = ("tenant", "model", "slo_ms", "weight", "quota_qps",
                 "quota_concurrent", "admission", "burst")

    def __init__(self, tenant: str, model: Optional[str] = None, *,
                 slo_ms: Optional[float] = None, weight: float = 1.0,
                 quota_qps: Optional[float] = None,
                 quota_concurrent: Optional[int] = None,
                 admission: str = "shed",
                 burst: Optional[float] = None):
        if not tenant:
            raise ValueError("tenant name must be non-empty")
        if weight <= 0:
            raise ValueError(f"weight must be > 0, got {weight}")
        if slo_ms is not None and slo_ms <= 0:
            raise ValueError(f"slo_ms must be > 0, got {slo_ms}")
        if quota_qps is not None and quota_qps <= 0:
            raise ValueError(f"quota_qps must be > 0, got {quota_qps}")
        if quota_concurrent is not None and quota_concurrent < 1:
            raise ValueError(
                f"quota_concurrent must be >= 1, got {quota_concurrent}")
        if admission not in ADMISSION_POLICIES:
            raise ValueError(f"admission must be one of "
                             f"{ADMISSION_POLICIES}, got {admission!r}")
        self.tenant = str(tenant)
        self.model = model
        self.slo_ms = float(slo_ms) if slo_ms is not None else None
        self.weight = float(weight)
        self.quota_qps = float(quota_qps) if quota_qps is not None else None
        self.quota_concurrent = (int(quota_concurrent)
                                 if quota_concurrent is not None else None)
        self.admission = admission
        self.burst = (float(burst) if burst is not None
                      else (max(1.0, self.quota_qps)
                            if self.quota_qps is not None else None))

    @classmethod
    def from_dict(cls, d: dict) -> "TenantConfig":
        """Build from one row of a ``--tenants tenants.json`` spec."""
        known = {"tenant", "model", "slo_ms", "weight", "quota_qps",
                 "quota_concurrent", "admission", "burst"}
        extra = set(d) - known
        if extra:
            raise ValueError(
                f"unknown tenant-spec keys {sorted(extra)}; "
                f"known: {sorted(known)}")
        if "tenant" not in d:
            raise ValueError("tenant spec row needs a 'tenant' key")
        kw = {k: v for k, v in d.items() if k not in ("tenant", "model")}
        return cls(d["tenant"], d.get("model"), **kw)

    def to_dict(self) -> dict:
        return {"tenant": self.tenant, "model": self.model,
                "slo_ms": self.slo_ms, "weight": self.weight,
                "quota_qps": self.quota_qps,
                "quota_concurrent": self.quota_concurrent,
                "admission": self.admission}


class _TenantState:
    """Mutable accounting for one tenant (across all its models)."""

    __slots__ = ("concurrent", "admitted", "shed", "completed",
                 "tokens", "token_t")

    def __init__(self):
        self.concurrent = 0
        self.admitted = 0
        self.shed = 0
        self.completed = 0
        self.tokens: Optional[float] = None   # lazily seeded to burst
        self.token_t: Optional[float] = None


class TenantTable:
    """Thread-safe registry of :class:`TenantConfig` rows plus the live
    per-tenant accounting.  One table is shared by every batcher on a
    host (predict + decode), so the concurrent cap really is the
    tenant's host-wide budget.

    Lock ordering: callers (the batchers) hold their own lock when
    calling in; this table's lock is strictly inner and nothing here
    calls back out — no inversion is possible.
    """

    def __init__(self, configs=(), *,
                 default: Optional[TenantConfig] = None,
                 clock=time.monotonic):
        self._lock = threading.Lock()
        self._configs: Dict[Tuple[str, Optional[str]], TenantConfig] = {}
        self._states: Dict[str, _TenantState] = {}
        self._default = default
        self.clock = clock
        for c in configs:
            self.register(c)

    @classmethod
    def from_specs(cls, rows, **kw) -> "TenantTable":
        """Build from a list of dict rows (the ``tenants.json`` shape)."""
        return cls([TenantConfig.from_dict(r) for r in rows], **kw)

    # -- configuration ---------------------------------------------------

    def register(self, config: TenantConfig) -> None:
        with self._lock:
            self._configs[(config.tenant, config.model)] = config
            self._states.setdefault(config.tenant, _TenantState())

    def resolve(self, tenant: str,
                model: Optional[str] = None) -> Optional[TenantConfig]:
        """Most specific row that covers (tenant, model): the exact
        pair, else the tenant-wide row, else the table default (which
        may be None — an unknown tenant is then unlimited)."""
        with self._lock:
            return self._resolve_locked(tenant, model)

    def _resolve_locked(self, tenant, model):
        c = self._configs.get((tenant, model))
        if c is None and model is not None:
            c = self._configs.get((tenant, None))
        if c is None:
            c = self._default
        return c

    def tenants(self):
        with self._lock:
            return sorted({t for t, _ in self._configs})

    def weight(self, tenant: str) -> float:
        """Fair-share weight for the batcher's stride scheduler; the
        anonymous lane (untagged traffic) weighs 1.0."""
        if not tenant:
            return 1.0
        with self._lock:
            c = self._resolve_locked(tenant, None)
        return c.weight if c is not None else 1.0

    def slo_ms_for(self, tenant: str,
                   model: Optional[str] = None) -> Optional[float]:
        c = self.resolve(tenant, model)
        return c.slo_ms if c is not None else None

    def admission_for(self, tenant: str,
                      model: Optional[str] = None) -> str:
        c = self.resolve(tenant, model)
        return c.admission if c is not None else "shed"

    # -- admission accounting --------------------------------------------

    def try_admit(self, tenant: str, model: Optional[str] = None,
                  now: Optional[float] = None) -> bool:
        """Check-and-charge in ONE critical section: returns True and
        charges the tenant's concurrent slot + QPS token, or returns
        False having charged nothing (the caller sheds or blocks).
        Untagged traffic ("" tenant) is never limited here."""
        if not tenant:
            return True
        now = self.clock() if now is None else now
        with self._lock:
            c = self._resolve_locked(tenant, model)
            s = self._states.setdefault(tenant, _TenantState())
            if c is None:
                s.concurrent += 1
                s.admitted += 1
                return True
            if (c.quota_concurrent is not None
                    and s.concurrent >= c.quota_concurrent):
                return False
            if c.quota_qps is not None:
                if s.tokens is None:
                    s.tokens, s.token_t = c.burst, now
                else:
                    s.tokens = min(c.burst, s.tokens
                                   + (now - s.token_t) * c.quota_qps)
                    s.token_t = now
                if s.tokens < 1.0:
                    return False
                s.tokens -= 1.0
            s.concurrent += 1
            s.admitted += 1
        return True

    def shed(self, tenant: str, model: Optional[str] = None,
             reason: str = "quota") -> TenantOverloadedError:
        """Charge one shed to the tenant and build the typed error the
        caller raises (the error carries the updated counter)."""
        with self._lock:
            s = self._states.setdefault(tenant, _TenantState())
            s.shed += 1
            n = s.shed
        obs_trace.instant("tenant/shed", cat="serve", tenant=tenant,
                          model=model, reason=reason)
        return TenantOverloadedError(
            f"tenant {tenant!r} over its {reason} "
            f"({n} sheds so far); victims are unaffected",
            tenant, n, reason=reason)

    def release(self, tenant: str) -> None:
        """Free the concurrent slot charged by :meth:`try_admit` —
        wired to ``future.add_done_callback``, so the engine invariant
        (every future resolves) guarantees exactly one release."""
        if not tenant:
            return
        with self._lock:
            s = self._states.get(tenant)
            if s is None:
                return
            s.concurrent = max(0, s.concurrent - 1)
            s.completed += 1

    def concurrent(self, tenant: str) -> int:
        with self._lock:
            s = self._states.get(tenant)
            return s.concurrent if s else 0

    def shed_count(self, tenant: str) -> int:
        with self._lock:
            s = self._states.get(tenant)
            return s.shed if s else 0

    def snapshot(self) -> dict:
        """Per-tenant accounting for /metrics: admitted/shed/completed
        counters plus the live concurrent occupancy and config."""
        with self._lock:
            out = {}
            for t, s in sorted(self._states.items()):
                c = self._resolve_locked(t, None)
                out[t] = {
                    "admitted": s.admitted, "shed": s.shed,
                    "completed": s.completed, "concurrent": s.concurrent,
                    "weight": c.weight if c else 1.0,
                    "slo_ms": c.slo_ms if c else None,
                    "quota_qps": c.quota_qps if c else None,
                    "quota_concurrent": (c.quota_concurrent if c
                                         else None),
                }
            return out
