"""Versioned model registry with alias pinning and hot-swap.

The reference ecosystem's model-server keeps one mutable "current
model"; here versions are immutable once registered and DEPLOYMENT is a
pointer move:

    reg = ModelRegistry()
    v1 = reg.load("lenet", "ckpt_v1.zip")     # utils/serializer v1-v4 zips
    v2 = reg.load("lenet", "ckpt_v2.zip")
    reg.set_alias("lenet", "prod", v1)        # pin
    eng = Engine.from_registry(reg, "lenet", "prod").load()
    reg.set_alias("lenet", "prod", v2)        # hot-swap: drains in-flight
    reg.set_alias("lenet", "prod", v1)        # rollback = alias move

``set_alias`` notifies subscribed engines synchronously and returns only
after each engine has warmed the incoming version, flipped its current
pointer, and drained every in-flight batch on the outgoing one — so when
it returns, no request is still executing the old version.  Batches
never mix versions (each batch snapshots exactly one version).

``set_alias(..., canary=frac)`` promotes THROUGH a canary instead of
flipping immediately: every subscribed engine mirrors ``frac`` of its
live traffic to the incoming version as shadow traffic (user results
still come from the incumbent), compares error rate / p99 / prediction
divergence over a decision window, and votes.  The alias moves only if
EVERY engine votes promote; otherwise the promotion auto-rolls-back and
the alias stays on the incumbent.  The decision (with per-engine stats)
is returned and recorded in :meth:`canary_history`.

Checkpoints load through ``utils/serializer.load_model`` and therefore
accept every supported FORMAT_VERSION (1-4), including v4 integrity
digests — a corrupt file raises instead of serving garbage.

Lineage (docs/LIFECYCLE.md): ``register``/``load`` accept a
``lineage=`` provenance record — which run produced the version, which
data slice it trained/evaluated on, its eval scores, the parent
version it continued from, and a content hash of its weights.  Records
are immutable alongside the version itself and drive
:meth:`rollback_target`: rollback re-aliases to the last
*eval-passing* ancestor on the parent chain, not merely version−1
(version−1 may be a registered-for-audit failure).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


class CanaryRejectedError(RuntimeError):
    """``set_alias(..., canary=frac, raise_on_reject=True)`` failed to
    promote: at least one subscribed engine voted rollback (or its
    decision window never filled).  Carries the full decision
    ``record`` (the same dict :meth:`ModelRegistry.canary_history`
    keeps) so callers — the PromotionPipeline above all — get a
    programmatic rejection signal instead of fishing the history."""

    def __init__(self, record: dict):
        reasons = [r for d in record.get("decisions", ())
                   for r in d.get("reasons", ())]
        super().__init__(
            f"canary rejected {record.get('name')} "
            f"v{record.get('from')} -> v{record.get('to')} on alias "
            f"{record.get('alias')!r}: {'; '.join(reasons) or 'no votes'}")
        self.record = record
        self.name = record.get("name")
        self.alias = record.get("alias")
        self.incumbent = record.get("from")
        self.candidate = record.get("to")
        self.reasons = reasons


#: lineage-record fields every record carries (absent inputs become None)
LINEAGE_FIELDS = ("run_id", "data_fingerprint", "parent_version",
                  "eval_score", "eval_passed", "weights_sha",
                  "checkpoint_path")


class ModelRegistry:
    """name -> {version -> model} + name -> {alias -> version}."""

    def __init__(self, clock=time.monotonic):
        self._models: Dict[str, Dict[int, Any]] = {}
        self._aliases: Dict[str, Dict[str, int]] = {}
        self._lock = threading.Lock()
        self.clock = clock
        # name -> registry-clock stamp of the last resolve() — the
        # placement controller's cold-model signal (injectable clock,
        # GC201: never a wall clock)
        self._last_access: Dict[str, float] = {}
        # (name, alias) -> [(callback(version, model), canary_cb or None)]
        self._subs: Dict[Tuple[str, str],
                         List[Tuple[Callable[[int, Any], None],
                                    Optional[Callable]]]] = {}
        self._canary_log: Dict[str, List[dict]] = {}
        # (name, version) -> checkpoint path, for versions that came off
        # disk — the provenance serving uses to find warmup bundles
        # (serving/warmcache.py: `<checkpoint>.warm` next to the zip)
        self._paths: Dict[Tuple[str, int], str] = {}
        # (name, version) -> lineage provenance record (immutable, like
        # the version itself); see LINEAGE_FIELDS / docs/LIFECYCLE.md
        self._lineage: Dict[Tuple[str, int], dict] = {}

    # -- registration ------------------------------------------------------

    def register(self, name: str, model, version: Optional[int] = None,
                 lineage: Optional[dict] = None) -> int:
        """Register an in-memory model; returns its version number
        (monotonically assigned when not given).  Re-registering an
        existing (name, version) is an error — versions are immutable.

        ``lineage`` attaches an immutable provenance record (see
        LINEAGE_FIELDS); unknown extra keys are preserved.  A version
        may be registered with ``eval_passed=False`` purely as an audit
        trail — :meth:`rollback_target` skips such versions."""
        with self._lock:
            versions = self._models.setdefault(name, {})
            if version is None:
                version = max(versions, default=0) + 1
            version = int(version)
            if version in versions:
                raise ValueError(f"{name} v{version} already registered — "
                                 "versions are immutable; register a new one")
            versions[version] = model
            if lineage is not None:
                rec = {k: None for k in LINEAGE_FIELDS}
                rec.update(lineage)
                rec["name"] = name
                rec["version"] = version
                self._lineage[(name, version)] = rec
            return version

    def lineage(self, name: str,
                version: Optional[int] = None):
        """Provenance records for ``name``: the single record for
        ``version`` (None if that version has no lineage), or — with
        ``version=None`` — every recorded lineage, version-ascending."""
        with self._lock:
            if version is not None:
                rec = self._lineage.get((name, int(version)))
                return dict(rec) if rec is not None else None
            return [dict(self._lineage[(n, v)])
                    for (n, v) in sorted(self._lineage)
                    if n == name]

    def rollback_target(self, name: str,
                        version: Optional[int] = None) -> Optional[int]:
        """The version a failed promotion of ``version`` (default: the
        newest registered) should roll back to: the nearest
        *eval-passing* ancestor, following the lineage
        ``parent_version`` chain first, then falling back to a
        descending scan of older versions.  Versions registered for
        audit with ``eval_passed=False`` (a NaN run, a gate failure)
        are never rollback targets — rollback is principled, not
        version−1.  None when no eval-passing ancestor exists (e.g.
        the very first generation failed)."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise KeyError(f"no model named {name!r} registered")
            start = max(versions) if version is None else int(version)

            def passing(v: int) -> bool:
                rec = self._lineage.get((name, v))
                return bool(rec is not None and rec.get("eval_passed"))

            # the parent chain: provenance-driven, survives version-number
            # gaps left by audit registrations
            seen = set()
            rec = self._lineage.get((name, start))
            cur = rec.get("parent_version") if rec is not None else None
            while cur is not None and cur not in seen:
                seen.add(cur)
                cur = int(cur)
                if cur in versions and passing(cur):
                    return cur
                nxt = self._lineage.get((name, cur))
                cur = nxt.get("parent_version") if nxt is not None else None
            # chain exhausted / absent: newest eval-passing older version
            for v in sorted(versions, reverse=True):
                if v < start and passing(v):
                    return v
            return None

    def load(self, name: str, path: str,
             version: Optional[int] = None,
             lineage: Optional[dict] = None) -> int:
        """Load a checkpoint zip (serializer FORMAT_VERSION 1-4) and
        register it.  The checkpoint path is recorded as provenance —
        on the registry (:meth:`checkpoint_path`) AND stamped on the
        model object — so serving's warmup can find the version's
        warmup bundle (``<checkpoint>.warm``) through every swap /
        promote seam without re-plumbing paths."""
        from ..utils.serializer import load_model

        model = load_model(path)
        model._checkpoint_path = str(path)
        if lineage is not None:
            lineage = dict(lineage)
            lineage.setdefault("checkpoint_path", str(path))
        version = self.register(name, model, version=version,
                                lineage=lineage)
        with self._lock:
            self._paths[(name, version)] = str(path)
        return version

    def checkpoint_path(self, name: str, ref: Any = "latest") -> Optional[str]:
        """The checkpoint zip a version was loaded from (None for
        in-memory registrations)."""
        with self._lock:
            if name not in self._models:
                return None
            try:
                v = self._resolve_version_locked(name, ref)
            except KeyError:
                return None
            return self._paths.get((name, v))

    # -- lookup ------------------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def versions(self, name: str) -> List[int]:
        with self._lock:
            return sorted(self._models.get(name, {}))

    def aliases(self, name: str) -> Dict[str, int]:
        with self._lock:
            return dict(self._aliases.get(name, {}))

    def list_aliases(self) -> Dict[str, Dict[str, int]]:
        """Every alias pin across the whole registry:
        name -> {alias -> version}.  Names with no aliases are omitted
        — this is the \"what is deployable right now\" view."""
        with self._lock:
            return {n: dict(a)
                    for n, a in sorted(self._aliases.items()) if a}

    def models_snapshot(self) -> Dict[str, dict]:
        """Inventory for the placement controller (and /metrics): every
        registered name with its versions, alias pins, prod-pinned
        version (None when unpinned), a lineage summary, the pinned (or
        latest) version's checkpoint path, and the registry-clock stamp
        of its last :meth:`resolve` (None = never served)."""
        with self._lock:
            out: Dict[str, dict] = {}
            for name in sorted(self._models):
                versions = self._models[name]
                aliases = dict(self._aliases.get(name, {}))
                pinned = aliases.get("prod")
                head = pinned if pinned is not None else max(versions)
                recs = [self._lineage[(n, v)]
                        for (n, v) in sorted(self._lineage) if n == name]
                head_rec = self._lineage.get((name, head))
                out[name] = {
                    "versions": sorted(versions),
                    "aliases": aliases,
                    "pinned": pinned,
                    "lineage": {
                        "recorded": len(recs),
                        "eval_passed": sum(1 for r in recs
                                           if r.get("eval_passed")),
                        "head": ({"version": head,
                                  "parent_version":
                                      head_rec.get("parent_version"),
                                  "eval_passed":
                                      head_rec.get("eval_passed"),
                                  "run_id": head_rec.get("run_id")}
                                 if head_rec is not None else None),
                    },
                    "checkpoint_path": self._paths.get((name, head)),
                    "last_access": self._last_access.get(name),
                }
            return out

    def resolve(self, name: str, ref: Any = "latest") -> Tuple[int, Any]:
        """(version, model) for a ref: an int version, ``"latest"``, a
        ``"v<N>"`` string, or an alias name."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise KeyError(f"no model named {name!r} registered")
            v = self._resolve_version_locked(name, ref)
            self._last_access[name] = self.clock()
            return v, versions[v]

    def _resolve_version_locked(self, name: str, ref: Any) -> int:
        versions = self._models[name]
        if isinstance(ref, int):
            v = ref
        elif ref == "latest":
            v = max(versions)
        elif isinstance(ref, str) and ref.startswith("v") and ref[1:].isdigit():
            v = int(ref[1:])
        else:
            alias = self._aliases.get(name, {})
            if ref not in alias:
                raise KeyError(
                    f"{name}: unknown version ref {ref!r} (have versions "
                    f"{sorted(versions)}, aliases {sorted(alias)})")
            v = alias[ref]
        if v not in versions:
            raise KeyError(f"{name}: version {v} not registered "
                           f"(have {sorted(versions)})")
        return v

    # -- aliases + hot swap ------------------------------------------------

    def set_alias(self, name: str, alias: str, version: int,
                  canary: Optional[float] = None,
                  canary_window: int = 32,
                  canary_timeout_s: float = 60.0,
                  canary_thresholds: Optional[Dict[str, Any]] = None,
                  raise_on_reject: bool = False):
        """Atomically move ``alias`` to ``version`` and hot-swap every
        subscribed engine (synchronously — returns after old versions
        drained).  Returns the alias's previous version (None if new).
        Rollback is just another ``set_alias`` to the old version.

        With ``canary=frac`` (0 < frac <= 1) the move goes through a
        canary evaluation first: each subscribed engine mirrors ``frac``
        of its live traffic to the incoming version over
        ``canary_window`` mirrored batches (bounded by
        ``canary_timeout_s``), judged against ``canary_thresholds``
        (``max_error_rate``, ``p99_factor``, ``max_divergence`` — see
        ``Engine.run_canary``).  The alias moves only if every engine
        votes promote; on any rollback vote the alias stays put.
        Returns the decision record (also kept in
        :meth:`canary_history`) instead of the previous version.

        ``raise_on_reject=True`` turns a failed canary into a typed
        :class:`CanaryRejectedError` (record attached) instead of a
        record the caller must inspect — the programmatic rejection
        signal promotion controllers key rollback off.  A promoted
        canary (and the non-canary path) is unaffected.
        """
        with self._lock:
            if name not in self._models:
                raise KeyError(f"no model named {name!r} registered")
            version = self._resolve_version_locked(name, version)
            amap = self._aliases.setdefault(name, {})
            prev = amap.get(alias)
            model = self._models[name][version]
            subs = list(self._subs.get((name, alias), ()))
            canary_subs = [c for _, c in subs if c is not None]
            run_canary = (canary is not None and prev is not None
                          and prev != version and canary_subs)
            if not run_canary:
                amap[alias] = version
        if run_canary:
            # canary path: the alias has NOT moved — engines judge the
            # candidate on shadow traffic first (outside the lock: the
            # decision window is live serving time)
            thresholds = dict(canary_thresholds or {})
            canary_pairs = [(cb, c) for cb, c in subs if c is not None]
            decisions = [c(version, model, frac=canary,
                           window=canary_window,
                           timeout_s=canary_timeout_s, **thresholds)
                         for _, c in canary_pairs]
            promoted = all(d.get("promote") for d in decisions)
            record = {"name": name, "alias": alias, "from": prev,
                      "to": version, "promoted": promoted,
                      "decisions": decisions}
            with self._lock:
                self._canary_log.setdefault(name, []).append(record)
                if promoted:
                    self._aliases[name][alias] = version
                incumbent_model = self._models[name][prev]
            if promoted:
                # promote-voting engines already completed their hot-swap
                # inside run_canary; plain (non-canary) subscribers still
                # need the regular swap notification
                for cb, canary_cb in subs:
                    if canary_cb is None:
                        cb(version, model)
            else:
                # unanimity failed: any engine whose individual vote was
                # promote has already swapped — swap it back to the
                # incumbent so the fleet stays version-consistent
                for (cb, _), d in zip(canary_pairs, decisions):
                    if d.get("promote"):
                        cb(prev, incumbent_model)
            if not promoted and raise_on_reject:
                raise CanaryRejectedError(record)
            return record
        if prev != version:
            # callbacks run OUTSIDE the registry lock: an engine's swap
            # blocks on draining in-flight batches, whose replica threads
            # must never need this lock
            for cb, _ in subs:
                cb(version, model)
        return prev

    def canary_history(self, name: str) -> List[dict]:
        """Every canary promotion decision recorded for ``name``."""
        with self._lock:
            return list(self._canary_log.get(name, ()))

    def subscribe(self, name: str, alias: str,
                  callback: Callable[[int, Any], None],
                  canary: Optional[Callable] = None) -> None:
        """Engine hook: ``callback(version, model)`` fires on every
        ``set_alias`` move of (name, alias); ``canary(version, model,
        frac=, window=, timeout_s=, **thresholds)`` (when provided)
        handles ``set_alias(..., canary=frac)`` evaluations and must
        return the decision dict (``Engine.run_canary``'s contract)."""
        with self._lock:
            self._subs.setdefault((name, alias), []).append((callback, canary))
