"""Deadline-aware dynamic micro-batcher.

Replaces the fixed-poll drain of the old ``parallel/inference.py``
worker (``queue.get(timeout=queue_timeout_s)`` per item — a latency
floor under EVERY request, and a throughput stall whenever the queue
briefly empties) with an event-driven close: a batch closes the moment

  * queued rows reach ``max_batch`` (never overshooting it — the old
    drain bucketed on the TOTAL queued rows, so a 33-row drain at
    ``max_batch=32`` ran an unbucketed 33-row program; here drains are
    split at ``max_batch`` BEFORE bucketing), or
  * waiting any longer would eat into the oldest request's deadline:
    close time = earliest deadline − the EMA device time of the bucket
    the batch would run in (seeded by AOT warmup, see engine.load()).

Requests carry their own deadline (default: submit + SLO budget).  A
request whose deadline passes while still queued fails fast with
``DeadlineExceededError`` instead of returning a stale result.

Admission control: the queue is bounded (``max_queue`` requests) with a
configurable overload policy — ``"block"`` (backpressure the caller) or
``"shed"`` (raise ``OverloadedError`` immediately) — so overload
degrades predictably instead of growing an unbounded queue until OOM.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Deque, Dict, List, Optional, Sequence

import numpy as np

from ..obs import trace as obs_trace

ADMISSION_POLICIES = ("block", "shed")


class DeadlineExceededError(RuntimeError):
    """The request's deadline passed before a device slot freed up —
    the caller's SLO is already blown, so the result would be stale."""


class OverloadedError(RuntimeError):
    """The admission queue is full and the policy is ``shed`` — retry
    with backoff or route to another replica group."""


class _Request:
    __slots__ = ("x", "rows", "future", "t_submit", "deadline",
                 "retries", "tried", "payload")

    def __init__(self, x: np.ndarray, future: Future, t_submit: float,
                 deadline: float):
        self.x = x
        self.rows = int(x.shape[0])
        self.future = future
        self.t_submit = t_submit
        self.deadline = deadline
        self.retries = 0          # failure-isolation retries consumed
        self.tried = set()        # replica indices that failed this request
        self.payload = None       # decode-path request spec (ContinuousBatcher)


def pow2_buckets(max_batch: int) -> List[int]:
    """1, 2, 4, ... up to and including ``max_batch``."""
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return sorted(set(out))


class DynamicBatcher:
    """Bounded request queue + deadline-aware batch former.

    One or more worker/dispatcher threads call :meth:`next_batch`; any
    number of caller threads call :meth:`submit`.  ``clock`` is
    injectable (monotonic seconds) so deadline logic is testable
    without sleeping.
    """

    def __init__(self, max_batch: int = 32, slo_ms: float = 50.0,
                 bucket_sizes: Optional[Sequence[int]] = None,
                 max_queue: int = 1024, admission: str = "block",
                 max_wait_ms: Optional[float] = None,
                 metrics=None, clock=time.monotonic):
        if admission not in ADMISSION_POLICIES:
            raise ValueError(f"admission must be one of "
                             f"{ADMISSION_POLICIES}, got {admission!r}")
        if max_batch < 1 or max_queue < 1 or slo_ms <= 0:
            raise ValueError("max_batch/max_queue must be >=1, slo_ms > 0")
        self.max_batch = int(max_batch)
        self.slo_ms = float(slo_ms)
        # batch-forming window: at LOW load a batch must not sit waiting
        # for companions until its deadline-slack runs out (that would
        # make p50 == SLO); the oldest request waits at most this long
        # before the batch closes.  The deadline-slack close below stays
        # the binding constraint whenever it is tighter.
        self.max_wait_ms = (float(max_wait_ms) if max_wait_ms is not None
                            else min(5.0, self.slo_ms / 10.0))
        self.buckets = (sorted(set(int(b) for b in bucket_sizes))
                        if bucket_sizes else pow2_buckets(max_batch))
        self.max_queue = int(max_queue)
        self.admission = admission
        self.metrics = metrics
        self.clock = clock
        self._pending: Deque[_Request] = deque()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._space = threading.Condition(self._lock)
        self._closed = False
        self._draining = False
        # bucket -> EMA device ms; the exec budget subtracted from the
        # oldest deadline when deciding how long a batch may keep filling
        self._exec_ema_ms: Dict[int, float] = {}

    # -- shape buckets -----------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest configured bucket >= n; oversized requests (> the
        largest bucket) get the next power of two — they run, but pay
        their own compile (engine metrics count them as unwarmed)."""
        for b in self.buckets:
            if n <= b:
                return b
        b = self.buckets[-1]
        while b < n:
            b *= 2
        return b

    def observe_exec_ms(self, bucket: int, ms: float, alpha: float = 0.3) -> None:
        prev = self._exec_ema_ms.get(bucket)
        self._exec_ema_ms[bucket] = (ms if prev is None
                                     else alpha * ms + (1 - alpha) * prev)

    def _exec_budget_ms(self, rows: int) -> float:
        """Expected device time for a batch of ``rows`` — the slack we
        must keep in hand when deciding to wait for more requests.
        Unmeasured buckets assume a quarter of the SLO."""
        ema = self._exec_ema_ms.get(self.bucket_for(min(rows, self.max_batch)))
        return ema if ema is not None else self.slo_ms * 0.25

    # -- submission --------------------------------------------------------

    def submit(self, x: np.ndarray, slo_ms: Optional[float] = None,
               deadline: Optional[float] = None) -> Future:
        """Enqueue one request; returns its Future.  Shedding raises
        ``OverloadedError`` synchronously; a closed batcher fails the
        future deterministically (never a silent hang)."""
        x = np.asarray(x)
        if x.ndim < 1 or x.shape[0] < 1:
            raise ValueError(f"request must have a leading batch axis, "
                             f"got shape {x.shape}")
        fut: Future = Future()
        now = self.clock()
        dl = deadline if deadline is not None else now + (
            slo_ms if slo_ms is not None else self.slo_ms) / 1000.0
        with self._lock:
            if self._closed:
                fut.set_exception(RuntimeError("serving engine is shut down"))
                return fut
            if self._draining:
                if self.metrics:
                    self.metrics.inc("shed")
                raise OverloadedError(
                    "admission stopped: engine is draining (preemption "
                    "notice)")
            if len(self._pending) >= self.max_queue:
                if self.admission == "shed":
                    if self.metrics:
                        self.metrics.inc("shed")
                    raise OverloadedError(
                        f"admission queue full ({self.max_queue} requests); "
                        "policy=shed")
                while (len(self._pending) >= self.max_queue
                       and not self._closed and not self._draining):
                    self._space.wait(timeout=0.1)
                if self._closed:
                    fut.set_exception(
                        RuntimeError("serving engine is shut down"))
                    return fut
                if self._draining:
                    if self.metrics:
                        self.metrics.inc("shed")
                    raise OverloadedError(
                        "admission stopped: engine is draining (preemption "
                        "notice)")
            self._pending.append(_Request(x, fut, now, dl))
            self._nonempty.notify()
        return fut

    def qsize(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- batch formation ---------------------------------------------------

    def _expire_locked(self, now: float) -> None:
        """Fail-fast every queued request whose deadline already passed."""
        if not self._pending:
            return
        keep: Deque[_Request] = deque()
        expired = 0
        for r in self._pending:
            if r.deadline < now:
                expired += 1
                if not r.future.done():
                    r.future.set_exception(DeadlineExceededError(
                        f"request waited {(now - r.t_submit) * 1e3:.1f}ms in "
                        f"queue, past its {(r.deadline - r.t_submit) * 1e3:.0f}"
                        "ms deadline"))
            else:
                keep.append(r)
        if expired:
            self._pending = keep
            if self.metrics:
                self.metrics.inc("deadline_missed", expired)
            self._space.notify_all()

    def _pop_batch_locked(self) -> List[_Request]:
        batch: List[_Request] = []
        rows = 0
        while self._pending:
            r = self._pending[0]
            # split at max_batch BEFORE bucketing; a single oversized
            # request still goes alone (it cannot be split)
            if batch and rows + r.rows > self.max_batch:
                break
            batch.append(self._pending.popleft())
            rows += r.rows
            if rows >= self.max_batch:
                break
        self._space.notify_all()
        if batch:
            # post-hoc span: batch formation ran from the oldest member's
            # submit until this close decision
            obs_trace.complete_at(
                "serve/batch_form", min(r.t_submit for r in batch),
                self.clock(), cat="serve", rows=rows, n_requests=len(batch))
        return batch

    def next_batch(self) -> Optional[List[_Request]]:
        """Block until a batch closes; None once closed AND drained."""
        with self._lock:
            while True:
                now = self.clock()
                self._expire_locked(now)
                if not self._pending:
                    if self._closed:
                        return None
                    # pure event wait — the timeout only bounds how stale
                    # a missed notify can leave us (defensive, not a poll)
                    self._nonempty.wait(timeout=0.5)
                    continue
                total = sum(r.rows for r in self._pending)
                if total >= self.max_batch or self._closed:
                    return self._pop_batch_locked()
                earliest = min(r.deadline for r in self._pending)
                oldest = min(r.t_submit for r in self._pending)
                t_close = min(
                    oldest + self.max_wait_ms / 1000.0,
                    earliest - self._exec_budget_ms(total) / 1000.0)
                if now >= t_close:
                    return self._pop_batch_locked()
                # cap the wait so deadline expiry scans keep running even
                # if no new request arrives to notify us
                self._nonempty.wait(timeout=min(t_close - now, 0.05))

    # -- shutdown ----------------------------------------------------------

    def begin_drain(self) -> None:
        """Stop admission without failing anything queued: every
        SUBSEQUENT submit sheds (``OverloadedError``, → HTTP 429)
        regardless of the admission policy — block-policy callers
        already waiting for space are woken and shed too — while queued
        requests keep draining through ``next_batch``/``admit``.  The
        graceful-preemption front half: shed new, finish in-flight,
        then ``close()``.  Idempotent."""
        with self._lock:
            self._draining = True
            self._space.notify_all()

    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def close(self, fail_pending: bool = True) -> None:
        """Idempotent.  With ``fail_pending`` every queued request —
        including one enqueued concurrently with shutdown — resolves
        deterministically (the old implementation could leave a future
        enqueued between shutdown-flag set and worker exit hanging
        forever under timing skew)."""
        with self._lock:
            self._closed = True
            if fail_pending:
                while self._pending:
                    r = self._pending.popleft()
                    if not r.future.done():
                        r.future.set_exception(
                            RuntimeError("serving engine is shut down"))
            self._nonempty.notify_all()
            self._space.notify_all()


class ContinuousBatcher(DynamicBatcher):
    """Iteration-level admission for the decode engine (serving/decode.py).

    The one-shot ``DynamicBatcher`` forms a batch and hands it over
    whole; a decode batch instead runs for many steps, and NEW requests
    must join it at the next step boundary rather than waiting for the
    running batch to drain.  So instead of ``next_batch()`` this front
    door exposes ``admit(limit)`` — a non-blocking pop of up to
    ``limit`` requests, called by the decode loop between steps —
    while keeping the parent's admission control (bounded queue,
    block/shed overload policy), queued-deadline fail-fast, and
    injectable clock.  Requests carry an opaque ``payload`` (the
    generation spec) instead of an input array.
    """

    def submit_request(self, payload, slo_ms: Optional[float] = None,
                       deadline: Optional[float] = None) -> Future:
        """Enqueue one decode request; same admission semantics as
        ``DynamicBatcher.submit`` (shed raises ``OverloadedError``
        synchronously, closed fails the future deterministically)."""
        fut: Future = Future()
        now = self.clock()
        dl = deadline if deadline is not None else now + (
            slo_ms if slo_ms is not None else self.slo_ms) / 1000.0
        with self._lock:
            if self._closed:
                fut.set_exception(RuntimeError("serving engine is shut down"))
                return fut
            if self._draining:
                if self.metrics:
                    self.metrics.inc("shed")
                raise OverloadedError(
                    "admission stopped: engine is draining (preemption "
                    "notice)")
            if len(self._pending) >= self.max_queue:
                if self.admission == "shed":
                    if self.metrics:
                        self.metrics.inc("shed")
                    raise OverloadedError(
                        f"admission queue full ({self.max_queue} requests); "
                        "policy=shed")
                while (len(self._pending) >= self.max_queue
                       and not self._closed and not self._draining):
                    self._space.wait(timeout=0.1)
                if self._closed:
                    fut.set_exception(
                        RuntimeError("serving engine is shut down"))
                    return fut
                if self._draining:
                    if self.metrics:
                        self.metrics.inc("shed")
                    raise OverloadedError(
                        "admission stopped: engine is draining (preemption "
                        "notice)")
            r = _Request(np.empty((1, 0), np.float32), fut, now, dl)
            r.payload = payload
            self._pending.append(r)
            self._nonempty.notify()
        return fut

    def admit(self, limit: int) -> List[_Request]:
        """Pop up to ``limit`` queued requests (0 when idle) — called at
        every decode-step boundary.  Expired requests fail fast first,
        exactly as in the one-shot path."""
        if limit <= 0:
            return []
        with self._lock:
            self._expire_locked(self.clock())
            out: List[_Request] = []
            while self._pending and len(out) < limit:
                out.append(self._pending.popleft())
            if out:
                self._space.notify_all()
            return out

    def requeue_front(self, r: _Request) -> None:
        """Put a request back at the head of the queue — admission
        raced ahead of capacity (no free pages/slot) or its replica
        crashed mid-decode and it has retry budget left."""
        with self._lock:
            if self._closed:
                if not r.future.done():
                    r.future.set_exception(
                        RuntimeError("serving engine is shut down"))
                return
            self._pending.appendleft(r)
            self._nonempty.notify()

    def wait_for_work(self, timeout: float = 0.05) -> bool:
        """Park the decode loop until a request is queued (or timeout /
        close).  Returns True when work is pending."""
        with self._lock:
            if self._pending or self._closed:
                return bool(self._pending)
            self._nonempty.wait(timeout=timeout)
            return bool(self._pending)
