"""Deadline-aware dynamic micro-batcher with per-tenant fair-share lanes.

Replaces the fixed-poll drain of the old ``parallel/inference.py``
worker (``queue.get(timeout=queue_timeout_s)`` per item — a latency
floor under EVERY request, and a throughput stall whenever the queue
briefly empties) with an event-driven close: a batch closes the moment

  * queued rows reach ``max_batch`` (never overshooting it — the old
    drain bucketed on the TOTAL queued rows, so a 33-row drain at
    ``max_batch=32`` ran an unbucketed 33-row program; here drains are
    split at ``max_batch`` BEFORE bucketing), or
  * waiting any longer would eat into the oldest request's deadline:
    close time = earliest deadline − the EMA device time of the bucket
    the batch would run in (seeded by AOT warmup, see engine.load()).

Requests carry their own deadline (default: submit + SLO budget).  A
request whose deadline passes while still queued fails fast with
``DeadlineExceededError`` instead of returning a stale result.

Admission control: the queue is bounded (``max_queue`` requests) with a
configurable overload policy — ``"block"`` (backpressure the caller) or
``"shed"`` (raise ``OverloadedError`` immediately) — so overload
degrades predictably instead of growing an unbounded queue until OOM.

Multi-tenancy (serving/tenancy.py): requests are tenant-tagged and the
queue is a set of PER-TENANT LANES drained by stride scheduling — the
scheduler always pops from the non-empty lane with the smallest virtual
time ``served_rows / weight`` — so a bursting tenant's backlog queues
behind its own lane, never in front of a victim tenant's requests.
Per-tenant quotas (concurrent cap, QPS bucket) are checked-and-charged
atomically at submit; a tenant over quota sheds with the typed
``TenantOverloadedError`` carrying the tenant and its shed count.
Untagged traffic rides the anonymous lane (weight 1.0) and behaves
exactly as the pre-tenancy FIFO.  Requests also carry an optional
``model`` tag; a batch never mixes models (the engine executes one
model version per batch — the no-version-mixing contract extended to
the zoo).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Deque, Dict, List, Optional, Sequence

import numpy as np

from ..obs import trace as obs_trace

ADMISSION_POLICIES = ("block", "shed")

_ANY_MODEL = object()      # sentinel: lane selection unconstrained


class DeadlineExceededError(RuntimeError):
    """The request's deadline passed before a device slot freed up —
    the caller's SLO is already blown, so the result would be stale."""


class OverloadedError(RuntimeError):
    """The admission queue is full and the policy is ``shed`` — retry
    with backoff or route to another replica group."""


class _Request:
    __slots__ = ("x", "rows", "future", "t_submit", "deadline",
                 "retries", "tried", "payload", "tenant", "model")

    def __init__(self, x: np.ndarray, future: Future, t_submit: float,
                 deadline: float, tenant: str = "",
                 model: Optional[str] = None):
        self.x = x
        self.rows = int(x.shape[0])
        self.future = future
        self.t_submit = t_submit
        self.deadline = deadline
        self.retries = 0          # failure-isolation retries consumed
        self.tried = set()        # replica indices that failed this request
        self.payload = None       # decode-path request spec (ContinuousBatcher)
        self.tenant = tenant      # "" = the anonymous lane
        self.model = model        # None = the engine's default model


def pow2_buckets(max_batch: int) -> List[int]:
    """1, 2, 4, ... up to and including ``max_batch``."""
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return sorted(set(out))


class DynamicBatcher:
    """Bounded request queue + deadline-aware batch former.

    One or more worker/dispatcher threads call :meth:`next_batch`; any
    number of caller threads call :meth:`submit`.  ``clock`` is
    injectable (monotonic seconds) so deadline logic is testable
    without sleeping.  ``tenants`` (a ``tenancy.TenantTable``) arms
    per-tenant admission quotas and weighted-fair lane scheduling;
    without it every request rides the anonymous lane — byte-identical
    to the pre-tenancy behavior.
    """

    def __init__(self, max_batch: int = 32, slo_ms: float = 50.0,
                 bucket_sizes: Optional[Sequence[int]] = None,
                 max_queue: int = 1024, admission: str = "block",
                 max_wait_ms: Optional[float] = None,
                 metrics=None, clock=time.monotonic, tenants=None):
        if admission not in ADMISSION_POLICIES:
            raise ValueError(f"admission must be one of "
                             f"{ADMISSION_POLICIES}, got {admission!r}")
        if max_batch < 1 or max_queue < 1 or slo_ms <= 0:
            raise ValueError("max_batch/max_queue must be >=1, slo_ms > 0")
        self.max_batch = int(max_batch)
        self.slo_ms = float(slo_ms)
        # batch-forming window: at LOW load a batch must not sit waiting
        # for companions until its deadline-slack runs out (that would
        # make p50 == SLO); the oldest request waits at most this long
        # before the batch closes.  The deadline-slack close below stays
        # the binding constraint whenever it is tighter.
        self.max_wait_ms = (float(max_wait_ms) if max_wait_ms is not None
                            else min(5.0, self.slo_ms / 10.0))
        self.buckets = (sorted(set(int(b) for b in bucket_sizes))
                        if bucket_sizes else pow2_buckets(max_batch))
        self.max_queue = int(max_queue)
        self.admission = admission
        self.metrics = metrics
        self.clock = clock
        self.tenants = tenants
        # tenant -> FIFO lane; drained by stride scheduling over _pass
        # (virtual time = rows served / weight).  A new lane joins at
        # the minimum live pass so it neither starves nor is starved.
        self._lanes: Dict[str, Deque[_Request]] = {}
        self._pass: Dict[str, float] = {}
        self._n_pending = 0
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._space = threading.Condition(self._lock)
        self._closed = False
        self._draining = False
        # bucket -> EMA device ms; the exec budget subtracted from the
        # oldest deadline when deciding how long a batch may keep filling
        self._exec_ema_ms: Dict[int, float] = {}

    # -- shape buckets -----------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest configured bucket >= n; oversized requests (> the
        largest bucket) get the next power of two — they run, but pay
        their own compile (engine metrics count them as unwarmed)."""
        for b in self.buckets:
            if n <= b:
                return b
        b = self.buckets[-1]
        while b < n:
            b *= 2
        return b

    def observe_exec_ms(self, bucket: int, ms: float, alpha: float = 0.3) -> None:
        prev = self._exec_ema_ms.get(bucket)
        self._exec_ema_ms[bucket] = (ms if prev is None
                                     else alpha * ms + (1 - alpha) * prev)

    def _exec_budget_ms(self, rows: int) -> float:
        """Expected device time for a batch of ``rows`` — the slack we
        must keep in hand when deciding to wait for more requests.
        Unmeasured buckets assume a quarter of the SLO."""
        ema = self._exec_ema_ms.get(self.bucket_for(min(rows, self.max_batch)))
        return ema if ema is not None else self.slo_ms * 0.25

    # -- tenant lanes ------------------------------------------------------

    def _count_shed(self, tenant: str) -> None:
        if self.metrics:
            self.metrics.inc("shed", tenant=tenant or None)

    def _weight_of(self, tenant: str) -> float:
        if not tenant or self.tenants is None:
            return 1.0
        return self.tenants.weight(tenant)

    def _append_locked(self, r: _Request, front: bool = False) -> None:
        lane = self._lanes.get(r.tenant)
        if lane is None:
            lane = self._lanes[r.tenant] = deque()
            live = [p for t, p in self._pass.items() if self._lanes.get(t)]
            self._pass[r.tenant] = max(self._pass.get(r.tenant, 0.0),
                                       min(live, default=0.0))
        if front:
            lane.appendleft(r)
        else:
            lane.append(r)
        self._n_pending += 1

    def _iter_pending(self):
        for lane in self._lanes.values():
            yield from lane

    def _next_lane_locked(self, model=_ANY_MODEL) -> Optional[str]:
        """Stride scheduling: the non-empty lane with the smallest
        virtual time whose head matches ``model`` (batches never mix
        models)."""
        best = None
        best_pass = None
        for t, lane in self._lanes.items():
            if not lane:
                continue
            if model is not _ANY_MODEL and lane[0].model != model:
                continue
            p = self._pass.get(t, 0.0)
            if best_pass is None or p < best_pass:
                best, best_pass = t, p
        return best

    def _pop_one_locked(self, tenant: str) -> _Request:
        r = self._lanes[tenant].popleft()
        self._n_pending -= 1
        self._pass[tenant] = (self._pass.get(tenant, 0.0)
                              + r.rows / self._weight_of(tenant))
        return r

    # -- submission --------------------------------------------------------

    def _admission_locked(self, fut: Future, tenant: str,
                          model: Optional[str]) -> bool:
        """Every admission gate, under ``self._lock``: closed fails the
        future deterministically (returns False — do not enqueue);
        draining and quota exhaustion shed by RAISING; True means the
        caller must enqueue.  On True with a tenant, the concurrent
        slot is already charged and its release is chained to the
        future — the engine invariant (every future resolves) makes
        the release exactly-once."""
        if self._closed:
            fut.set_exception(RuntimeError("serving engine is shut down"))
            return False
        if self._draining:
            self._count_shed(tenant)
            raise OverloadedError(
                "admission stopped: engine is draining (preemption "
                "notice)")
        if self._n_pending >= self.max_queue:
            if self.admission == "shed":
                self._count_shed(tenant)
                raise OverloadedError(
                    f"admission queue full ({self.max_queue} requests); "
                    "policy=shed")
            while (self._n_pending >= self.max_queue
                   and not self._closed and not self._draining):
                self._space.wait(timeout=0.1)
            if self._closed:
                fut.set_exception(
                    RuntimeError("serving engine is shut down"))
                return False
            if self._draining:
                self._count_shed(tenant)
                raise OverloadedError(
                    "admission stopped: engine is draining (preemption "
                    "notice)")
        if self.tenants is not None and tenant:
            if not self.tenants.try_admit(tenant, model, now=self.clock()):
                if self.tenants.admission_for(tenant, model) == "block":
                    # poll-with-timeout: quota releases happen on other
                    # threads' done-callbacks, which cannot notify this
                    # condition — the 50ms cap bounds staleness
                    while (not self._closed and not self._draining
                           and not self.tenants.try_admit(
                               tenant, model, now=self.clock())):
                        self._space.wait(timeout=0.05)
                    if self._closed:
                        fut.set_exception(
                            RuntimeError("serving engine is shut down"))
                        return False
                    if self._draining:
                        self._count_shed(tenant)
                        raise OverloadedError(
                            "admission stopped: engine is draining "
                            "(preemption notice)")
                else:
                    self._count_shed(tenant)
                    raise self.tenants.shed(tenant, model)
            fut.add_done_callback(
                lambda f, t=tenant: self.tenants.release(t))
        return True

    def _resolve_deadline(self, now: float, slo_ms: Optional[float],
                          deadline: Optional[float], tenant: str,
                          model: Optional[str]) -> float:
        if deadline is not None:
            return deadline
        if slo_ms is None and tenant and self.tenants is not None:
            slo_ms = self.tenants.slo_ms_for(tenant, model)
        return now + (slo_ms if slo_ms is not None else self.slo_ms) / 1000.0

    def submit(self, x: np.ndarray, slo_ms: Optional[float] = None,
               deadline: Optional[float] = None,
               tenant: Optional[str] = None,
               model: Optional[str] = None) -> Future:
        """Enqueue one request; returns its Future.  Shedding raises
        ``OverloadedError`` (the tenant-quota flavor carries the
        tenant) synchronously; a closed batcher fails the future
        deterministically (never a silent hang)."""
        x = np.asarray(x)
        if x.ndim < 1 or x.shape[0] < 1:
            raise ValueError(f"request must have a leading batch axis, "
                             f"got shape {x.shape}")
        tenant = tenant or ""
        fut: Future = Future()
        now = self.clock()
        dl = self._resolve_deadline(now, slo_ms, deadline, tenant, model)
        with self._lock:
            if not self._admission_locked(fut, tenant, model):
                return fut
            self._append_locked(_Request(x, fut, now, dl, tenant, model))
            self._nonempty.notify()
        return fut

    def qsize(self) -> int:
        with self._lock:
            return self._n_pending

    def tenant_qsize(self, tenant: str) -> int:
        with self._lock:
            lane = self._lanes.get(tenant or "")
            return len(lane) if lane else 0

    # -- batch formation ---------------------------------------------------

    def _expire_locked(self, now: float) -> None:
        """Fail-fast every queued request whose deadline already passed."""
        if not self._n_pending:
            return
        expired = 0
        for t, lane in self._lanes.items():
            if not lane or all(r.deadline >= now for r in lane):
                continue
            keep: Deque[_Request] = deque()
            lane_expired = 0
            for r in lane:
                if r.deadline < now:
                    lane_expired += 1
                    if not r.future.done():
                        r.future.set_exception(DeadlineExceededError(
                            f"request waited "
                            f"{(now - r.t_submit) * 1e3:.1f}ms in queue, "
                            f"past its "
                            f"{(r.deadline - r.t_submit) * 1e3:.0f}"
                            "ms deadline"))
                else:
                    keep.append(r)
            if lane_expired:
                self._lanes[t] = keep
                expired += lane_expired
                if self.metrics:
                    self.metrics.inc("deadline_missed", lane_expired,
                                     tenant=t or None)
        if expired:
            self._n_pending -= expired
            self._space.notify_all()

    def _pop_batch_locked(self) -> List[_Request]:
        batch: List[_Request] = []
        rows = 0
        model = _ANY_MODEL
        while self._n_pending:
            t = self._next_lane_locked(model)
            if t is None:       # only other-model lanes remain
                break
            head = self._lanes[t][0]
            # split at max_batch BEFORE bucketing; a single oversized
            # request still goes alone (it cannot be split)
            if batch and rows + head.rows > self.max_batch:
                break
            r = self._pop_one_locked(t)
            batch.append(r)
            rows += r.rows
            model = r.model     # the batch never mixes models
            if rows >= self.max_batch:
                break
        self._space.notify_all()
        if batch:
            # post-hoc span: batch formation ran from the oldest member's
            # submit until this close decision
            obs_trace.complete_at(
                "serve/batch_form", min(r.t_submit for r in batch),
                self.clock(), cat="serve", rows=rows, n_requests=len(batch))
        return batch

    def next_batch(self) -> Optional[List[_Request]]:
        """Block until a batch closes; None once closed AND drained."""
        with self._lock:
            while True:
                now = self.clock()
                self._expire_locked(now)
                if not self._n_pending:
                    if self._closed:
                        return None
                    # pure event wait — the timeout only bounds how stale
                    # a missed notify can leave us (defensive, not a poll)
                    self._nonempty.wait(timeout=0.5)
                    continue
                total = sum(r.rows for r in self._iter_pending())
                if total >= self.max_batch or self._closed:
                    return self._pop_batch_locked()
                earliest = min(r.deadline for r in self._iter_pending())
                oldest = min(r.t_submit for r in self._iter_pending())
                t_close = min(
                    oldest + self.max_wait_ms / 1000.0,
                    earliest - self._exec_budget_ms(total) / 1000.0)
                if now >= t_close:
                    return self._pop_batch_locked()
                # cap the wait so deadline expiry scans keep running even
                # if no new request arrives to notify us
                self._nonempty.wait(timeout=min(t_close - now, 0.05))

    # -- shutdown ----------------------------------------------------------

    def begin_drain(self) -> None:
        """Stop admission without failing anything queued: every
        SUBSEQUENT submit sheds (``OverloadedError``, → HTTP 429)
        regardless of the admission policy — block-policy callers
        already waiting for space are woken and shed too — while queued
        requests keep draining through ``next_batch``/``admit``.  The
        graceful-preemption front half: shed new, finish in-flight,
        then ``close()``.  Idempotent."""
        with self._lock:
            self._draining = True
            self._space.notify_all()

    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def close(self, fail_pending: bool = True) -> None:
        """Idempotent.  With ``fail_pending`` every queued request —
        including one enqueued concurrently with shutdown — resolves
        deterministically (the old implementation could leave a future
        enqueued between shutdown-flag set and worker exit hanging
        forever under timing skew)."""
        with self._lock:
            self._closed = True
            if fail_pending:
                for lane in self._lanes.values():
                    while lane:
                        r = lane.popleft()
                        if not r.future.done():
                            r.future.set_exception(
                                RuntimeError("serving engine is shut down"))
                self._n_pending = 0
            self._nonempty.notify_all()
            self._space.notify_all()


class ContinuousBatcher(DynamicBatcher):
    """Iteration-level admission for the decode engine (serving/decode.py).

    The one-shot ``DynamicBatcher`` forms a batch and hands it over
    whole; a decode batch instead runs for many steps, and NEW requests
    must join it at the next step boundary rather than waiting for the
    running batch to drain.  So instead of ``next_batch()`` this front
    door exposes ``admit(limit)`` — a non-blocking pop of up to
    ``limit`` requests, called by the decode loop between steps —
    while keeping the parent's admission control (bounded queue,
    block/shed overload policy, per-tenant quotas + fair-share lanes),
    queued-deadline fail-fast, and injectable clock.  Requests carry an
    opaque ``payload`` (the generation spec) instead of an input array.
    """

    def submit_request(self, payload, slo_ms: Optional[float] = None,
                       deadline: Optional[float] = None,
                       tenant: Optional[str] = None,
                       model: Optional[str] = None) -> Future:
        """Enqueue one decode request; same admission semantics as
        ``DynamicBatcher.submit`` (shed raises ``OverloadedError``
        synchronously, closed fails the future deterministically)."""
        tenant = tenant or ""
        fut: Future = Future()
        now = self.clock()
        dl = self._resolve_deadline(now, slo_ms, deadline, tenant, model)
        with self._lock:
            if not self._admission_locked(fut, tenant, model):
                return fut
            r = _Request(np.empty((1, 0), np.float32), fut, now, dl,
                         tenant, model)
            r.payload = payload
            self._append_locked(r)
            self._nonempty.notify()
        return fut

    def admit(self, limit: int,
              token_budget: Optional[int] = None) -> List[_Request]:
        """Pop up to ``limit`` queued requests (0 when idle) — called at
        every decode-step boundary, in fair-share lane order (decode
        slots each carry their own model tag, so one admit round MAY
        span models).  Expired requests fail fast first, exactly as in
        the one-shot path.

        ``token_budget`` is the chunked-prefill batch-formation rule
        (docs/SERVING.md "Host-overhead elimination"): stop admitting
        once the popped payloads' prompt tokens (``len(payload.prompt)``
        for payloads that carry one) would exceed the budget, so one
        admit round never enqueues more prefill work than the engine is
        willing to interleave per step — a wall of long prompts drains
        one chunk-budget's worth per round instead of all at once.  The
        head request is always admitted even when it alone exceeds the
        budget (an oversized prompt cannot be split at admission; the
        engine chunks its prefill instead), so the rule bounds pacing
        without ever starving."""
        if limit <= 0:
            return []

        def _cost(r: _Request) -> int:
            p = getattr(r.payload, "prompt", None)
            return 0 if p is None else len(p)

        with self._lock:
            self._expire_locked(self.clock())
            out: List[_Request] = []
            spent = 0
            while self._n_pending and len(out) < limit:
                t = self._next_lane_locked()
                if t is None:
                    break
                if (token_budget is not None and out
                        and spent + _cost(self._lanes[t][0]) > token_budget):
                    break
                r = self._pop_one_locked(t)
                spent += _cost(r)
                out.append(r)
            if out:
                self._space.notify_all()
            return out

    def requeue_front(self, r: _Request) -> None:
        """Put a request back at the head of its lane — admission
        raced ahead of capacity (no free pages/slot) or its replica
        crashed mid-decode and it has retry budget left.  The fair
        scheduler's charge for the pop is refunded so a requeue does
        not eat the tenant's share."""
        with self._lock:
            if self._closed:
                if not r.future.done():
                    r.future.set_exception(
                        RuntimeError("serving engine is shut down"))
                return
            self._pass[r.tenant] = (self._pass.get(r.tenant, 0.0)
                                    - r.rows / self._weight_of(r.tenant))
            self._append_locked(r, front=True)
            self._nonempty.notify()

    def wait_for_work(self, timeout: float = 0.05) -> bool:
        """Park the decode loop until a request is queued (or timeout /
        close).  Returns True when work is pending."""
        with self._lock:
            if self._n_pending or self._closed:
                return bool(self._n_pending)
            self._nonempty.wait(timeout=timeout)
            return bool(self._n_pending)
