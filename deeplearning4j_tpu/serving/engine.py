"""Replicated serving engine: dispatch, AOT warmup, hot-swap, resilience.

Topology (one Engine):

    callers ──submit──▶ DynamicBatcher ──next_batch──▶ dispatcher thread
        ──round-robin over DISPATCHABLE replicas (per-replica in-flight
          cap, health + circuit breaker)──▶ replica queues
        ──▶ replica threads (one per replica, params device_put onto
            jax.local_devices()[i]) ──▶ futures resolve
    supervisor thread ──▶ detects crashed/hung replica threads, completes
        or retries their in-flight futures, respawns + re-warms them

Model versions are immutable `_ModelVersion` snapshots: every batch
reads the CURRENT version exactly once (under the version lock) before
executing, so a batch can never mix parameters from two versions.
`swap_model` builds + warms the incoming version first, flips the
pointer atomically, then blocks until every in-flight batch on the old
version has drained — the registry's hot-swap contract.

AOT warmup (`load()`): every (bucket, dtype) pair is compiled on every
replica's device at model-load time, so no user request pays an XLA
compile.  The compile counter is the jitted forward's own executable
cache (`_cache_size()`); tests assert it does not grow while serving.
Models without a jit-able forward (ComputationGraph, arbitrary duck-
typed `.output` models) fall back to calling `model.output` — warmup
still pre-triggers their compiles, only the counter is unavailable.

Failure model (docs/SERVING.md "Failure model"):

- Every submitted future ALWAYS completes — with a result or a typed
  error, never stranded.  A replica thread that dies or hangs mid-batch
  is detected by the supervisor (bounded ``forward_timeout_s``, the
  serving analog of ElasticTrainer's step watchdog), its in-flight
  requests are retried once on a DIFFERENT replica when their deadline
  still allows (else failed with `ReplicaCrashError`/`ReplicaHungError`),
  and the replica is respawned with an AOT re-warm pass (zero new
  compiles — executables live in the version's jit cache).
- K consecutive replica failures trip a per-replica circuit breaker:
  the dispatcher routes around the replica until ``breaker_cooldown_s``
  passes, then half-opens it (one probe batch; success closes it).
- A batch whose forward produces non-finite outputs is BISECTED and
  re-executed to isolate the poison request(s): co-batched requests
  still succeed, the poison request fails with `PoisonInputError`.
- Canary promotion (`run_canary`, driven by
  ``registry.set_alias(..., canary=frac)``) mirrors a deterministic
  fraction of live batches to the incoming version as shadow traffic,
  compares error rate / p99 / prediction divergence against the
  incumbent over a decision window, and either completes the hot-swap
  or auto-rolls-back.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import trace as obs_trace
from .batcher import DeadlineExceededError, DynamicBatcher, _Request
from .metrics import ServingMetrics

_SENTINEL = object()

# engine-side serving chaos kinds (string literals, not an import — the
# chaos module lives in parallel/ and must stay import-independent of
# serving/; parallel.chaos.FaultKind defines the same constants)
_CHAOS_CRASH = "replica_crash"
_CHAOS_HANG = "replica_hang"


class ReplicaCrashError(RuntimeError):
    """The replica thread executing this request died; the request's
    deadline (or retry budget) did not allow a retry elsewhere."""


class ReplicaHungError(RuntimeError):
    """The replica executing this request exceeded ``forward_timeout_s``
    and was abandoned; no retry was possible within the deadline."""


class PoisonInputError(RuntimeError):
    """This request's input made the forward produce non-finite outputs
    (isolated by batch bisection — co-batched requests were unaffected)."""


class ServingUnavailableError(RuntimeError):
    """No dispatchable replica (all dead or circuit-broken)."""


class ModelNotLoadedError(RuntimeError):
    """The request named a model this engine does not currently place —
    either it was never added or the placement controller evicted it.
    Retryable at fleet level: the router re-routes to a host that does
    place it (or demand-loads it, serving/placement.py)."""


def _fail_safe(fut: Future, exc: BaseException) -> None:
    if not fut.done():
        try:
            fut.set_exception(exc)
        except InvalidStateError:   # lost a completion race — resolved
            pass


def _set_safe(fut: Future, value) -> bool:
    if not fut.done():
        try:
            fut.set_result(value)
            return True
        except InvalidStateError:   # lost a completion race — resolved
            pass
    return False


def _jitable(model) -> bool:
    return (hasattr(model, "_apply_layers") and hasattr(model, "params")
            and hasattr(model, "state"))


def _bundle_key(bucket: int, dtype: str) -> str:
    """Warmup-bundle key for one predict executable (serving/warmcache.py)."""
    return f"predict|b={bucket}|dtype={dtype}"


class _ModelVersion:
    """Immutable serving snapshot of one model version: the jitted
    forward + per-replica device-resident params/state, plus the drain
    bookkeeping for hot-swap."""

    def __init__(self, model, tag: str, devices: Sequence[Any]):
        import jax

        self.model = model
        self.tag = tag
        self.fwd = None
        # AOT executables for the lead device, keyed by _bundle_key —
        # populated at warmup (bundle deserialize or explicit
        # lower().compile()); replicas on other devices use the jit fwd
        self.aot: Dict[str, Any] = {}
        self.params: List[Any] = []
        self.state: List[Any] = []
        self.active = 0          # batches currently executing on this version
        self.retired = False
        self.drained = threading.Event()
        if _jitable(model):
            def fwd(params, state, x):
                y = model._apply_layers(params, state, x, train=False,
                                        rng=None, mask=None)[0]
                return y
            self.fwd = jax.jit(fwd)
            # replica loads ride the shared async-put helper: params
            # already resident on the target device pass through instead
            # of re-staging through host (same seam the input pipeline's
            # DevicePrefetchIterator uses for batches)
            from ..datasets.device_prefetch import device_put_batch
            for d in devices:
                self.params.append(device_put_batch(model.params, d))
                self.state.append(device_put_batch(model.state, d))

    def cache_size(self) -> Optional[int]:
        if self.fwd is None:
            return None
        try:
            return int(self.fwd._cache_size())
        except Exception:
            return None


class _ModelEntry:
    """One NAMED model placed on a multi-model engine: its current
    version plus the per-model warmup state (the AOT/bundle and
    zero-serve-time-compiles contracts hold per model, not per host).
    The engine's constructor model stays the DEFAULT model outside this
    table; placement adds/evicts entries at runtime."""

    __slots__ = ("version", "example_shape", "warm_dtypes", "warmed",
                 "last_used")

    def __init__(self, version: _ModelVersion,
                 example_shape: Tuple[int, ...],
                 warm_dtypes: Tuple[str, ...]):
        self.version = version
        self.example_shape = example_shape
        self.warm_dtypes = warm_dtypes
        self.warmed: set = set()       # (bucket, dtype_str) pairs
        self.last_used: Optional[float] = None


class _Execution:
    """One batch execution's claim on a model version.  ``release`` is
    idempotent so the supervisor (abandoning a hung incarnation) and the
    executing thread's ``finally`` can both call it — the version's
    active count is decremented exactly once."""

    __slots__ = ("version", "released")

    def __init__(self, version: _ModelVersion):
        self.version = version
        self.released = False


class _Replica:
    def __init__(self, idx: int, device, inflight_cap: int):
        self.idx = idx
        self.device = device
        self.queue: "queue.Queue" = queue.Queue(maxsize=max(1, inflight_cap))
        self.thread: Optional[threading.Thread] = None
        self.processed = 0
        # supervision state — all mutated under `lock`
        self.lock = threading.Lock()
        self.generation = 0              # bumped on every abandon/respawn
        self.current_batch: Optional[List[_Request]] = None
        self.busy_since: Optional[float] = None
        self.execution: Optional[_Execution] = None
        self.consecutive_failures = 0
        self.breaker_open = False
        self.breaker_open_until = 0.0
        self.respawns = 0


class _CanaryState:
    """Shadow-traffic measurement window for one canary candidate."""

    def __init__(self, version: _ModelVersion, frac: float, window: int):
        self.version = version
        self.frac = float(frac)
        self.window = int(window)
        self.lock = threading.Lock()
        self.eligible = 0
        self.mirrored = 0
        self.canary_ms: List[float] = []
        self.incumbent_ms: List[float] = []
        self.canary_errors = 0
        self.divergences: List[float] = []
        self.done = threading.Event()

    def select(self) -> bool:
        """Deterministic traffic-fraction selection: mirror batch k iff
        the integer part of k*frac advanced (so exactly ceil(frac*n) of
        the first n eligible batches mirror, no RNG)."""
        self.eligible += 1
        return (int(self.eligible * self.frac)
                > int((self.eligible - 1) * self.frac))


class Engine:
    """Production inference engine over any model with ``.output(x)``.

    Parameters
    ----------
    model: the model to serve (or use :meth:`from_registry`).
    max_batch / slo_ms / bucket_sizes / max_queue / admission: batching
        + admission control (see `serving/batcher.py`).
    replicas: engine replica count; ``-1`` = one per local device.
        Replica *i* pins its params to ``jax.local_devices()[i % n]``.
    inflight_per_replica: per-replica dispatch-queue bound — the
        round-robin dispatcher skips a replica whose queue is full.
    forward_timeout_s: if set, a replica whose batch executes longer
        than this is declared HUNG: the supervisor abandons it, retries
        its requests elsewhere, and respawns the replica (the serving
        analog of ElasticTrainer's step watchdog).  None disables hang
        detection (crash detection stays on).
    max_retries: per-request retry budget after a replica failure or a
        retryable forward error; retries go to a DIFFERENT replica when
        one is available and never launch past the request's deadline.
    breaker_threshold / breaker_cooldown_s: K consecutive failures trip
        the replica's circuit breaker (dispatch routes around it);
        after the cooldown it half-opens (one probe; success closes it).
    poison_isolation: bisect batches whose forward output is non-finite
        to isolate the poison request (co-batched requests succeed).
    chaos: an armed ``parallel.chaos.ServingChaos`` (tests/soaks only).
    """

    def __init__(self, model=None, *, registry=None, name: Optional[str] = None,
                 ref: str = "prod", max_batch: int = 32, slo_ms: float = 50.0,
                 bucket_sizes: Optional[Sequence[int]] = None,
                 replicas: int = 1, max_queue: int = 1024,
                 admission: str = "block", inflight_per_replica: int = 2,
                 max_wait_ms: Optional[float] = None,
                 metrics: Optional[ServingMetrics] = None,
                 forward_timeout_s: Optional[float] = None,
                 max_retries: int = 1, breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 1.0,
                 supervise_interval_s: float = 0.02,
                 poison_isolation: bool = True,
                 chaos=None,
                 tenants=None,
                 clock=time.monotonic):
        import jax

        if model is None:
            if registry is None or name is None:
                raise ValueError("pass a model, or registry= and name=")
            version, model = registry.resolve(name, ref)
            tag = f"{name}:v{version}"
        else:
            tag = "v0"
        self.metrics = metrics or ServingMetrics()
        self.tenants = tenants           # tenancy.TenantTable or None
        self.batcher = DynamicBatcher(
            max_batch=max_batch, slo_ms=slo_ms, bucket_sizes=bucket_sizes,
            max_queue=max_queue, admission=admission,
            max_wait_ms=max_wait_ms, metrics=self.metrics, clock=clock,
            tenants=tenants)
        self.clock = clock
        self.forward_timeout_s = forward_timeout_s
        self.max_retries = int(max_retries)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.supervise_interval_s = float(supervise_interval_s)
        self.poison_isolation = bool(poison_isolation)
        self._chaos = chaos
        devices = jax.local_devices()
        n = len(devices) if replicas in (-1, 0) else int(replicas)
        if n < 1:
            raise ValueError(f"replicas must be >=1 or -1, got {replicas}")
        self._inflight_per_replica = int(inflight_per_replica)
        self._replicas = [
            _Replica(i, devices[i % len(devices)], inflight_per_replica)
            for i in range(n)]
        self._devices = [r.device for r in self._replicas]
        self._vlock = threading.Lock()
        self._swap_lock = threading.Lock()
        self._current = _ModelVersion(model, tag, self._devices)
        # the constructor model is the DEFAULT model; placement can add
        # further named models at runtime (one engine, many models)
        self._default_name: Optional[str] = (name if registry is not None
                                             else None)
        self._named: Dict[str, _ModelEntry] = {}
        self._canary: Optional[_CanaryState] = None
        self._canary_log: List[dict] = []
        self._warmed: set = set()       # (bucket, dtype_str) pairs
        self._example_shape: Optional[Tuple[int, ...]] = None
        self._warm_dtypes: Tuple[str, ...] = ("float32",)
        self._loaded = False
        self._shutdown = False
        self._autoscaler = None             # see enable_autoscale()
        self._autoscale_interval_s = 0.25
        self._last_autoscale_t: Optional[float] = None
        self._shed_seen = 0.0
        self.batch_log: List[dict] = []  # bounded; test/debug hook
        self._log_lock = threading.Lock()
        if registry is not None and name is not None:
            registry.subscribe(
                name, ref,
                lambda version, m: self.swap_model(m, tag=f"{name}:v{version}"),
                canary=lambda version, m, **kw: self.run_canary(
                    m, tag=f"{name}:v{version}", **kw))
        for r in self._replicas:
            self._start_replica_thread(r)
        self._dispatcher = threading.Thread(target=self._dispatch_loop,
                                            daemon=True)
        self._dispatcher.start()
        self._supervisor = threading.Thread(target=self._supervise_loop,
                                            daemon=True)
        self._supervisor.start()

    @classmethod
    def from_registry(cls, registry, name: str, ref: str = "prod",
                      **kwargs) -> "Engine":
        return cls(registry=registry, name=name, ref=ref, **kwargs)

    # -- warmup ------------------------------------------------------------

    @staticmethod
    def _infer_shape_of(model) -> Optional[Tuple[int, ...]]:
        conf = getattr(model, "conf", None)
        it = getattr(conf, "input_type", None)
        if it is None:
            return None
        try:
            return tuple(it.batch_shape(1))[1:]
        except ValueError:  # variable-length recurrent input
            return None

    def _infer_example_shape(self) -> Optional[Tuple[int, ...]]:
        return self._infer_shape_of(self._current.model)

    def load(self, input_shape: Optional[Sequence[int]] = None,
             dtypes: Sequence[str] = ("float32",),
             quantize: Optional[str] = None,
             calibration_inputs=None,
             warm_bundle: Optional[str] = None) -> "Engine":
        """AOT warmup: compile every (bucket, dtype) pair on every
        replica so no user request pays a compile.  ``input_shape`` is
        the per-example shape; inferred from the model's configured
        InputType when omitted.  Warmup timings seed the batcher's
        per-bucket exec EMA (the deadline-slack close).

        ``quantize="int8"`` serves the int8 fast path (ops/quantize.py):
        the current version's Dense-style matmul weights are quantized
        per-output-channel with activation scales calibrated on
        ``calibration_inputs`` (an array or list of arrays of
        representative per-example inputs; a fixed-seed synthetic batch
        when omitted — pass real inputs for production envelopes), and
        warmup compiles the QUANTIZED executables, so the
        zero-serve-time-compiles contract covers the int8 path too.

        ``warm_bundle`` points warmup at an explicit warmup-bundle zip
        (serving/warmcache.py); omitted, the ``<checkpoint>.warm``
        convention is tried for registry-loaded models.  A usable bundle
        deserializes the AOT executables instead of compiling them — the
        zero-cold-start path; any miss silently falls back to compiling.
        The quantized path never reads a bundle (its executables differ
        from the float checkpoint's)."""
        shape = tuple(input_shape) if input_shape is not None else (
            self._infer_example_shape())
        if shape is None:
            raise ValueError(
                "cannot infer the per-example input shape from the model's "
                "configuration — pass input_shape=(...) explicitly")
        self._example_shape = shape
        self._warm_dtypes = tuple(dtypes)
        if quantize is not None:
            if quantize != "int8":
                raise ValueError(
                    f"unsupported quantize mode {quantize!r}; only 'int8'")
            from ..ops.quantize import quantize_model
            if calibration_inputs is None:
                rng = np.random.default_rng(0)
                calibration_inputs = rng.standard_normal(
                    (max(self.batcher.buckets),) + shape).astype(np.float32)
            qm = quantize_model(self._current.model, calibration_inputs)
            with self._vlock:
                self._current = _ModelVersion(
                    qm, self._current.tag + "+int8", self._devices)
        self._warm_version(self._current, warm_bundle=warm_bundle,
                           use_bundle=quantize is None)
        self._loaded = True
        return self

    def _load_bundle_for(self, v: _ModelVersion,
                         explicit_path: Optional[str] = None) -> dict:
        """Resolve + load the warmup bundle for a version: an explicit
        path wins, else the ``<checkpoint>.warm`` convention via the
        provenance the registry stamps on loaded models.  Returns {} on
        any miss (warmcache's fallback-to-compile contract)."""
        if v.fwd is None:
            return {}
        from . import warmcache
        path = explicit_path
        if path is None:
            ckpt = getattr(v.model, "_checkpoint_path", None)
            if ckpt:
                path = warmcache.bundle_path_for(ckpt)
        if not path:
            return {}
        return warmcache.load_bundle(path)

    def _warm_version(self, v: _ModelVersion,
                      warm_bundle: Optional[str] = None,
                      use_bundle: bool = True,
                      shape: Optional[Tuple[int, ...]] = None,
                      dtypes: Optional[Tuple[str, ...]] = None,
                      warmed: Optional[set] = None) -> None:
        """Warm one version over every (bucket, dtype) pair.  With no
        overrides this warms the DEFAULT model (engine-level shape/
        dtypes/warmed set); ``add_model``/per-model swap pass the named
        entry's own triple so the contracts hold per model."""
        shape = shape if shape is not None else self._example_shape
        dtypes = dtypes if dtypes is not None else self._warm_dtypes
        warmed = warmed if warmed is not None else self._warmed
        if shape is None:
            return
        bundle = (self._load_bundle_for(v, warm_bundle) if use_bundle
                  else {})
        for dtype in dtypes:
            for b in self.batcher.buckets:
                dts = str(np.dtype(dtype))
                x = np.zeros((b,) + shape, dtype=dtype)
                t0 = self.clock()
                with obs_trace.span("serve/warmup", cat="serve", bucket=b,
                                    dtype=dts, tag=v.tag):
                    self._warm_pair(v, b, dts, x, bundle)
                self.metrics.inc("warmup_seconds_total",
                                 self.clock() - t0)
                # the warm passes above include the compile (or bundle
                # deserialize), so re-run replica 0 once for a clean
                # per-bucket EMA seed
                t1 = self.clock()
                np.asarray(self._run_forward(v, 0, x))
                self.batcher.observe_exec_ms(b, (self.clock() - t1) * 1e3)
                warmed.add((b, dts))

    def _warm_pair(self, v: _ModelVersion, b: int, dts: str, x: np.ndarray,
                   bundle: dict) -> None:
        """Warm one (bucket, dtype) pair: install the lead-device AOT
        executable (bundle hit, else explicit lower+compile) and run it
        on every replica (non-lead-device replicas warm the jit path)."""
        if v.fwd is not None:
            key = _bundle_key(b, dts)
            if key not in v.aot:
                hit = bundle.get(key)
                if hit is not None:
                    v.aot[key] = hit
                    self.metrics.inc("bundle_hits")
                else:
                    v.aot[key] = v.fwd.lower(
                        v.params[0], v.state[0], x).compile()
                    self.metrics.inc("bundle_misses")
        for i in range(len(self._replicas)):
            np.asarray(self._run_forward(v, i, x))

    def _rewarm_replica(self, idx: int) -> None:
        """Re-warm one (respawned) replica: run every warmed (bucket,
        dtype) pair once on its device, for the current version, every
        NAMED model's version, and any canary.  Executables already
        live in each version's jit cache, so this is a cache-hit pass —
        zero new compiles (the respawn contract) — that doubles as a
        health probe."""
        with self._vlock:
            triples = [(self._current, self._example_shape,
                        self._warm_dtypes)]
            triples += [(e.version, e.example_shape, e.warm_dtypes)
                        for e in self._named.values()]
        can = self._canary
        if can is not None:
            triples.append((can.version, self._example_shape,
                            self._warm_dtypes))
        for v, shape, dtypes in triples:
            if shape is None:
                continue
            for dtype in dtypes:
                for b in self.batcher.buckets:
                    x = np.zeros((b,) + shape, dtype=dtype)
                    np.asarray(self._run_forward(v, idx, x))

    def compile_cache_size(self, model: Optional[str] = None) -> Optional[int]:
        """Number of compiled executables backing one model's forward
        (None for non-jit-able models): the jit cache PLUS the AOT warm
        executables — the default model unless ``model`` names a placed
        one.  After warmup this must not grow while serving
        bucket-shaped requests — the zero-compiles-at-serve-time
        contract, held PER MODEL (also across replica respawns,
        autoscale births, and placement evict/reload cycles)."""
        with self._vlock:
            if model is None or model == self._default_name:
                v = self._current
            else:
                entry = self._named.get(model)
                if entry is None:
                    raise ModelNotLoadedError(
                        f"model {model!r} is not placed on this host")
                v = entry.version
            jit_n = v.cache_size()
            if jit_n is None:
                return None
            return jit_n + len(v.aot)

    def save_warmup_bundle(self, path: Optional[str] = None,
                           model: Optional[str] = None) -> str:
        """Write one model's AOT executables as a warmup bundle
        (serving/warmcache.py) — the default model unless ``model``
        names a placed one.  Default path: the ``<checkpoint>.warm``
        convention next to the version's checkpoint zip
        (registry-loaded models carry their provenance).  A fresh
        process passes the bundle to ``load(warm_bundle=)`` /
        ``add_model(warm_bundle=)`` — or just registry-loads the same
        checkpoint — and warms from disk instead of compiling."""
        from . import warmcache
        with self._vlock:
            if model is None or model == self._default_name:
                v = self._current
            else:
                entry = self._named.get(model)
                if entry is None:
                    raise ModelNotLoadedError(
                        f"model {model!r} is not placed on this host")
                v = entry.version
        if not v.aot:
            raise RuntimeError(
                "nothing to bundle — load() the engine first (non-jit-able "
                "models have no AOT executables)")
        if path is None:
            ckpt = getattr(v.model, "_checkpoint_path", None)
            if not ckpt:
                raise ValueError(
                    "model has no checkpoint provenance (not registry-"
                    "loaded); pass path= explicitly")
            path = warmcache.bundle_path_for(ckpt)
        return warmcache.save_bundle(path, v.tag, dict(v.aot))

    # -- request path ------------------------------------------------------

    def output(self, x, slo_ms: Optional[float] = None,
               model: Optional[str] = None,
               tenant: Optional[str] = None) -> np.ndarray:
        """Submit one request (leading batch axis); blocks for the result."""
        return self.output_async(x, slo_ms=slo_ms, model=model,
                                 tenant=tenant).result()

    def output_async(self, x, slo_ms: Optional[float] = None,
                     model: Optional[str] = None,
                     tenant: Optional[str] = None) -> Future:
        """``model`` routes to a placed named model (None = the default
        model this engine was constructed with); ``tenant`` tags the
        request for fair-share scheduling and quota accounting.  An
        unplaced model fails fast with :class:`ModelNotLoadedError`
        (retryable at fleet level — the router demand-loads)."""
        if model is not None:
            with self._vlock:
                if model == self._default_name:
                    model = None        # default lane: no fragmentation
                elif model not in self._named:
                    f: Future = Future()
                    f.set_exception(ModelNotLoadedError(
                        f"model {model!r} is not placed on this host"))
                    return f
        return self.batcher.submit(np.asarray(x), slo_ms=slo_ms,
                                   tenant=tenant, model=model)

    # -- dispatch ----------------------------------------------------------

    def _dispatchable(self, r: _Replica, now: float) -> bool:
        """Health gate for routing: thread alive AND breaker closed (or
        past its cooldown — half-open: the next batch is the probe)."""
        with r.lock:
            if r.thread is None or not r.thread.is_alive():
                return False
            if r.breaker_open and now < r.breaker_open_until:
                return False
        return True

    def _dispatch_loop(self) -> None:
        rr = 0
        while True:
            batch = self.batcher.next_batch()
            if batch is None:
                break
            rr = self._place_batch(batch, rr)
        for r in list(self._replicas):
            r.queue.put(_SENTINEL)

    def _place_batch(self, batch: List[_Request], rr: int) -> int:
        """Round-robin placement skipping unhealthy/full replicas; waits
        (expiring deadlines) when nothing is dispatchable, fails the
        batch deterministically on shutdown.  The replica list is
        re-snapshotted every round — the autoscaler grows and shrinks
        it concurrently."""
        while True:
            if self._shutdown:
                for req in batch:
                    _fail_safe(req.future,
                               RuntimeError("serving engine is shut down"))
                return rr
            now = self.clock()
            batch = self._expire_batch(batch, now)
            if not batch:
                return rr
            reps = list(self._replicas)
            n = len(reps)
            candidates = [reps[(rr + k) % n] for k in range(n)]
            dispatchable = [c for c in candidates
                            if self._dispatchable(c, now)]
            for c in dispatchable:
                try:
                    c.queue.put_nowait(batch)
                    return (c.idx + 1) % n
                except queue.Full:
                    continue
            if dispatchable:
                # every healthy replica at its in-flight cap: backpressure
                c = dispatchable[0]
                try:
                    c.queue.put(batch, timeout=0.1)
                    return (c.idx + 1) % n
                except queue.Full:
                    continue
            # nothing dispatchable (all dead / breakers open): wait for
            # the supervisor to respawn, expiring deadlines meanwhile
            time.sleep(0.005)

    def _expire_batch(self, batch: List[_Request],
                      now: float) -> List[_Request]:
        live = []
        expired = 0
        for r in batch:
            if r.future.done():
                continue
            if r.deadline < now:
                expired += 1
                _fail_safe(r.future, DeadlineExceededError(
                    f"deadline passed after "
                    f"{(now - r.t_submit) * 1e3:.1f}ms"))
            else:
                live.append(r)
        if expired:
            self.metrics.inc("deadline_missed", expired)
        return live

    def _start_replica_thread(self, r: _Replica) -> None:
        with r.lock:
            gen = r.generation
            t = threading.Thread(target=self._replica_loop, args=(r, gen),
                                 daemon=True)
            r.thread = t
        t.start()

    def _replica_loop(self, replica: _Replica, gen: int) -> None:
        while True:
            item = replica.queue.get()
            if item is _SENTINEL:
                break
            with replica.lock:
                if gen != replica.generation:
                    # abandoned while blocked in get(): hand the batch to
                    # the live incarnation and exit
                    replica.queue.put(item)
                    return
                replica.current_batch = item
                replica.busy_since = self.clock()
            if self._chaos is not None:
                kinds = self._chaos.pop_batch(replica.idx)
                if _CHAOS_CRASH in kinds:
                    # simulated thread death: exit with the batch still in
                    # limbo (current_batch set, futures unresolved) — the
                    # supervisor must detect, complete/retry, and respawn
                    return
                if _CHAOS_HANG in kinds:
                    self._chaos.sleep_fn(self._chaos.hang_seconds)
                    with replica.lock:
                        if gen != replica.generation:
                            return      # supervisor abandoned us mid-hang
            self._execute(item, replica, gen)
            with replica.lock:
                if gen != replica.generation:
                    return              # abandoned mid-execute; successor runs
                replica.current_batch = None
                replica.busy_since = None
                replica.processed += 1

    # -- execution ---------------------------------------------------------

    def _ensure_replica_params(self, v: _ModelVersion,
                               replica_idx: int) -> None:
        if replica_idx < len(v.params):
            return
        # a version built before an autoscale birth has no device-resident
        # params for the new replica yet — extend on first touch
        from ..datasets.device_prefetch import device_put_batch
        with self._vlock:
            while len(v.params) <= replica_idx:
                d = self._replicas[len(v.params)].device
                v.params.append(device_put_batch(v.model.params, d))
                v.state.append(device_put_batch(v.model.state, d))

    def _run_forward(self, v: _ModelVersion, replica_idx: int, xs: np.ndarray):
        if v.fwd is not None:
            self._ensure_replica_params(v, replica_idx)
            if v.aot and self._replicas[replica_idx].device == self._devices[0]:
                # AOT executables are compiled for the lead device; only
                # replicas pinned there may run them (np inputs are
                # uncommitted, params are per-replica device-resident)
                exe = v.aot.get(_bundle_key(xs.shape[0], str(xs.dtype)))
                if exe is not None:
                    return exe(v.params[replica_idx], v.state[replica_idx], xs)
            return v.fwd(v.params[replica_idx], v.state[replica_idx], xs)
        out = v.model.output(xs)
        return out[0] if isinstance(out, list) else out

    def _forward_padded(self, v: _ModelVersion, replica_idx: int,
                        reqs: List[_Request],
                        count_unwarmed: bool = True,
                        warmed: Optional[set] = None) -> Tuple[np.ndarray,
                                                               int, int, int]:
        """Concat + pad ``reqs`` to their bucket, run the forward, and
        return (out rows for the requests, rows, bucket, padded)."""
        xs = (reqs[0].x if len(reqs) == 1
              else np.concatenate([r.x for r in reqs], axis=0))
        rows = xs.shape[0]
        bucket = self.batcher.bucket_for(rows)
        padded = bucket - rows
        if padded:
            pad = np.zeros((padded,) + xs.shape[1:], xs.dtype)
            xs = np.concatenate([xs, pad], axis=0)
        warm_set = self._warmed if warmed is None else warmed
        if (count_unwarmed and self._loaded
                and (bucket, str(xs.dtype)) not in warm_set):
            self.metrics.inc("unwarmed_serves")
        out = np.asarray(self._run_forward(v, replica_idx, xs))
        return out[:rows], rows, bucket, padded

    def _execute(self, batch: List[_Request], replica: _Replica,
                 gen: int) -> None:
        now = self.clock()
        live = self._expire_batch(batch, now)
        if not live:
            return
        for r in live:
            self.metrics.queue_wait.record((now - r.t_submit) * 1e3)
            # post-hoc span: the request sat queued from submit to here
            # (the engine clock and the trace clock are both monotonic)
            obs_trace.complete_at("serve/queue_wait", r.t_submit, now,
                                  cat="serve", rows=r.rows)
        # batches are model-homogeneous (the batcher never mixes models
        # in one batch), so the whole batch reads ONE version snapshot
        model_name = live[0].model
        with self._vlock:
            if model_name is None:
                v = self._current
                warmed = self._warmed
            else:
                entry = self._named.get(model_name)
                if entry is None:
                    # evicted between admission and execution: typed
                    # failure, retryable at fleet level (demand reload)
                    err = ModelNotLoadedError(
                        f"model {model_name!r} was evicted from this host")
                    for r in live:
                        _fail_safe(r.future, err)
                    return
                entry.last_used = now
                v = entry.version
                warmed = entry.warmed
            v.active += 1
        ex = _Execution(v)
        with replica.lock:
            replica.execution = ex
        t0 = self.clock()
        try:
            out, rows, bucket, padded = self._forward_padded(
                v, replica.idx, live, warmed=warmed)
            device_ms = (self.clock() - t0) * 1e3
            obs_trace.complete_at("serve/forward", t0, self.clock(),
                                  cat="serve", replica=replica.idx,
                                  rows=rows, bucket=bucket, tag=v.tag)
            if self.poison_isolation and not np.isfinite(out).all():
                # non-finite forward: bisect to isolate the poison
                # request(s) so co-batched requests still succeed
                self._isolate_poison(v, replica, live, precomputed=out)
                self.metrics.record_batch(len(live), rows, padded, device_ms)
                return
        except Exception as e:
            self.metrics.inc("errors")
            self._retry_or_fail(live, replica.idx, e)
            return
        finally:
            self._release(ex)
            with replica.lock:
                replica.execution = None
        with replica.lock:
            abandoned = gen != replica.generation
            if not abandoned and (replica.consecutive_failures
                                  or replica.breaker_open):
                # a completed batch is the half-open probe succeeding:
                # close the breaker, forget the failure streak
                replica.consecutive_failures = 0
                replica.breaker_open = False
        if abandoned:
            # the supervisor already redispatched this batch to another
            # replica — discard this late result (futures are one-shot,
            # so even a completion race is harmless)
            return
        self.batcher.observe_exec_ms(bucket, device_ms)
        self.metrics.record_batch(len(live), rows, padded, device_ms)
        with self._log_lock:
            self.batch_log.append({"tag": v.tag, "n_requests": len(live),
                                   "rows": rows, "padded": padded,
                                   "replica": replica.idx})
            if len(self.batch_log) > 4096:
                del self.batch_log[:2048]
        done = self.clock()
        ofs = 0
        for r in live:
            _set_safe(r.future, out[ofs:ofs + r.rows])
            ofs += r.rows
            self.metrics.e2e.record((done - r.t_submit) * 1e3)
            obs_trace.complete_at("serve/request", r.t_submit, done,
                                  cat="serve", rows=r.rows,
                                  retries=r.retries)
        # the batch-execution span wraps the forward on this replica's
        # thread track (queue_wait spans end where this one begins)
        obs_trace.complete_at("serve/batch", now, done, cat="serve",
                              replica=replica.idx, n_requests=len(live),
                              rows=rows, padded=padded, tag=v.tag)
        can = self._canary
        if (can is not None and not can.done.is_set()
                and model_name is None):
            # canary mirrors DEFAULT-model traffic only: a named model's
            # batches never shadow another model's candidate (canary and
            # rollback stay per-model, never crossing tenants)
            self._mirror_canary(can, replica, live, out, device_ms)

    def _isolate_poison(self, v: _ModelVersion, replica: _Replica,
                        reqs: List[_Request],
                        precomputed: Optional[np.ndarray] = None) -> None:
        """Bisection: resolve every request in ``reqs`` with a result or
        `PoisonInputError`.  Re-executes halves (bucket-shaped, so still
        zero new compiles) until each non-finite output is pinned to a
        single request; sub-batches that come back finite complete all
        their requests — one poison request cannot fail its batch-mates.
        Works even for models where a poison row contaminates the whole
        batch output (e.g. cross-batch normalization)."""
        if precomputed is not None:
            out = precomputed
        else:
            out, _, _, _ = self._forward_padded(v, replica.idx, reqs,
                                                count_unwarmed=False)
        ofs = 0
        finite = []
        for r in reqs:
            finite.append(bool(np.isfinite(out[ofs:ofs + r.rows]).all()))
            ofs += r.rows
        if all(finite):
            done = self.clock()
            ofs = 0
            for r in reqs:
                _set_safe(r.future, out[ofs:ofs + r.rows])
                ofs += r.rows
                self.metrics.e2e.record((done - r.t_submit) * 1e3)
            return
        if len(reqs) == 1:
            self.metrics.inc("poison_isolated")
            _fail_safe(reqs[0].future, PoisonInputError(
                "request input produced non-finite outputs (isolated by "
                "batch bisection)"))
            return
        mid = max(1, len(reqs) // 2)
        self._isolate_poison(v, replica, reqs[:mid])
        self._isolate_poison(v, replica, reqs[mid:])

    # -- failure isolation + retry -----------------------------------------

    def _release(self, ex: _Execution) -> None:
        with self._vlock:
            if ex.released:
                return
            ex.released = True
            v = ex.version
            v.active -= 1
            if v.retired and v.active == 0:
                v.drained.set()

    def _retry_or_fail(self, reqs: List[_Request], failed_idx: int,
                       error: BaseException) -> None:
        """Deadline-aware bounded retry: requests with retry budget AND
        enough deadline slack for another execution are redispatched to
        a different replica; the rest fail with the typed error.  Every
        future resolves — nothing is ever stranded."""
        now = self.clock()
        retry = []
        for r in reqs:
            if r.future.done():
                continue
            budget_s = self.batcher._exec_budget_ms(r.rows) / 1000.0
            if (r.retries < self.max_retries
                    and r.deadline - now > budget_s):
                r.retries += 1
                r.tried.add(failed_idx)
                retry.append(r)
            else:
                _fail_safe(r.future, error)
        if not retry:
            return
        self.metrics.inc("retries", len(retry))
        obs_trace.instant("serve/retry", cat="serve", n_requests=len(retry),
                          failed_replica=failed_idx,
                          error=type(error).__name__)
        self._redispatch(retry)

    def _redispatch(self, reqs: List[_Request]) -> None:
        """Place retried requests on a healthy replica, preferring one
        that has not already failed them; expires deadlines while
        waiting and fails deterministically on shutdown.  Under backlog
        the dispatcher refills replica queues the instant a slot frees,
        so a pure ``put_nowait`` poll can starve — use a short BLOCKING
        put (enters the queue's waiter list, competing fairly) and drop
        the different-replica preference after a few failed rounds
        rather than starve the retry until its deadline."""
        tried = set()
        for r in reqs:
            tried |= r.tried
        rounds = 0
        while True:
            if self._shutdown:
                for r in reqs:
                    _fail_safe(r.future,
                               RuntimeError("serving engine is shut down"))
                return
            now = self.clock()
            reqs = self._expire_batch(reqs, now)
            if not reqs:
                return
            candidates = [c for c in self._replicas
                          if self._dispatchable(c, now)]
            preferred = ([c for c in candidates
                          if c.idx not in tried] if rounds < 3 else []) \
                or candidates
            for c in preferred[1:]:
                try:
                    c.queue.put_nowait(list(reqs))
                    return
                except queue.Full:
                    continue
            if preferred:
                try:
                    preferred[0].queue.put(list(reqs), timeout=0.05)
                    return
                except queue.Full:
                    pass
            else:
                time.sleep(0.005)
            rounds += 1

    # -- supervision -------------------------------------------------------

    def _supervise_loop(self) -> None:
        while not self._shutdown:
            time.sleep(self.supervise_interval_s)
            if self._shutdown:
                return
            now = self.clock()
            for r in list(self._replicas):
                if self._shutdown:
                    return
                self._check_replica(r, now)
            self._autoscale_tick(now)

    # -- autoscaling --------------------------------------------------------

    def enable_autoscale(self, autoscaler=None, *,
                         min_replicas: Optional[int] = None,
                         max_replicas: Optional[int] = None,
                         interval_s: float = 0.25, **knobs) -> "Engine":
        """Arm load-driven replica autoscaling (docs/SERVING.md "Cold
        start & autoscaling").  The supervisor loop ticks a
        ``ReplicaAutoscaler`` every ``interval_s`` with queue depth,
        in-flight count, and the shed-counter delta; +1 births a replica
        warmed from the AOT cache (zero new compiles), -1 retires the
        last replica once idle.  Pass a pre-built controller for full
        control (tests inject fake clocks), or knobs for the default one
        (``up_load``/``down_load``/``up_ticks``/``down_ticks``/
        ``cooldown_s``)."""
        from .autoscale import ReplicaAutoscaler
        if autoscaler is None:
            n = len(self._replicas)
            autoscaler = ReplicaAutoscaler(
                min_replicas=n if min_replicas is None else int(min_replicas),
                max_replicas=n if max_replicas is None else int(max_replicas),
                clock=self.clock, **knobs)
        self._autoscale_interval_s = float(interval_s)
        self._shed_seen = self.metrics.counter_value("shed")
        self._autoscaler = autoscaler
        return self

    def _autoscale_tick(self, now: float) -> None:
        a = self._autoscaler
        if a is None or not self._loaded or self._shutdown:
            return
        if (self._last_autoscale_t is not None
                and now - self._last_autoscale_t < self._autoscale_interval_s):
            return
        self._last_autoscale_t = now
        shed = self.metrics.counter_value("shed")
        shed_delta = shed - self._shed_seen
        self._shed_seen = shed
        reps = list(self._replicas)
        inflight = 0
        for r in reps:
            inflight += r.queue.qsize()
            with r.lock:
                if r.busy_since is not None:
                    inflight += 1
        decision = a.observe(self.batcher.qsize(), inflight, len(reps),
                             shed_delta=int(shed_delta))
        if decision > 0:
            self._add_replica()
        elif decision < 0:
            self._retire_replica()

    def _add_replica(self) -> None:
        """Autoscale birth: a new replica on the next local device,
        warmed from the already-compiled executables (AOT/jit cache-hit
        pass — zero new compiles, the same contract as a respawn)."""
        import jax

        devices = jax.local_devices()
        idx = len(self._replicas)
        device = devices[idx % len(devices)]
        r = _Replica(idx, device, self._inflight_per_replica)
        with obs_trace.span("serve/scale_up", cat="serve", replica=idx):
            self._start_replica_thread(r)
            self._replicas.append(r)
            self._devices.append(device)
            self._rewarm_replica(idx)
        self.metrics.inc("scale_ups")

    def _retire_replica(self) -> None:
        """Autoscale retire: remove the LAST replica (keeping indices
        dense for round-robin) once it is live and idle; a busy one is
        left for the next tick.  Anything a racing dispatch parked
        behind the sentinel is redispatched — nothing strands."""
        if len(self._replicas) <= 1:
            return
        r = self._replicas[-1]
        with r.lock:
            alive = r.thread is not None and r.thread.is_alive()
            busy = r.busy_since is not None
        if not alive or busy or not r.queue.empty():
            return
        with obs_trace.span("serve/scale_down", cat="serve", replica=r.idx):
            # unroute first (the dispatcher snapshots the list), then
            # sentinel the thread out
            self._replicas.pop()
            self._devices.pop()
            r.queue.put(_SENTINEL)
            if r.thread is not None:
                r.thread.join(timeout=5.0)
            leftovers = []
            while True:
                try:
                    item = r.queue.get_nowait()
                except queue.Empty:
                    break
                if item is not _SENTINEL:
                    leftovers.append(item)
            for item in leftovers:
                self._redispatch([q for q in item if not q.future.done()])
        self.metrics.inc("scale_downs")

    def _check_replica(self, r: _Replica, now: float) -> None:
        with r.lock:
            if self._shutdown:
                # a sentinel-exited thread is a clean shutdown, not a
                # crash — never respawn into a closing engine
                return
            crashed = (r.thread is not None and not r.thread.is_alive())
            hung = (not crashed
                    and self.forward_timeout_s is not None
                    and r.busy_since is not None
                    and now - r.busy_since > self.forward_timeout_s)
            if not crashed and not hung:
                return
            batch = r.current_batch
            ex = r.execution
            r.current_batch = None
            r.busy_since = None
            r.execution = None
            r.generation += 1       # any late wake-up is now a no-op
            r.consecutive_failures += 1
            r.respawns += 1
            opened = False
            if (r.consecutive_failures >= self.breaker_threshold
                    and not r.breaker_open):
                r.breaker_open = True
                opened = True
            if r.breaker_open:
                r.breaker_open_until = now + self.breaker_cooldown_s
        if ex is not None:
            self._release(ex)       # idempotent vs the hung finally
        self.metrics.inc("replica_crashes" if crashed else "replica_hangs")
        obs_trace.instant(
            "serve/replica_crash" if crashed else "serve/replica_hang",
            cat="serve", replica=r.idx,
            in_flight=len(batch) if batch else 0)
        if opened:
            self.metrics.inc("circuit_opens")
            obs_trace.instant("serve/circuit_open", cat="serve",
                              replica=r.idx)
        # respawn FIRST so the retry path has a live target even with a
        # single replica...
        self._start_replica_thread(r)
        error: RuntimeError = (ReplicaCrashError(
            f"replica {r.idx} thread died mid-batch")
            if crashed else ReplicaHungError(
                f"replica {r.idx} exceeded forward_timeout_s="
                f"{self.forward_timeout_s}"))
        # ...then recover OFF the supervisor thread: re-warm and retry
        # can block (device time, backpressured queues) and the
        # supervisor must keep scanning — a crash recovery that stalls
        # hang detection on the OTHER replica would strand its batch
        # until the hang resolves itself
        threading.Thread(target=self._recover_replica,
                         args=(r, batch, error), daemon=True).start()

    def _recover_replica(self, r: _Replica, batch: Optional[List[_Request]],
                         error: RuntimeError) -> None:
        try:
            with obs_trace.span("serve/respawn", cat="serve", replica=r.idx):
                self._rewarm_replica(r.idx)   # cache-hit pass: 0 compiles
        except Exception as e:
            # the replica will fail its next batch and re-enter the
            # supervisor; the breaker bounds how often we retry — but a
            # failed re-warm must be visible on the timeline, not silent
            self.metrics.inc("respawn_failures")
            obs_trace.instant("serve/respawn_failed", cat="serve",
                              replica=r.idx,
                              error=f"{type(e).__name__}: {e}")
        self.metrics.inc("replica_respawns")
        if batch:
            self._retry_or_fail(
                [q for q in batch if not q.future.done()], r.idx, error)

    def health_snapshot(self) -> dict:
        """Per-replica health (healthy/degraded/dead) + readiness.
        ``status``: "ok" (all healthy), "degraded" (≥1 dispatchable),
        "unready" (none dispatchable — or shut down)."""
        now = self.clock()
        reps = []
        n_healthy = n_dispatchable = 0
        for r in self._replicas:
            with r.lock:
                alive = r.thread is not None and r.thread.is_alive()
                cooling = r.breaker_open and now < r.breaker_open_until
                if not alive or cooling:
                    h = "dead"
                elif r.breaker_open or r.consecutive_failures:
                    h = "degraded"      # half-open / recent failures
                else:
                    h = "healthy"
                reps.append({
                    "replica": r.idx, "health": h, "alive": alive,
                    "busy": r.busy_since is not None,
                    "consecutive_failures": r.consecutive_failures,
                    "breaker_open": r.breaker_open,
                    "respawns": r.respawns, "processed": r.processed,
                })
            if h == "healthy":
                n_healthy += 1
            if h != "dead":
                n_dispatchable += 1
        if self._shutdown or n_dispatchable == 0:
            status = "unready"
        elif n_healthy == len(self._replicas):
            status = "ok"
        else:
            status = "degraded"
        return {"status": status, "ready": status != "unready",
                "replicas": reps}

    # -- canary ------------------------------------------------------------

    def _mirror_canary(self, can: _CanaryState, replica: _Replica,
                       reqs: List[_Request], incumbent_out: np.ndarray,
                       incumbent_ms: float) -> None:
        """Shadow one live batch to the canary version AFTER the
        incumbent's results are already delivered (user latency is never
        behind the canary forward) and record the comparison."""
        with can.lock:
            if can.done.is_set() or not can.select():
                return
        err = False
        div = None
        t0 = self.clock()
        try:
            out, _, _, _ = self._forward_padded(can.version, replica.idx,
                                                reqs, count_unwarmed=False)
            if not np.isfinite(out).all():
                err = True
            elif out.shape == incumbent_out.shape:
                div = float(np.mean(np.abs(out - incumbent_out)))
        except Exception:
            err = True
        ms = (self.clock() - t0) * 1e3
        self.metrics.inc("canary_mirrored_batches")
        with can.lock:
            can.mirrored += 1
            can.canary_ms.append(ms)
            can.incumbent_ms.append(incumbent_ms)
            if err:
                can.canary_errors += 1
            if div is not None:
                can.divergences.append(div)
            if can.mirrored >= can.window:
                can.done.set()

    def run_canary(self, model, tag: Optional[str] = None, *,
                   frac: float = 0.2, window: int = 32,
                   timeout_s: float = 60.0, max_error_rate: float = 0.0,
                   p99_factor: float = 3.0,
                   max_divergence: Optional[float] = None) -> dict:
        """Canary the incoming ``model`` against the incumbent: mirror a
        deterministic ``frac`` of live batches to it as shadow traffic,
        compare error rate / p99 exec / prediction divergence over a
        ``window`` of mirrored batches, then either complete the
        hot-swap (promote) or auto-roll-back.  Blocks until the window
        fills or ``timeout_s`` passes (an unfilled window is a rollback
        — an unjudged version is never promoted).  Returns the decision
        dict; usually driven via ``registry.set_alias(..., canary=)``."""
        if self._canary is not None:
            raise RuntimeError("a canary evaluation is already running")
        # graftcheck: disable=GC201 (wall-anchor: human-facing default tag names WHEN the canary started; never feeds math or replay)
        nv = _ModelVersion(model, tag or f"canary@{time.time():.0f}",
                           self._devices)
        if self._loaded:
            self._warm_version(nv)
        can = _CanaryState(nv, frac, window)
        self._canary = can
        try:
            can.done.wait(timeout_s)
        finally:
            self._canary = None     # no more mirrors record into `can`
        with can.lock:
            mirrored = can.mirrored
            errors = can.canary_errors
            c_ms = list(can.canary_ms)
            i_ms = list(can.incumbent_ms)
            divs = list(can.divergences)
        err_rate = errors / mirrored if mirrored else None
        p99_c = float(np.percentile(c_ms, 99)) if c_ms else None
        p99_i = float(np.percentile(i_ms, 99)) if i_ms else None
        mean_div = float(np.mean(divs)) if divs else None
        reasons = []
        if mirrored < window:
            reasons.append(f"window incomplete ({mirrored}/{window} "
                           "mirrored batches before timeout)")
        if err_rate is not None and err_rate > max_error_rate:
            reasons.append(f"error rate {err_rate:.3f} > {max_error_rate}")
        # the 1ms floor keeps clock-resolution noise on sub-ms forwards
        # from vetoing a healthy canary (sub-3ms p99 is never a regression)
        if (p99_c is not None and p99_i is not None
                and p99_c > p99_factor * max(p99_i, 1.0)):
            reasons.append(f"p99 {p99_c:.2f}ms > {p99_factor}x incumbent "
                           f"{p99_i:.2f}ms")
        if (max_divergence is not None and mean_div is not None
                and mean_div > max_divergence):
            reasons.append(f"prediction divergence {mean_div:.4f} > "
                           f"{max_divergence}")
        promote = not reasons
        decision = {
            "candidate": nv.tag, "incumbent": self.current_tag,
            "promote": promote, "reasons": reasons,
            "mirrored_batches": mirrored, "error_rate": err_rate,
            "canary_p99_ms": round(p99_c, 3) if p99_c is not None else None,
            "incumbent_p99_ms": (round(p99_i, 3) if p99_i is not None
                                 else None),
            "mean_divergence": (round(mean_div, 6) if mean_div is not None
                                else None),
        }
        obs_trace.instant("serve/canary_decision", cat="serve",
                          candidate=nv.tag, promote=promote,
                          reasons=list(reasons))
        if promote:
            self._swap_version(nv)      # already warmed: no extra compiles
            self.metrics.inc("canary_promotions")
        else:
            self.metrics.inc("canary_rollbacks")
        with self._log_lock:
            self._canary_log.append(decision)
        return decision

    # -- hot swap ----------------------------------------------------------

    def swap_model(self, model, tag: Optional[str] = None,
                   warm_bundle: Optional[str] = None,
                   name: Optional[str] = None) -> str:
        """Atomic hot-swap: build + AOT-warm the new version, flip the
        current pointer, then drain — block until every in-flight batch
        on the old version completes before releasing it.  In-flight
        requests keep their version; a batch never mixes two versions.
        Returns the retired version's tag (rollback = swap back, or an
        alias move in the registry).

        ``name`` scopes the swap to one placed named model (None or the
        default name = the default model) — swaps never cross models, so
        a rollout of tenant A's model cannot disturb tenant B's.

        ``warm_bundle`` (or the incoming model's registry-stamped
        ``<checkpoint>.warm`` provenance) lets the warm pass deserialize
        AOT executables instead of compiling — a mid-traffic swap warms
        from disk; any miss falls back to compile silently."""
        # graftcheck: disable=GC201 (wall-anchor: human-facing default tag names WHEN the swap happened; never feeds math or replay)
        nv = _ModelVersion(model, tag or f"swap@{time.time():.0f}",
                           self._devices)
        if name is not None and name != self._default_name:
            with self._vlock:
                entry = self._named.get(name)
                if entry is None:
                    raise ModelNotLoadedError(
                        f"model {name!r} is not placed on this host")
            if self._loaded:
                self._warm_version(nv, warm_bundle=warm_bundle,
                                   shape=entry.example_shape,
                                   dtypes=entry.warm_dtypes,
                                   warmed=entry.warmed)
            with self._swap_lock:
                with self._vlock:
                    old = entry.version
                    entry.version = nv
                    old.retired = True
                    if old.active == 0:
                        old.drained.set()
                old.drained.wait()
                self.metrics.inc("swaps")
                obs_trace.instant("serve/swap", cat="serve",
                                  incoming=nv.tag, retired=old.tag,
                                  model=name)
                return old.tag
        if self._loaded:
            self._warm_version(nv, warm_bundle=warm_bundle)
        return self._swap_version(nv)

    def _swap_version(self, nv: _ModelVersion) -> str:
        with self._swap_lock:
            with self._vlock:
                old = self._current
                self._current = nv
                old.retired = True
                if old.active == 0:
                    old.drained.set()
            old.drained.wait()
            self.metrics.inc("swaps")
            obs_trace.instant("serve/swap", cat="serve", incoming=nv.tag,
                              retired=old.tag)
            return old.tag

    @property
    def current_tag(self) -> str:
        with self._vlock:
            return self._current.tag

    # -- multi-model placement ---------------------------------------------

    def add_model(self, name: str, model, *,
                  input_shape: Optional[Sequence[int]] = None,
                  dtypes: Sequence[str] = ("float32",),
                  warm_bundle: Optional[str] = None,
                  tag: Optional[str] = None) -> "Engine":
        """Place a NAMED model on this engine alongside the default one.
        The new model is fully AOT-warmed (bundle-first via
        ``warm_bundle`` or its ``<checkpoint>.warm`` provenance) BEFORE
        it becomes routable, so the zero-serve-time-compiles contract
        holds per model from its first request.  Placement load is a
        scheduling decision, not an outage: existing models keep serving
        throughout."""
        if not name:
            raise ValueError("model name must be non-empty")
        with self._vlock:
            if name == self._default_name or name in self._named:
                raise ValueError(f"model {name!r} is already placed")
        shape = (tuple(input_shape) if input_shape is not None
                 else self._infer_shape_of(model))
        if shape is None:
            raise ValueError(
                f"cannot infer the per-example input shape for {name!r} — "
                "pass input_shape=(...) explicitly")
        v = _ModelVersion(model, tag or name, self._devices)
        entry = _ModelEntry(v, shape, tuple(dtypes))
        entry.last_used = self.clock()
        t0 = self.clock()
        if self._loaded:
            self._warm_version(v, warm_bundle=warm_bundle,
                               shape=entry.example_shape,
                               dtypes=entry.warm_dtypes,
                               warmed=entry.warmed)
        with self._vlock:
            self._named[name] = entry
        self.metrics.inc("model_loads")
        obs_trace.instant("serve/model_load", cat="serve", model=name,
                          tag=v.tag,
                          warm_ms=(self.clock() - t0) * 1e3)
        return self

    def add_model_from_registry(self, registry, name: str,
                                ref: str = "prod", *,
                                input_shape: Optional[Sequence[int]] = None,
                                dtypes: Sequence[str] = ("float32",),
                                warm_bundle: Optional[str] = None,
                                subscribe: bool = False) -> "Engine":
        """Registry-backed :meth:`add_model`: resolves ``name@ref``,
        places it under ``name`` with the registry tag convention
        (``name:vN``), and warms from the checkpoint's warm bundle when
        one exists.  ``subscribe=True`` additionally wires alias moves
        to per-model hot-swaps — leave False under a placement
        controller (it owns reload/evict and an alias callback firing
        after an eviction would dangle)."""
        version, model = registry.resolve(name, ref)
        self.add_model(name, model, input_shape=input_shape,
                       dtypes=dtypes, warm_bundle=warm_bundle,
                       tag=f"{name}:v{version}")
        if subscribe:
            registry.subscribe(
                name, ref,
                lambda ver, m: self.swap_model(m, tag=f"{name}:v{ver}",
                                               name=name))
        return self

    def remove_model(self, name: str, timeout: float = 30.0) -> bool:
        """Evict a named model: unroute it (new requests fail typed →
        the fleet re-routes), then drain — wait for in-flight batches on
        its version to complete so eviction can never strand a future or
        mix versions.  Returns False if the model was not placed.  The
        default model cannot be evicted (use ``begin_drain`` to retire a
        whole host)."""
        if name == self._default_name:
            raise ValueError(
                f"model {name!r} is this engine's default model and "
                "cannot be evicted; drain the host instead")
        with self._vlock:
            entry = self._named.pop(name, None)
            if entry is None:
                return False
            v = entry.version
            v.retired = True
            if v.active == 0:
                v.drained.set()
        v.drained.wait(timeout)
        self.metrics.inc("model_evictions")
        obs_trace.instant("serve/model_evict", cat="serve", model=name,
                          tag=v.tag)
        return True

    def has_model(self, name: Optional[str]) -> bool:
        """True when this engine currently places ``name`` (None and
        the default model's own name are always served)."""
        if name is None:
            return True
        with self._vlock:
            return name == self._default_name or name in self._named

    def placed_models(self) -> Dict[str, str]:
        """name → current version tag for every model this engine
        places (the default model under its registry name, or "" when
        it was constructed from a bare model)."""
        with self._vlock:
            out = {self._default_name if self._default_name is not None
                   else "": self._current.tag}
            for name, e in self._named.items():
                out[name] = e.version.tag
            return out

    def model_last_used(self, name: str) -> Optional[float]:
        """Engine-clock stamp of the last batch executed for a named
        model (None = never, or not placed) — the placement
        controller's idle-eviction signal."""
        with self._vlock:
            e = self._named.get(name)
            return e.last_used if e is not None else None

    # -- lifecycle ---------------------------------------------------------

    def metrics_snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        snap["model"] = self.current_tag
        snap["models"] = self.placed_models()
        if self.tenants is not None:
            snap["tenants"] = self.tenants.snapshot()
        snap["replicas"] = len(self._replicas)
        snap["queue_depth"] = self.batcher.qsize()
        snap["buckets"] = list(self.batcher.buckets)
        snap["compile_cache_size"] = self.compile_cache_size()
        snap["health"] = self.health_snapshot()
        with self._log_lock:
            snap["canary_decisions"] = list(self._canary_log[-8:])
        return snap

    def begin_drain(self) -> None:
        """Graceful-preemption front half (docs/SERVING.md "Graceful
        SIGTERM drain"): stop admission — every subsequent submission
        sheds (``OverloadedError`` → HTTP 429) — while queued and
        in-flight batches complete normally.  Call ``shutdown()`` once
        ``queue_depth`` reaches zero or the grace budget runs out."""
        self.batcher.begin_drain()
        self.metrics.inc("drains")
        obs_trace.instant("serve/drain", cat="serve")

    def shutdown(self, timeout: float = 5.0) -> None:
        """Deterministic shutdown: every request — queued, in a replica
        queue, or submitted concurrently with this call — resolves
        (result or RuntimeError), never hangs."""
        if self._shutdown:
            return
        self._shutdown = True
        self.batcher.close(fail_pending=True)
        self._dispatcher.join(timeout=timeout)
        for r in self._replicas:
            if r.thread:
                r.thread.join(timeout=timeout)
        if self._supervisor:
            self._supervisor.join(timeout=timeout)
        # anything still sitting in replica queues (threads died, or the
        # sentinel raced a late dispatch) fails deterministically —
        # including an in-flight batch of a dead/hung replica
        for r in self._replicas:
            with r.lock:
                stranded = r.current_batch
                r.current_batch = None
            if stranded:
                for req in stranded:
                    _fail_safe(req.future,
                               RuntimeError("serving engine is shut down"))
            while True:
                try:
                    item = r.queue.get_nowait()
                except queue.Empty:
                    break
                if item is _SENTINEL:
                    continue
                for req in item:
                    _fail_safe(req.future,
                               RuntimeError("serving engine is shut down"))
