"""Replicated serving engine: dispatch, AOT warmup, hot-swap.

Topology (one Engine):

    callers ──submit──▶ DynamicBatcher ──next_batch──▶ dispatcher thread
        ──round-robin (per-replica in-flight cap)──▶ replica queues
        ──▶ replica threads (one per replica, params device_put onto
            jax.local_devices()[i]) ──▶ futures resolve

Model versions are immutable `_ModelVersion` snapshots: every batch
reads the CURRENT version exactly once (under the version lock) before
executing, so a batch can never mix parameters from two versions.
`swap_model` builds + warms the incoming version first, flips the
pointer atomically, then blocks until every in-flight batch on the old
version has drained — the registry's hot-swap contract.

AOT warmup (`load()`): every (bucket, dtype) pair is compiled on every
replica's device at model-load time, so no user request pays an XLA
compile.  The compile counter is the jitted forward's own executable
cache (`_cache_size()`); tests assert it does not grow while serving.
Models without a jit-able forward (ComputationGraph, arbitrary duck-
typed `.output` models) fall back to calling `model.output` — warmup
still pre-triggers their compiles, only the counter is unavailable.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .batcher import DeadlineExceededError, DynamicBatcher, _Request
from .metrics import ServingMetrics

_SENTINEL = object()


def _jitable(model) -> bool:
    return (hasattr(model, "_apply_layers") and hasattr(model, "params")
            and hasattr(model, "state"))


class _ModelVersion:
    """Immutable serving snapshot of one model version: the jitted
    forward + per-replica device-resident params/state, plus the drain
    bookkeeping for hot-swap."""

    def __init__(self, model, tag: str, devices: Sequence[Any]):
        import jax

        self.model = model
        self.tag = tag
        self.fwd = None
        self.params: List[Any] = []
        self.state: List[Any] = []
        self.active = 0          # batches currently executing on this version
        self.retired = False
        self.drained = threading.Event()
        if _jitable(model):
            def fwd(params, state, x):
                y = model._apply_layers(params, state, x, train=False,
                                        rng=None, mask=None)[0]
                return y
            self.fwd = jax.jit(fwd)
            # replica loads ride the shared async-put helper: params
            # already resident on the target device pass through instead
            # of re-staging through host (same seam the input pipeline's
            # DevicePrefetchIterator uses for batches)
            from ..datasets.device_prefetch import device_put_batch
            for d in devices:
                self.params.append(device_put_batch(model.params, d))
                self.state.append(device_put_batch(model.state, d))

    def cache_size(self) -> Optional[int]:
        if self.fwd is None:
            return None
        try:
            return int(self.fwd._cache_size())
        except Exception:
            return None


class _Replica:
    def __init__(self, idx: int, device, inflight_cap: int):
        self.idx = idx
        self.device = device
        self.queue: "queue.Queue" = queue.Queue(maxsize=max(1, inflight_cap))
        self.thread: Optional[threading.Thread] = None
        self.processed = 0


class Engine:
    """Production inference engine over any model with ``.output(x)``.

    Parameters
    ----------
    model: the model to serve (or use :meth:`from_registry`).
    max_batch / slo_ms / bucket_sizes / max_queue / admission: batching
        + admission control (see `serving/batcher.py`).
    replicas: engine replica count; ``-1`` = one per local device.
        Replica *i* pins its params to ``jax.local_devices()[i % n]``.
    inflight_per_replica: per-replica dispatch-queue bound — the
        round-robin dispatcher skips a replica whose queue is full.
    """

    def __init__(self, model=None, *, registry=None, name: Optional[str] = None,
                 ref: str = "prod", max_batch: int = 32, slo_ms: float = 50.0,
                 bucket_sizes: Optional[Sequence[int]] = None,
                 replicas: int = 1, max_queue: int = 1024,
                 admission: str = "block", inflight_per_replica: int = 2,
                 max_wait_ms: Optional[float] = None,
                 metrics: Optional[ServingMetrics] = None,
                 clock=time.monotonic):
        import jax

        if model is None:
            if registry is None or name is None:
                raise ValueError("pass a model, or registry= and name=")
            version, model = registry.resolve(name, ref)
            tag = f"{name}:v{version}"
        else:
            tag = "v0"
        self.metrics = metrics or ServingMetrics()
        self.batcher = DynamicBatcher(
            max_batch=max_batch, slo_ms=slo_ms, bucket_sizes=bucket_sizes,
            max_queue=max_queue, admission=admission,
            max_wait_ms=max_wait_ms, metrics=self.metrics, clock=clock)
        self.clock = clock
        devices = jax.local_devices()
        n = len(devices) if replicas in (-1, 0) else int(replicas)
        if n < 1:
            raise ValueError(f"replicas must be >=1 or -1, got {replicas}")
        self._replicas = [
            _Replica(i, devices[i % len(devices)], inflight_per_replica)
            for i in range(n)]
        self._devices = [r.device for r in self._replicas]
        self._vlock = threading.Lock()
        self._swap_lock = threading.Lock()
        self._current = _ModelVersion(model, tag, self._devices)
        self._warmed: set = set()       # (bucket, dtype_str) pairs
        self._example_shape: Optional[Tuple[int, ...]] = None
        self._warm_dtypes: Tuple[str, ...] = ("float32",)
        self._loaded = False
        self._shutdown = False
        self.batch_log: List[dict] = []  # bounded; test/debug hook
        self._log_lock = threading.Lock()
        if registry is not None and name is not None:
            registry.subscribe(
                name, ref,
                lambda version, m: self.swap_model(m, tag=f"{name}:v{version}"))
        for r in self._replicas:
            r.thread = threading.Thread(target=self._replica_loop, args=(r,),
                                        daemon=True)
            r.thread.start()
        self._dispatcher = threading.Thread(target=self._dispatch_loop,
                                            daemon=True)
        self._dispatcher.start()

    @classmethod
    def from_registry(cls, registry, name: str, ref: str = "prod",
                      **kwargs) -> "Engine":
        return cls(registry=registry, name=name, ref=ref, **kwargs)

    # -- warmup ------------------------------------------------------------

    def _infer_example_shape(self) -> Optional[Tuple[int, ...]]:
        conf = getattr(self._current.model, "conf", None)
        it = getattr(conf, "input_type", None)
        if it is None:
            return None
        try:
            return tuple(it.batch_shape(1))[1:]
        except ValueError:  # variable-length recurrent input
            return None

    def load(self, input_shape: Optional[Sequence[int]] = None,
             dtypes: Sequence[str] = ("float32",)) -> "Engine":
        """AOT warmup: compile every (bucket, dtype) pair on every
        replica so no user request pays a compile.  ``input_shape`` is
        the per-example shape; inferred from the model's configured
        InputType when omitted.  Warmup timings seed the batcher's
        per-bucket exec EMA (the deadline-slack close)."""
        shape = tuple(input_shape) if input_shape is not None else (
            self._infer_example_shape())
        if shape is None:
            raise ValueError(
                "cannot infer the per-example input shape from the model's "
                "configuration — pass input_shape=(...) explicitly")
        self._example_shape = shape
        self._warm_dtypes = tuple(dtypes)
        self._warm_version(self._current)
        self._loaded = True
        return self

    def _warm_version(self, v: _ModelVersion) -> None:
        if self._example_shape is None:
            return
        for dtype in self._warm_dtypes:
            for b in self.batcher.buckets:
                x = np.zeros((b,) + self._example_shape, dtype=dtype)
                t0 = self.clock()
                for i in range(len(self._replicas)):
                    np.asarray(self._run_forward(v, i, x))
                # amortized per-replica steady-ish cost; the first call
                # includes the compile, so only the LAST replica's time
                # would be clean — re-run replica 0 once for the EMA seed
                t0 = self.clock()
                np.asarray(self._run_forward(v, 0, x))
                self.batcher.observe_exec_ms(b, (self.clock() - t0) * 1e3)
                self._warmed.add((b, str(np.dtype(dtype))))

    def compile_cache_size(self) -> Optional[int]:
        """Number of compiled executables backing the CURRENT version's
        forward (None for non-jit-able models).  After ``load()`` this
        must not grow while serving bucket-shaped requests — the
        zero-compiles-at-serve-time contract."""
        with self._vlock:
            return self._current.cache_size()

    # -- request path ------------------------------------------------------

    def output(self, x, slo_ms: Optional[float] = None) -> np.ndarray:
        """Submit one request (leading batch axis); blocks for the result."""
        return self.output_async(x, slo_ms=slo_ms).result()

    def output_async(self, x, slo_ms: Optional[float] = None) -> Future:
        return self.batcher.submit(np.asarray(x), slo_ms=slo_ms)

    # -- dispatch ----------------------------------------------------------

    def _dispatch_loop(self) -> None:
        rr = 0
        n = len(self._replicas)
        while True:
            batch = self.batcher.next_batch()
            if batch is None:
                break
            placed = False
            for k in range(n):  # round-robin, skipping full replicas
                r = self._replicas[(rr + k) % n]
                try:
                    r.queue.put_nowait(batch)
                    rr = (rr + k + 1) % n
                    placed = True
                    break
                except queue.Full:
                    continue
            if not placed:  # all at their in-flight cap: backpressure
                self._replicas[rr].queue.put(batch)
                rr = (rr + 1) % n
        for r in self._replicas:
            r.queue.put(_SENTINEL)

    def _replica_loop(self, replica: _Replica) -> None:
        while True:
            item = replica.queue.get()
            if item is _SENTINEL:
                break
            self._execute(item, replica)
            replica.processed += 1

    def _run_forward(self, v: _ModelVersion, replica_idx: int, xs: np.ndarray):
        if v.fwd is not None:
            return v.fwd(v.params[replica_idx], v.state[replica_idx], xs)
        out = v.model.output(xs)
        return out[0] if isinstance(out, list) else out

    def _execute(self, batch: List[_Request], replica: _Replica) -> None:
        now = self.clock()
        live = []
        expired = 0
        for r in batch:  # deadlines re-checked at execution start — the
            if r.deadline < now:  # batch may have sat in the replica queue
                expired += 1
                if not r.future.done():
                    r.future.set_exception(DeadlineExceededError(
                        f"deadline passed after "
                        f"{(now - r.t_submit) * 1e3:.1f}ms"))
            else:
                live.append(r)
        if expired:
            self.metrics.inc("deadline_missed", expired)
        if not live:
            return
        for r in live:
            self.metrics.queue_wait.record((now - r.t_submit) * 1e3)
        xs = (live[0].x if len(live) == 1
              else np.concatenate([r.x for r in live], axis=0))
        rows = xs.shape[0]
        bucket = self.batcher.bucket_for(rows)
        padded = bucket - rows
        if padded:
            pad = np.zeros((padded,) + xs.shape[1:], xs.dtype)
            xs = np.concatenate([xs, pad], axis=0)
        if self._loaded and (bucket, str(xs.dtype)) not in self._warmed:
            self.metrics.inc("unwarmed_serves")
        with self._vlock:
            v = self._current
            v.active += 1
        t0 = self.clock()
        try:
            out = np.asarray(self._run_forward(v, replica.idx, xs))
        except Exception as e:
            self.metrics.inc("errors")
            for r in live:
                if not r.future.done():
                    r.future.set_exception(e)
            return
        finally:
            with self._vlock:
                v.active -= 1
                if v.retired and v.active == 0:
                    v.drained.set()
        device_ms = (self.clock() - t0) * 1e3
        self.batcher.observe_exec_ms(bucket, device_ms)
        self.metrics.record_batch(len(live), rows, padded, device_ms)
        with self._log_lock:
            self.batch_log.append({"tag": v.tag, "n_requests": len(live),
                                   "rows": rows, "padded": padded,
                                   "replica": replica.idx})
            if len(self.batch_log) > 4096:
                del self.batch_log[:2048]
        done = self.clock()
        ofs = 0
        for r in live:
            r.future.set_result(out[ofs:ofs + r.rows])
            ofs += r.rows
            self.metrics.e2e.record((done - r.t_submit) * 1e3)

    # -- hot swap ----------------------------------------------------------

    def swap_model(self, model, tag: Optional[str] = None) -> str:
        """Atomic hot-swap: build + AOT-warm the new version, flip the
        current pointer, then drain — block until every in-flight batch
        on the old version completes before releasing it.  In-flight
        requests keep their version; a batch never mixes two versions.
        Returns the retired version's tag (rollback = swap back, or an
        alias move in the registry)."""
        with self._swap_lock:
            nv = _ModelVersion(model, tag or f"swap@{time.time():.0f}",
                               self._devices)
            if self._loaded:
                self._warm_version(nv)
            with self._vlock:
                old = self._current
                self._current = nv
                old.retired = True
                if old.active == 0:
                    old.drained.set()
            old.drained.wait()
            self.metrics.inc("swaps")
            return old.tag

    @property
    def current_tag(self) -> str:
        with self._vlock:
            return self._current.tag

    # -- lifecycle ---------------------------------------------------------

    def metrics_snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        snap["model"] = self.current_tag
        snap["replicas"] = len(self._replicas)
        snap["queue_depth"] = self.batcher.qsize()
        snap["buckets"] = list(self.batcher.buckets)
        snap["compile_cache_size"] = self.compile_cache_size()
        return snap

    def shutdown(self, timeout: float = 5.0) -> None:
        """Deterministic shutdown: every request — queued, in a replica
        queue, or submitted concurrently with this call — resolves
        (result or RuntimeError), never hangs."""
        if self._shutdown:
            return
        self._shutdown = True
        self.batcher.close(fail_pending=True)
        self._dispatcher.join(timeout=timeout)
        for r in self._replicas:
            if r.thread:
                r.thread.join(timeout=timeout)
        # anything still sitting in replica queues (threads died, or the
        # sentinel raced a late dispatch) fails deterministically
        for r in self._replicas:
            while True:
                try:
                    item = r.queue.get_nowait()
                except queue.Empty:
                    break
                if item is _SENTINEL:
                    continue
                for req in item:
                    if not req.future.done():
                        req.future.set_exception(
                            RuntimeError("serving engine is shut down"))
