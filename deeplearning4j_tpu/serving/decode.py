"""Autoregressive decode engine: paged KV-cache + continuous batching.

A genuinely different execution mode from the one-shot ``Engine``:
stateful (the KV-cache carries across steps), multi-step (one request
spans many device dispatches), and shape-bucketed in TWO dimensions
(prompt length at prefill, slot count at decode).  The design:

  prefill/decode split
      A request's prompt runs ONCE through a bucketed prefill program
      (one AOT-compiled executable per prompt bucket) that writes K/V
      for every prompt position into the request's cache pages and
      samples the first token — so TTFT is one prefill dispatch, not
      ``n_prompt`` decode steps.  After that, every token costs one
      fixed-shape decode step.

  iteration-level continuous batching
      The decode step always runs over ALL ``max_slots`` slots with an
      active mask (masked slots write to the scratch page — see
      ops/kv_cache.py), so its compiled shape never changes and a new
      request can join the running batch at the NEXT step boundary
      (``ContinuousBatcher.admit``) instead of waiting for the batch to
      drain.  Zero serve-time compiles is therefore structural: the
      serve path only ever calls executables built at ``load()``
      (``compile_cache_size()`` is the witness, same contract as the
      one-shot engine).

  per-request stop conditions
      EOS / max-tokens / deadline are checked after every sampled
      token; a stopped request resolves immediately and its cache pages
      go back to the free list the same step — the pool oversubscribes
      slots when request lengths vary.

  resilience (the PR-7 supervisor patterns, decode-shaped)
      A crash anywhere in the decode loop fails or RETRIES every
      in-flight request (sampling is seeded + counter-based, so a retry
      regenerates the identical sequence), resets the pool, and keeps
      serving; a supervisor thread respawns the loop if it dies
      outright.  Poison isolation is per-slot: non-finite logits fail
      only that slot's request (its pages are scrubbed — a NaN left in
      a freed page would contaminate the next tenant), co-batched slots
      never notice.  Every future resolves on every path.

  hot-swap without version mixing
      ``swap_model`` flips the tag NEW admissions use; in-flight slots
      keep decoding under the version that prefilled them (the decode
      step runs once per distinct active tag — same executable,
      different params), so no request ever mixes versions and a swap
      never stalls the batch.  ``attach_registry`` wires this to
      ``ModelRegistry.set_alias``.

Sampling is greedy / temperature / top-k / top-p, seeded and
deterministic: the PRNG key is ``fold_in(PRNGKey(seed), token_index)``,
so a sequence is a pure function of (params, prompt, sampling spec) —
the property the retry path and the A/B bit-identity gate both lean on.

Three stacked decode-side optimizations, each independently gated
(docs/SERVING.md "Decode-side optimizations"):

  radix prefix cache (``prefix_cache=True``)
      A host-side trie keyed by page-sized token chunks maps
      fully-filled prompt pages to refcounted pool pages.  On admit,
      the longest matching PAGE-ALIGNED prefix is attached read-only to
      the new request's page table and only the unmatched suffix
      prefills (``prefill_at``), so a shared-prefix TTFT collapses
      toward one suffix dispatch.  Shared pages are copy-on-write by
      construction: a request only ever WRITES pages it privately owns
      (the first partial page is re-prefilled privately; generated
      tokens land past the insertable region), so sharing needs no page
      copies at all.  ``_finish`` decrefs instead of freeing; eviction
      is LRU over refcount-zero leaves under pool pressure.

  speculative decoding (``draft_model=..., speculate_k=k``)
      A draft program proposes k tokens per round (k cheap draft steps
      against a draft-sized pool indexed by the SAME page table); the
      target scores all k+1 rows in ONE fixed-shape ``spec_step``
      dispatch and seeded rejection sampling commits 1..k+1 tokens.
      At temperature 0 acceptance degenerates to exact greedy match,
      so output is BIT-identical to non-speculative decode; at
      temperature > 0 commits are exactly target-distributed but use
      dedicated RNG streams, so the sampled sequence differs from the
      non-speculative stream (documented, not gated).

  int8 KV storage (``kv_dtype="int8"``)
      Pages hold per-row symmetric int8 values + f32 scales
      (ops/kv_cache.QuantPages), quantized on write and dequantized in
      ``gather_layer`` — attention math stays f32.  ~4x sessions at
      fixed HBM; changes bits, so it is gated by a top1-agree accuracy
      envelope in ``decode_speed_ab``, never by the identity gates.

Two host-overhead eliminations ride on top (docs/SERVING.md
"Host-overhead elimination"; both off by default, both bit-exact):

  fused multi-step decode (``decode_horizon=H``)
      H consecutive decode steps + device-resident sampling run inside
      ONE AOT executable (``DecodeProgram.step_multi`` — a ``lax.scan``
      of the step body) so the per-token Python round-trip is paid once
      per H tokens.  The ``fold_in(seed, token_index)`` keying makes
      the fused stream bit-identical to step-by-step; per-slot
      EOS/budget/poison masking on device routes a finished slot's
      remaining writes to the scratch page, and the host discards the
      ≤ H-1 token overrun at replay.  Mutually exclusive with
      speculative decoding (checked at construction).

  chunked prefill (``prefill_chunk=N``)
      Long prompts prefill in ≤ N-token chunks through ``prefill_at``
      at increasing offsets, ONE chunk per loop iteration, so a long
      prompt never serializes the decode step loop; the batcher's
      token-budget admission rule paces a wall of prompts to the same
      chunk budget.  Per-row attention math is unchanged, so the final
      chunk's logits (and every sampled token) are bit-identical to an
      unchunked prefill.

TTFT and time-per-output-token are first-class (``DecodeMetrics``,
``serve/prefill`` / ``serve/decode_step`` / ``serve/prefix_attach`` /
``serve/spec_verify`` spans — docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..obs import trace as obs_trace
from .batcher import ContinuousBatcher, pow2_buckets
from .engine import (ModelNotLoadedError, PoisonInputError,
                     ReplicaCrashError, _fail_safe, _set_safe)
from .metrics import DecodeMetrics

FINISH_REASONS = ("eos", "max_tokens", "deadline")


@dataclass
class GenerationResult:
    """One finished generation.  ``tokens`` are the GENERATED ids only
    (prompt excluded; a terminating EOS is included).  ``logits`` is
    [n_tokens, vocab] float32 when the request asked ``echo_logits``
    (the bit-identity gate's evidence), else None."""

    tokens: List[int]
    n_prompt: int
    finish_reason: str
    model_tag: str
    ttft_ms: float
    tpot_ms: Optional[float]
    logits: Optional[np.ndarray] = None


@dataclass(frozen=True)
class _GenSpec:
    """Immutable request payload — a crash-retry re-runs exactly this."""

    prompt: np.ndarray
    max_new: int
    temperature: float
    top_k: int
    top_p: float
    seed: int
    echo_logits: bool


@dataclass
class PrefillHandoff:
    """The complete baton a ``role="prefill"`` host passes to a
    ``role="decode"`` host: the request spec, the first sampled token
    (TTFT is paid on the prefill side), and the prompt's KV pages packed
    with ``ops.kv_cache.pack_transfer`` — bit-exact f32 bytes or the
    int8+scale pair, so the decode host continues the EXACT sequence a
    unified engine would have produced.  ``logits0`` carries the prefill
    logits row only when the request asked ``echo_logits``."""

    prompt: np.ndarray
    max_new: int
    temperature: float
    top_k: int
    top_p: float
    seed: int
    echo_logits: bool
    first_token: int
    finite: bool
    n_pages: int
    pages: bytes
    logits0: Optional[np.ndarray]
    model_tag: str


@dataclass(frozen=True)
class _HandoffSpec(_GenSpec):
    """``_GenSpec`` + the inbound transfer — what a decode-role host's
    batcher queues for ``continue_async``."""

    handoff: Any = None


class _Slot:
    """Host-side state of one occupied decode slot.  ``page_ids`` are
    the slot's PRIVATE pages (freed at finish); ``shared_nodes`` are the
    prefix-trie nodes it holds a reference on — the first ``n_matched``
    are donor pages attached read-only at admit, the rest are pages this
    slot's own prefill inserted (decref'd, never freed directly)."""

    __slots__ = ("req", "spec", "tag", "page_ids", "n_prompt", "pos",
                 "last_token", "tokens", "n_out", "max_new", "deadline",
                 "t_first", "t_last", "logits", "shared_nodes", "n_matched",
                 "n_prefilled")

    def __init__(self, req, tag: str, page_ids: List[int], max_new: int):
        self.req = req
        self.spec = req.payload
        self.tag = tag
        self.page_ids = page_ids
        self.n_prompt = int(self.spec.prompt.shape[0])
        self.pos = self.n_prompt      # where the NEXT input token lands
        self.last_token = 0
        self.tokens: List[int] = []
        self.n_out = 0
        self.max_new = max_new
        self.deadline = req.deadline
        self.t_first = 0.0
        self.t_last = 0.0
        self.logits: Optional[List[np.ndarray]] = \
            [] if self.spec.echo_logits else None
        self.shared_nodes: List["_PrefixNode"] = []
        self.n_matched = 0
        # chunked prefill progress: prompt tokens already resident in
        # the cache (None once prefill completes / for unchunked slots);
        # a slot with n_prefilled set is NOT steppable yet
        self.n_prefilled: Optional[int] = None


class _PrefixNode:
    """One fully-filled, immutable KV page in the radix prefix trie.
    ``key`` is the page's token tuple (length = page_size); ``refs``
    counts the slots currently holding the page in their page table
    (a holder of a node holds every ancestor, so refs are monotonically
    non-increasing root -> leaf and a refs-0 node's children are also
    refs-0).  ``last_used`` is an injectable-clock timestamp (GC201)
    driving LRU eviction; ``detached`` marks a node already pulled out
    of the trie (never match it again)."""

    __slots__ = ("key", "page_id", "refs", "children", "parent",
                 "last_used", "detached")

    def __init__(self, key: tuple, page_id: Optional[int], parent):
        self.key = key
        self.page_id = page_id
        self.refs = 0
        self.children: Dict[tuple, "_PrefixNode"] = {}
        self.parent = parent
        self.last_used = 0.0
        self.detached = False


def _make_samplers(vocab_size: int):
    """(sample_one, sample_batch) pure fns.  Deterministic: the key is
    ``fold_in(PRNGKey(seed), step)`` — same (seed, step) → same draw.
    temperature <= 0 is greedy; top_k == 0 and top_p >= 1 disable those
    filters.  Also returns the all-finite flag the poison check reads.

    The math lives in ``ops.sampling.sample_token`` so the fused
    ``step_multi`` programs trace the SAME function — that shared
    source is what makes horizon fusion bit-identical to step-by-step.
    """
    import jax

    from ..ops.sampling import sample_token

    def sample_one(lg, t, k, p, seed, step):
        return sample_token(lg, t, k, p, seed, step, vocab_size)

    def sample_batch(lgs, ts, ks, ps, seeds, steps):
        return jax.vmap(sample_one)(lgs, ts, ks, ps, seeds, steps)

    return sample_one, sample_batch


# speculative decoding draws from dedicated RNG streams so a request's
# (seed, token_index) space never collides across the draft proposal,
# the accept test, and the residual resample
_DRAFT_STREAM, _ACCEPT_STREAM, _RESID_STREAM = 1, 2, 3


def _make_spec_fns(vocab_size: int, n_spec: int):
    """(propose, accept) pure fns for speculative decoding with
    ``n_spec`` draft tokens per round.

    ``propose`` samples one draft token per slot from the WARPED draft
    distribution (same temperature/top-k/top-p filter as
    ``_make_samplers``; one-hot(argmax) at temperature <= 0) and
    returns the full distribution — the accept test needs p_draft(d).

    ``accept`` runs exact rejection sampling: draft token j is accepted
    iff u_j < p_target(d_j) / p_draft(d_j) with u_j a seeded uniform;
    the first rejected position resamples from the normalized residual
    max(p_target - p_draft, 0), and full acceptance earns the bonus
    token from the target's row k ("residual" against an all-zero draft
    row — pure target).  At temperature <= 0 both distributions are
    one-hot, the ratio is exactly 0 or 1, and the commit short-circuits
    to argmax of the target row — deterministic, RNG-free, and
    bit-identical to the non-speculative greedy path.
    """
    import jax
    import jax.numpy as jnp

    def _key(seed, stream, step):
        return jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(seed), stream), step)

    def _warped(lg, t, k, p):
        # the sample_one filter, expressed as a distribution
        scaled = lg / jnp.maximum(t, 1e-6)
        srt = jnp.sort(scaled)[::-1]
        kk = jnp.clip(jnp.where(k > 0, k, vocab_size), 1, vocab_size)
        thr_k = srt[kk - 1]
        probs = jax.nn.softmax(srt)
        cum_excl = jnp.cumsum(probs) - probs
        keep = cum_excl < jnp.clip(p, 1e-6, 1.0)
        thr_p = jnp.min(jnp.where(keep, srt, jnp.inf))
        thr = jnp.maximum(thr_k, thr_p)
        masked = jnp.where(scaled >= thr, scaled, -jnp.inf)
        onehot = jax.nn.one_hot(jnp.argmax(lg), vocab_size,
                                dtype=jnp.float32)
        return jnp.where(t <= 0.0, onehot, jax.nn.softmax(masked))

    def propose_one(lg, t, k, p, seed, step):
        dist = _warped(lg, t, k, p)
        g = jax.random.gumbel(_key(seed, _DRAFT_STREAM, step), lg.shape)
        sampled = jnp.argmax(jnp.log(jnp.maximum(dist, 1e-30)) + g)
        tok = jnp.where(t <= 0.0, jnp.argmax(lg), sampled)
        return tok.astype(jnp.int32), dist

    def accept_one(tlgs, dtoks, dprobs, t, k, p, seed, step0):
        # tlgs [n_spec+1, V] target logits; dtoks [n_spec] draft tokens;
        # dprobs [n_spec, V] warped draft distributions
        finite = jnp.all(jnp.isfinite(tlgs))
        targ = jax.vmap(lambda lg: _warped(lg, t, k, p))(tlgs)
        j = jnp.arange(n_spec)
        p_t_d = targ[j, dtoks]
        p_d_d = dprobs[j, dtoks]
        u = jax.vmap(lambda jj: jax.random.uniform(
            _key(seed, _ACCEPT_STREAM, step0 + jj)))(j)
        acc = u < p_t_d / jnp.maximum(p_d_d, 1e-30)
        a = jnp.sum(jnp.cumprod(acc.astype(jnp.int32)))   # leading accepts
        dp_full = jnp.concatenate(
            [dprobs, jnp.zeros((1, vocab_size), jnp.float32)], 0)
        resid = jnp.maximum(targ - dp_full, 0.0)
        rs = jnp.sum(resid, -1, keepdims=True)
        resid = jnp.where(rs > 0, resid / jnp.maximum(rs, 1e-30), targ)
        jr = jnp.arange(n_spec + 1)

        def draw_row(jj):
            g = jax.random.gumbel(_key(seed, _RESID_STREAM, step0 + jj),
                                  (vocab_size,))
            return jnp.argmax(jnp.log(jnp.maximum(resid[jj], 1e-30)) + g)

        draws = jax.vmap(draw_row)(jr).astype(jnp.int32)
        dt_full = jnp.concatenate([dtoks, jnp.zeros((1,), jnp.int32)])
        sampled = jnp.where(jr < a, dt_full,
                            jnp.where(jr == a, draws, 0))
        greedy = jnp.where(jr < a, dt_full,
                           jnp.where(jr == a,
                                     jnp.argmax(tlgs, -1).astype(jnp.int32),
                                     0))
        commit = jnp.where(t <= 0.0, greedy, sampled)
        return (a + 1).astype(jnp.int32), commit, finite

    def propose(lgs, ts, ks, ps, seeds, steps):
        return jax.vmap(propose_one)(lgs, ts, ks, ps, seeds, steps)

    def accept(tlgs, dtoks, dprobs, ts, ks, ps, seeds, steps):
        return jax.vmap(accept_one)(tlgs, dtoks, dprobs, ts, ks, ps,
                                    seeds, steps)

    return propose, accept


class DecodeEngine:
    """``DecodeEngine(lm).load()`` then ``generate(prompt_ids, ...)``.

    ``model`` provides ``decode_program()`` (ShardedTransformerLM) — the
    pure prefill/step/re-encode functions of ops/kv_cache.DecodeProgram.
    ``clock`` is injectable (monotonic seconds) so deadline/TTFT logic
    is testable without sleeping.
    """

    def __init__(self, model, *, max_slots: int = 4, page_size: int = 16,
                 max_len: Optional[int] = None,
                 total_pages: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 eos_id: Optional[int] = None, slo_ms: float = 30_000.0,
                 max_queue: int = 256, admission: str = "block",
                 max_retries: int = 1, default_max_new: int = 32,
                 clock=time.monotonic, tag: str = "v0",
                 metrics: Optional[DecodeMetrics] = None,
                 prefix_cache: bool = False, draft_model=None,
                 speculate_k: int = 4, kv_dtype: Optional[str] = None,
                 role: str = "unified", tenants=None,
                 decode_horizon: int = 1,
                 prefill_chunk: Optional[int] = None):
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        if decode_horizon < 1:
            raise ValueError("decode_horizon must be >= 1")
        if decode_horizon > 1 and draft_model is not None:
            raise ValueError(
                "fused multi-step decode and speculative decoding are "
                "mutually exclusive — speculation keeps its own round "
                "structure (propose/verify/commit), so a fused horizon "
                "has nothing to amortize there")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if prefill_chunk is not None and role != "unified":
            raise ValueError(
                "chunked prefill is unified-role only: a decode-role "
                "host never prefills, and a prefill-role host has no "
                "step loop to interleave with")
        if prefill_chunk is not None and draft_model is not None:
            raise ValueError(
                "chunked prefill + speculative decoding is unsupported "
                "(the draft pool's mirror prefill is not chunked)")
        if kv_dtype not in (None, "f32", "float32", "int8", "i8"):
            raise ValueError(f"kv_dtype {kv_dtype!r} not supported "
                             "(float32 or int8)")
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(f"role {role!r} not supported "
                             "(unified, prefill, or decode)")
        if role != "unified" and draft_model is not None:
            raise ValueError(
                "speculative decoding is unified-role only — the draft "
                "pool's state never crosses a page handoff")
        self.role = role
        self._mesh = getattr(model, "mesh", None)
        self.program = model.decode_program(page_size=page_size,
                                            max_len=max_len)
        prog = self.program
        if getattr(prog, "tp", 1) > 1 and kv_dtype in ("int8", "i8"):
            raise ValueError(
                "int8 KV + tensor-parallel decode is unsupported: the "
                "per-row quantization scale is an amax over ALL heads "
                "and cannot be computed inside one head shard")
        self._prefix_on = bool(prefix_cache)
        if self._prefix_on and prog.prefill_at is None:
            raise ValueError(
                "prefix_cache=True needs a decode program with a "
                "prefill_at entry point (suffix prefill)")
        self.decode_horizon = int(decode_horizon)
        if self.decode_horizon > 1 and prog.step_multi is None:
            raise ValueError(
                "decode_horizon > 1 needs a decode program with a "
                "step_multi entry point (fused multi-step decode)")
        self.prefill_chunk = (None if prefill_chunk is None
                              else int(prefill_chunk))
        if self.prefill_chunk is not None and prog.prefill_at is None:
            raise ValueError(
                "prefill_chunk needs a decode program with a prefill_at "
                "entry point (offset prefill drives each chunk)")
        self._kv_dtype = kv_dtype
        self.speculate_k = int(speculate_k)
        self._draft_program = None
        self._draft_params = None
        self._draft_cache = None
        if draft_model is not None:
            if self.speculate_k < 1:
                raise ValueError("speculate_k must be >= 1")
            if prog.spec_step is None:
                raise ValueError(
                    "speculative decoding needs a decode program with a "
                    "spec_step entry point (multi-token verify)")
            dprog = draft_model.decode_program(page_size=page_size,
                                               max_len=prog.max_len)
            if (dprog.vocab_size != prog.vocab_size
                    or dprog.max_len != prog.max_len
                    or dprog.page_size != prog.page_size):
                raise ValueError(
                    "draft/target program mismatch: vocab "
                    f"{dprog.vocab_size}/{prog.vocab_size}, max_len "
                    f"{dprog.max_len}/{prog.max_len}, page_size "
                    f"{dprog.page_size}/{prog.page_size} must all agree")
            self._draft_program = dprog
            self._draft_params = getattr(draft_model, "params", draft_model)
        self._prefix_root = _PrefixNode((), None, None)
        self._trie_pages = 0
        self.max_slots = int(max_slots)
        self.eos_id = eos_id
        self.max_retries = int(max_retries)
        self.default_max_new = int(default_max_new)
        self.clock = clock
        self.total_pages = int(
            total_pages if total_pages is not None
            else 1 + self.max_slots * prog.pages_per_slot)
        if self.total_pages < 1 + prog.pages_per_slot:
            raise ValueError(
                f"total_pages {self.total_pages} cannot hold even one "
                f"full-length request ({prog.pages_per_slot} pages) plus "
                "the scratch page")
        self.metrics = metrics or DecodeMetrics()
        self.tenants = tenants           # tenancy.TenantTable or None
        self.batcher = ContinuousBatcher(
            max_batch=self.max_slots, slo_ms=slo_ms, max_queue=max_queue,
            admission=admission, metrics=self.metrics, clock=clock,
            tenants=tenants)
        buckets = sorted(set(int(b) for b in (prompt_buckets
                                              or pow2_buckets(prog.max_len))))
        self.prompt_buckets = [b for b in buckets if 0 < b <= prog.max_len]
        if not self.prompt_buckets:
            raise ValueError("no prompt bucket <= max_len "
                             f"{prog.max_len}: {buckets}")
        self.max_prompt = min(self.prompt_buckets[-1], prog.max_len - 1)

        params = getattr(model, "params", model)
        self._versions: Dict[str, Any] = {tag: params}
        self._serve_tag = tag
        # NAMED models this engine also decodes: name -> serve tag in
        # _versions.  Param trees must be shape-compatible with the
        # loaded program (executables are shared across versions).
        self._model_tags: Dict[str, str] = {}
        self._model_last_used: Dict[str, float] = {}
        self._slots: List[Optional[_Slot]] = [None] * self.max_slots
        self._page_table = np.zeros(
            (self.max_slots, prog.pages_per_slot), np.int32)
        self._free_pages = deque(range(1, self.total_pages))
        self._cache = None
        self._compiled: Dict[tuple, Any] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._loaded = False
        self._shutdown = False
        self._generation = 0
        self._chunk_cursor = 0     # round-robin over chunked prefills
        self._crash_next = False   # test hook: raise inside the next step
        self._thread: Optional[threading.Thread] = None
        self._supervisor: Optional[threading.Thread] = None
        self._autoscaler = None             # see enable_autoscale()
        self._autoscale_cb = None
        self._autoscale_interval_s = 0.25
        self._last_autoscale_t: Optional[float] = None
        self._shed_seen = 0.0
        self._logical_replicas = 1

    # -- load / warmup -----------------------------------------------------

    def load(self, warm_bundle: Optional[str] = None) -> "DecodeEngine":
        """Allocate the pool and AOT-compile + run every serve-path
        executable: one prefill per prompt bucket, the decode step, the
        two samplers, the pool reset, and the page scrub.  After this,
        ``compile_cache_size()`` must not grow while serving — the
        zero-serve-time-compiles contract.

        ``warm_bundle`` points at a bundle written by
        :meth:`save_warmup_bundle` (serving/warmcache.py): each
        executable deserializes instead of compiling, with per-key
        fallback to compile on any miss.  Bundle hits are still executed
        once below, so the donated pool state flows identically to a
        cold load."""
        import jax

        from ..ops.kv_cache import alloc_cache
        from .warmcache import load_bundle

        prog = self.program
        params = self._versions[self._serve_tag]
        s_n, pps, v_n = self.max_slots, prog.pages_per_slot, prog.vocab_size
        kp, vp = alloc_cache(prog.n_layers, self.total_pages, prog.page_size,
                             prog.n_heads, prog.d_head,
                             kv_dtype=self._kv_dtype)
        bundle_mesh = self._mesh if getattr(prog, "tp", 1) > 1 else None
        bundle = (load_bundle(warm_bundle, mesh=bundle_mesh)
                  if warm_bundle else {})
        hits = misses = 0

        def _get(key, build):
            nonlocal hits, misses
            exe = bundle.get(key)
            if exe is not None:
                hits += 1
                return exe
            misses += 1
            return build()

        t0 = self.clock()
        with obs_trace.span("serve/warmup", cat="serve", kind="decode",
                            tag=self._serve_tag, role=self.role):
            lgs = None
            if self.role != "prefill":
                # decode step + batch sampler — a prefill-role host never
                # steps, so its warmup (and bundle) skips them entirely
                step_c = _get("step", lambda: jax.jit(
                    prog.step, donate_argnums=(1, 2)).lower(
                        params, kp, vp, np.zeros((s_n, pps), np.int32),
                        np.zeros((s_n,), np.int32),
                        np.zeros((s_n,), np.int32),
                        np.zeros((s_n,), bool)).compile())
                kp, vp, lgs = step_c(
                    params, kp, vp, np.zeros((s_n, pps), np.int32),
                    np.zeros((s_n,), np.int32), np.zeros((s_n,), np.int32),
                    np.zeros((s_n,), bool))
                self._compiled[("step",)] = step_c

                if self.decode_horizon > 1:
                    # fused multi-step decode: H is a compile-time
                    # constant (the scan length = horizon arange), so
                    # the executable lives in the bundle like any other
                    H = self.decode_horizon
                    zs_i = np.zeros((s_n,), np.int32)
                    sm_c = _get(f"step_multi:{H}", lambda: jax.jit(
                        prog.step_multi, donate_argnums=(1, 2)).lower(
                            params, kp, vp, np.zeros((s_n, pps), np.int32),
                            zs_i, zs_i, np.zeros((s_n,), bool),
                            np.zeros((s_n,), np.float32), zs_i,
                            np.ones((s_n,), np.float32),
                            np.zeros((s_n,), np.uint32), zs_i,
                            np.ones((s_n,), np.int32), np.int32(-1),
                            np.arange(H, dtype=np.int32)).compile())
                    kp, vp, _, _, _ = sm_c(
                        params, kp, vp, np.zeros((s_n, pps), np.int32),
                        zs_i, zs_i, np.zeros((s_n,), bool),
                        np.zeros((s_n,), np.float32), zs_i,
                        np.ones((s_n,), np.float32),
                        np.zeros((s_n,), np.uint32), zs_i,
                        np.ones((s_n,), np.int32), np.int32(-1),
                        np.arange(H, dtype=np.int32))
                    self._compiled[("step_multi", H)] = sm_c

            lg1 = None
            if self.role != "decode":
                prefill_jit = jax.jit(prog.prefill, donate_argnums=(1, 2))
                for b in self.prompt_buckets:
                    pf = _get(f"prefill:{b}", lambda b=b: prefill_jit.lower(
                        params, kp, vp, np.zeros((pps,), np.int32),
                        np.zeros((b,), np.int32), np.int32(1)).compile())
                    kp, vp, lg1 = pf(params, kp, vp,
                                     np.zeros((pps,), np.int32),
                                     np.zeros((b,), np.int32), np.int32(1))
                    self._compiled[("prefill", b)] = pf

                if self._prefix_on or self.prefill_chunk is not None:
                    # suffix prefill per bucket — prefix-cache HITS and
                    # chunked-prefill chunks drive these; the cold
                    # path's executables (and bits) are untouched when
                    # both features are off
                    pa_jit = jax.jit(prog.prefill_at, donate_argnums=(1, 2))
                    for b in self.prompt_buckets:
                        pf = _get(f"prefill_at:{b}",
                                  lambda b=b: pa_jit.lower(
                                      params, kp, vp,
                                      np.zeros((pps,), np.int32),
                                      np.zeros((b,), np.int32), np.int32(1),
                                      np.int32(0)).compile())
                        kp, vp, lg1 = pf(params, kp, vp,
                                         np.zeros((pps,), np.int32),
                                         np.zeros((b,), np.int32),
                                         np.int32(1), np.int32(0))
                        self._compiled[("prefill_at", b)] = pf

            one, batch = _make_samplers(v_n)
            if self.role != "decode":
                s1 = _get("sample1", lambda: jax.jit(one).lower(
                    lg1, np.float32(0), np.int32(0), np.float32(1),
                    np.uint32(0), np.int32(0)).compile())
                tok, _ = s1(lg1, np.float32(0), np.int32(0), np.float32(1),
                            np.uint32(0), np.int32(0))
                np.asarray(tok)
                self._compiled[("sample1",)] = s1
            if self.role != "prefill":
                sb = _get("sample", lambda: jax.jit(batch).lower(
                    lgs, np.zeros((s_n,), np.float32),
                    np.zeros((s_n,), np.int32),
                    np.ones((s_n,), np.float32),
                    np.zeros((s_n,), np.uint32),
                    np.zeros((s_n,), np.int32)).compile())
                toks, _ = sb(lgs, np.zeros((s_n,), np.float32),
                             np.zeros((s_n,), np.int32),
                             np.ones((s_n,), np.float32),
                             np.zeros((s_n,), np.uint32),
                             np.zeros((s_n,), np.int32))
                np.asarray(toks)
                self._compiled[("sample",)] = sb

            from ..ops.kv_cache import scrub_pool

            def _reset(k, v):
                import jax.numpy as jnp
                z = jax.tree_util.tree_map(jnp.zeros_like, (k, v))
                return z[0], z[1]

            def _scrub(k, v, ids):
                # zero the given pages (padded with repeats — idempotent;
                # int8 pools zero values AND scales)
                return scrub_pool(k, ids), scrub_pool(v, ids)

            reset_c = _get("reset", lambda: jax.jit(
                _reset, donate_argnums=(0, 1)).lower(kp, vp).compile())
            kp, vp = reset_c(kp, vp)
            self._compiled[("reset",)] = reset_c
            scrub_c = _get("scrub", lambda: jax.jit(
                _scrub, donate_argnums=(0, 1)).lower(
                    kp, vp, np.zeros((pps,), np.int32)).compile())
            kp, vp = scrub_c(kp, vp, np.zeros((pps,), np.int32))
            self._compiled[("scrub",)] = scrub_c

            if self.role == "prefill":
                # page export: gather one slot's pages out of the pool
                # (read-only — the pool stays donated to the serve path)
                from ..ops.kv_cache import gather_pages

                def _extract(k, v, ids):
                    return gather_pages(k, ids), gather_pages(v, ids)

                ex_c = _get("extract", lambda: jax.jit(_extract).lower(
                    kp, vp, np.zeros((pps,), np.int32)).compile())
                jax.block_until_ready(
                    ex_c(kp, vp, np.zeros((pps,), np.int32)))
                self._compiled[("extract",)] = ex_c
            if self.role == "decode":
                # page attach: scatter an inbound transfer's rows into
                # freshly-allocated pages in ONE donated dispatch
                from ..ops.kv_cache import set_pages

                def _attach(k, v, ids, kpay, vpay):
                    return set_pages(k, ids, kpay), set_pages(v, ids, vpay)

                zk_pay = self._zero_payload(kp)
                zv_pay = self._zero_payload(vp)
                at_c = _get("attach", lambda: jax.jit(
                    _attach, donate_argnums=(0, 1)).lower(
                        kp, vp, np.zeros((pps,), np.int32),
                        zk_pay, zv_pay).compile())
                kp, vp = at_c(kp, vp, np.zeros((pps,), np.int32),
                              zk_pay, zv_pay)
                self._compiled[("attach",)] = at_c

            if self._draft_program is not None:
                kp, vp = self._load_spec(_get, params, kp, vp)
        self.metrics.inc("bundle_hits", hits)
        self.metrics.inc("bundle_misses", misses)
        self.metrics.inc("warmup_seconds_total", self.clock() - t0)

        self._cache = (kp, vp)
        with self._lock:
            self._refresh_pool_gauges_locked()
        self._loaded = True
        self._start_loop()
        self._supervisor = threading.Thread(
            target=self._supervise, name="decode-supervisor", daemon=True)
        self._supervisor.start()
        return self

    def _load_spec(self, _get, params, kp, vp):
        """Warm the speculative-decoding executables: the draft pool's
        prefill/step/reset/scrub (draft dims, SAME page table), the
        target's fixed-[S, k+1] ``spec_step`` verify, and the
        propose/accept samplers — all AOT, all fixed-shape (k is frozen
        at construction), so speculation adds zero serve-time compiles.
        Returns the threaded target pool (spec_step donates it)."""
        import jax

        from ..ops.kv_cache import alloc_cache, scrub_pool

        prog, dprog = self.program, self._draft_program
        dparams = self._draft_params
        s_n, pps, v_n = self.max_slots, prog.pages_per_slot, prog.vocab_size
        k = self.speculate_k
        dkp, dvp = alloc_cache(dprog.n_layers, self.total_pages,
                               dprog.page_size, dprog.n_heads, dprog.d_head,
                               kv_dtype=self._kv_dtype)

        dp_jit = jax.jit(dprog.prefill, donate_argnums=(1, 2))
        for b in self.prompt_buckets:
            pf = _get(f"draft_prefill:{b}", lambda b=b: dp_jit.lower(
                dparams, dkp, dvp, np.zeros((pps,), np.int32),
                np.zeros((b,), np.int32), np.int32(1)).compile())
            dkp, dvp, _ = pf(dparams, dkp, dvp, np.zeros((pps,), np.int32),
                             np.zeros((b,), np.int32), np.int32(1))
            self._compiled[("draft_prefill", b)] = pf
        if self._prefix_on:
            dpa_jit = jax.jit(dprog.prefill_at, donate_argnums=(1, 2))
            for b in self.prompt_buckets:
                pf = _get(f"draft_prefill_at:{b}",
                          lambda b=b: dpa_jit.lower(
                              dparams, dkp, dvp, np.zeros((pps,), np.int32),
                              np.zeros((b,), np.int32), np.int32(1),
                              np.int32(0)).compile())
                dkp, dvp, _ = pf(dparams, dkp, dvp,
                                 np.zeros((pps,), np.int32),
                                 np.zeros((b,), np.int32), np.int32(1),
                                 np.int32(0))
                self._compiled[("draft_prefill_at", b)] = pf

        dstep_c = _get("draft_step", lambda: jax.jit(
            dprog.step, donate_argnums=(1, 2)).lower(
                dparams, dkp, dvp, np.zeros((s_n, pps), np.int32),
                np.zeros((s_n,), np.int32), np.zeros((s_n,), np.int32),
                np.zeros((s_n,), bool)).compile())
        dkp, dvp, dlgs = dstep_c(
            dparams, dkp, dvp, np.zeros((s_n, pps), np.int32),
            np.zeros((s_n,), np.int32), np.zeros((s_n,), np.int32),
            np.zeros((s_n,), bool))
        self._compiled[("draft_step",)] = dstep_c

        spec_c = _get("spec_step", lambda: jax.jit(
            prog.spec_step, donate_argnums=(1, 2)).lower(
                params, kp, vp, np.zeros((s_n, pps), np.int32),
                np.zeros((s_n, k + 1), np.int32), np.zeros((s_n,), np.int32),
                np.zeros((s_n,), bool)).compile())
        kp, vp, tlgs = spec_c(
            params, kp, vp, np.zeros((s_n, pps), np.int32),
            np.zeros((s_n, k + 1), np.int32), np.zeros((s_n,), np.int32),
            np.zeros((s_n,), bool))
        self._compiled[("spec_step",)] = spec_c

        propose, accept = _make_spec_fns(v_n, k)
        zt = np.zeros((s_n,), np.float32)
        zk = np.zeros((s_n,), np.int32)
        zp = np.ones((s_n,), np.float32)
        zs = np.zeros((s_n,), np.uint32)
        zj = np.zeros((s_n,), np.int32)
        prop_c = _get("propose", lambda: jax.jit(propose).lower(
            dlgs, zt, zk, zp, zs, zj).compile())
        d_tok, d_probs = prop_c(dlgs, zt, zk, zp, zs, zj)
        np.asarray(d_tok)
        self._compiled[("propose",)] = prop_c
        acc_c = _get("spec_accept", lambda: jax.jit(accept).lower(
            tlgs, np.zeros((s_n, k), np.int32),
            np.zeros((s_n, k, v_n), np.float32), zt, zk, zp, zs,
            zj).compile())
        nc, cm, fin = acc_c(tlgs, np.zeros((s_n, k), np.int32),
                            np.zeros((s_n, k, v_n), np.float32),
                            zt, zk, zp, zs, zj)
        np.asarray(nc)
        self._compiled[("spec_accept",)] = acc_c

        def _dreset(dk, dv):
            import jax.numpy as jnp
            z = jax.tree_util.tree_map(jnp.zeros_like, (dk, dv))
            return z[0], z[1]

        def _dscrub(dk, dv, ids):
            return scrub_pool(dk, ids), scrub_pool(dv, ids)

        dreset_c = _get("draft_reset", lambda: jax.jit(
            _dreset, donate_argnums=(0, 1)).lower(dkp, dvp).compile())
        dkp, dvp = dreset_c(dkp, dvp)
        self._compiled[("draft_reset",)] = dreset_c
        dscrub_c = _get("draft_scrub", lambda: jax.jit(
            _dscrub, donate_argnums=(0, 1)).lower(
                dkp, dvp, np.zeros((pps,), np.int32)).compile())
        dkp, dvp = dscrub_c(dkp, dvp, np.zeros((pps,), np.int32))
        self._compiled[("draft_scrub",)] = dscrub_c

        self._draft_cache = (dkp, dvp)
        return kp, vp

    def _zero_payload(self, pool):
        """A zero host-side payload with the shape
        ``gather_pages(pool, ids)`` produces for a full pages-per-slot id
        vector — the AOT lowering specimen for the attach executable
        (handles both the f32 pool and the int8 QuantPages pair)."""
        import jax
        pps = self.program.pages_per_slot
        return jax.tree_util.tree_map(
            lambda a: np.zeros((a.shape[0], pps) + tuple(a.shape[2:]),
                               a.dtype), pool)

    def save_warmup_bundle(self, path: str) -> str:
        """Export every serve-path executable as a warmup bundle
        (serving/warmcache.py) so a fresh process — a scaled-up decode
        host, a respawn — deserializes in milliseconds via
        ``load(warm_bundle=path)`` instead of paying the XLA compiles.
        Sharded (tp > 1) engines pin the mesh topology into the bundle
        fingerprint — a differently-meshed process recompiles."""
        from .warmcache import save_bundle
        if not self._loaded:
            raise RuntimeError("load() the engine before bundling")
        entries = {":".join(str(p) for p in key): exe
                   for key, exe in self._compiled.items()}
        mesh = self._mesh if getattr(self.program, "tp", 1) > 1 else None
        return save_bundle(path, self._serve_tag, entries, mesh=mesh)

    def compile_cache_size(self) -> int:
        """Executables backing the serve path.  Must not grow after
        ``load()`` while serving — watched by ``continuous_batching_ab``."""
        return len(self._compiled)

    @property
    def current_tag(self) -> str:
        with self._lock:
            return self._serve_tag

    # -- request path ------------------------------------------------------

    def generate_async(self, prompt_ids, *, max_new_tokens: Optional[int] = None,
                       temperature: float = 0.0, top_k: int = 0,
                       top_p: float = 1.0, seed: int = 0,
                       slo_ms: Optional[float] = None,
                       deadline: Optional[float] = None,
                       echo_logits: bool = False,
                       model: Optional[str] = None,
                       tenant: Optional[str] = None) -> Future:
        """Enqueue one generation; the Future resolves to a
        ``GenerationResult`` (or a typed serving error).  Joins the
        running decode batch at the next step boundary.  ``model``
        routes to a placed named model (``add_model``; None = the
        default); ``tenant`` tags the request for fair-share scheduling
        and quota accounting."""
        if not self._loaded:
            raise RuntimeError("DecodeEngine.load() must run before generate")
        if model is not None:
            with self._lock:
                if model not in self._model_tags:
                    f: Future = Future()
                    f.set_exception(ModelNotLoadedError(
                        f"model {model!r} is not placed on this decode "
                        "host"))
                    return f
        if self.role == "decode":
            raise RuntimeError(
                "decode-role host accepts page handoffs (continue_async), "
                "not raw prompts — route prompts at a prefill or unified "
                "host")
        prog = self.program
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.shape[0] < 1 or prompt.shape[0] > self.max_prompt:
            raise ValueError(
                f"prompt length {prompt.shape[0]} outside [1, "
                f"{self.max_prompt}] (largest warmed bucket, < max_len "
                f"{prog.max_len})")
        if prompt.min() < 0 or prompt.max() >= prog.vocab_size:
            raise ValueError(f"prompt ids outside [0, {prog.vocab_size})")
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.default_max_new)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        max_new = min(max_new, prog.max_len - int(prompt.shape[0]))
        if temperature < 0:
            raise ValueError("temperature must be >= 0 (0 = greedy)")
        if not (0 <= top_k <= prog.vocab_size):
            raise ValueError(f"top_k outside [0, {prog.vocab_size}]")
        if not (0 < top_p <= 1.0):
            raise ValueError("top_p must be in (0, 1]")
        spec = _GenSpec(prompt=prompt, max_new=max_new,
                        temperature=float(temperature), top_k=int(top_k),
                        top_p=float(top_p), seed=int(seed),
                        echo_logits=bool(echo_logits))
        return self.batcher.submit_request(spec, slo_ms=slo_ms,
                                           deadline=deadline,
                                           tenant=tenant, model=model)

    def generate(self, prompt_ids, **kw) -> GenerationResult:
        """Blocking ``generate_async``."""
        return self.generate_async(prompt_ids, **kw).result()

    def continue_async(self, handoff: PrefillHandoff, *,
                       slo_ms: Optional[float] = None,
                       deadline: Optional[float] = None,
                       tenant: Optional[str] = None) -> Future:
        """Enqueue the DECODE stage of a disaggregated generation:
        attach the prefill host's exported KV pages, then stream tokens
        from the already-sampled first token.  Only valid on a
        ``role="decode"`` engine.  Resolves to the same
        ``GenerationResult`` a unified engine would produce — seeded
        counter-based sampling continues at step 1, so the token
        sequence is bit-identical."""
        if not self._loaded:
            raise RuntimeError("DecodeEngine.load() must run before "
                               "continue_async")
        if self.role != "decode":
            raise RuntimeError(
                "continue_async needs a role='decode' engine "
                f"(this one is {self.role!r})")
        prog = self.program
        prompt = np.asarray(handoff.prompt, np.int32).reshape(-1)
        n = int(prompt.shape[0])
        if n < 1 or n >= prog.max_len:
            raise ValueError(
                f"handoff prompt length {n} outside [1, {prog.max_len})")
        if prompt.min() < 0 or prompt.max() >= prog.vocab_size:
            raise ValueError(f"prompt ids outside [0, {prog.vocab_size})")
        if not 0 <= int(handoff.first_token) < prog.vocab_size:
            raise ValueError(
                f"handoff first_token {handoff.first_token} outside "
                f"[0, {prog.vocab_size})")
        max_new = max(1, min(int(handoff.max_new), prog.max_len - n))
        spec = _HandoffSpec(
            prompt=prompt, max_new=max_new,
            temperature=float(handoff.temperature),
            top_k=int(handoff.top_k), top_p=float(handoff.top_p),
            seed=int(handoff.seed),
            echo_logits=bool(handoff.echo_logits), handoff=handoff)
        return self.batcher.submit_request(spec, slo_ms=slo_ms,
                                           deadline=deadline, tenant=tenant)

    # -- hot-swap ----------------------------------------------------------

    def _check_params_compat(self, params, tag: str) -> None:
        """Incoming params must match the loaded shapes/dtypes — the
        AOT executables are shared across every version and named
        model on this engine."""
        import jax

        ref = self._versions[self._serve_tag]
        try:
            mismatch = jax.tree_util.tree_map(
                lambda a, b: (np.shape(a) != np.shape(b)
                              or np.asarray(a).dtype != np.asarray(b).dtype),
                ref, params)
        except ValueError as e:
            raise ValueError(f"incoming model {tag!r} has a different "
                             f"parameter tree: {e}") from e
        if any(jax.tree_util.tree_leaves(mismatch)):
            raise ValueError(
                f"incoming model {tag!r} has mismatched parameter "
                "shapes/dtypes — decode versions must share the compiled "
                "executables")

    def swap_model(self, model, tag: str,
                   name: Optional[str] = None) -> None:
        """Flip the version NEW admissions decode under; in-flight slots
        finish under the version that prefilled them (the step runs per
        distinct active tag), so no request mixes versions and nothing
        drains.  ``name`` scopes the flip to one placed named model
        (swaps never cross models/tenants); None flips the default."""
        params = getattr(model, "params", model)
        self._check_params_compat(params, tag)
        with self._lock:
            if name is not None:
                if name not in self._model_tags:
                    raise ModelNotLoadedError(
                        f"model {name!r} is not placed on this decode host")
                self._versions[tag] = params
                self._model_tags[name] = tag
            else:
                self._versions[tag] = params
                self._serve_tag = tag
        self.metrics.inc("swaps")
        obs_trace.instant("serve/swap", cat="serve", incoming=tag,
                          kind="decode", model=name)

    # -- multi-model placement ---------------------------------------------

    def add_model(self, name: str, model,
                  tag: Optional[str] = None) -> "DecodeEngine":
        """Place a NAMED model alongside the default: its param tree
        must be shape/dtype-compatible with the loaded decode program
        (same vocab, max_len, page layout — the compiled step/prefill
        executables are shared, so placement costs a params residency,
        not a compile).  New generations route with
        ``generate_async(model=name)``."""
        if not name:
            raise ValueError("model name must be non-empty")
        params = getattr(model, "params", model)
        tag = tag or f"{name}:v0"
        self._check_params_compat(params, tag)
        with self._lock:
            if name in self._model_tags:
                raise ValueError(f"model {name!r} is already placed")
            self._versions[tag] = params
            self._model_tags[name] = tag
            self._model_last_used[name] = self.clock()
        self.metrics.inc("model_loads")
        obs_trace.instant("serve/model_load", cat="serve", model=name,
                          tag=tag, kind="decode")
        return self

    def add_model_from_registry(self, registry, name: str,
                                ref: str = "prod", *,
                                subscribe: bool = False) -> "DecodeEngine":
        """Registry-backed :meth:`add_model` (tag = ``name:vN``).
        ``subscribe=True`` follows alias moves with per-model swaps —
        leave False under a placement controller."""
        version, model = registry.resolve(name, ref)
        self.add_model(name, model, tag=f"{name}:v{version}")
        if subscribe:
            registry.subscribe(
                name, ref,
                lambda ver, m: self.swap_model(m, f"{name}:v{ver}",
                                               name=name))
        return self

    def remove_model(self, name: str) -> bool:
        """Evict a named model: unroute it (queued requests fail typed
        at admission → the fleet re-routes).  In-flight slots finish
        under their own tag — the params stay resident until the last
        such slot completes (version GC), so eviction never strands a
        generation or mixes versions.  Returns False if not placed."""
        with self._lock:
            tag = self._model_tags.pop(name, None)
            self._model_last_used.pop(name, None)
            if tag is None:
                return False
            live = {sl.tag for sl in self._slots if sl is not None}
            live.add(self._serve_tag)
            live.update(self._model_tags.values())
            if tag not in live:
                del self._versions[tag]
        self.metrics.inc("model_evictions")
        obs_trace.instant("serve/model_evict", cat="serve", model=name,
                          tag=tag, kind="decode")
        return True

    def has_model(self, name: Optional[str]) -> bool:
        """True when this engine currently decodes ``name`` (None — the
        default model — always)."""
        if name is None:
            return True
        with self._lock:
            return name in self._model_tags

    def placed_models(self) -> Dict[str, str]:
        """name → serve tag for every model this engine decodes (the
        default under "")."""
        with self._lock:
            out = {"": self._serve_tag}
            out.update(self._model_tags)
            return out

    def model_last_used(self, name: str) -> Optional[float]:
        """Engine-clock stamp of the last admission for a named model
        (None = never, or not placed) — the placement controller's
        idle-eviction signal."""
        with self._lock:
            return self._model_last_used.get(name)

    def attach_registry(self, registry, name: str,
                        alias: str = "prod") -> "DecodeEngine":
        """Serve (name, alias) from a ModelRegistry and follow every
        ``set_alias`` move with a no-drain ``swap_model``."""
        version, model = registry.resolve(name, alias)
        self.swap_model(model, f"{name}:v{version}")
        registry.subscribe(
            name, alias,
            lambda ver, mod: self.swap_model(mod, f"{name}:v{ver}"))
        return self

    # -- decode loop -------------------------------------------------------

    def _start_loop(self) -> None:
        with self._lock:
            self._generation += 1
            gen = self._generation
            self._thread = threading.Thread(
                target=self._loop, args=(gen,),
                name=f"decode-loop-{gen}", daemon=True)
            self._thread.start()

    def enable_autoscale(self, on_scale, autoscaler=None, *,
                         min_replicas: int = 1, max_replicas: int = 4,
                         interval_s: float = 0.25,
                         **knobs) -> "DecodeEngine":
        """Arm the load controller over the decode queue.  Unlike the
        predict engine, decode slot capacity is COMPILE-SHAPE-FIXED
        (the step executable is compiled for ``max_slots``), so the
        actuator is a callback, not an in-process replica birth: the
        fleet tier owns physical decode scaling (a new `serve` host
        warming from this engine's warmup bundle — docs/SERVING.md
        "Cold start & autoscaling").  ``on_scale(delta, replicas)`` is
        called with +1/-1 and the new logical replica count; spans and
        scale counters are emitted here either way."""
        from .autoscale import ReplicaAutoscaler
        if autoscaler is None:
            autoscaler = ReplicaAutoscaler(
                min_replicas=int(min_replicas),
                max_replicas=int(max_replicas),
                clock=self.clock, **knobs)
        self._autoscale_interval_s = float(interval_s)
        self._shed_seen = self.metrics.counter_value("shed")
        self._autoscale_cb = on_scale
        self._autoscaler = autoscaler
        return self

    def _autoscale_tick(self) -> None:
        a = self._autoscaler
        if a is None or not self._loaded or self._shutdown:
            return
        now = self.clock()
        if (self._last_autoscale_t is not None
                and now - self._last_autoscale_t < self._autoscale_interval_s):
            return
        self._last_autoscale_t = now
        shed = self.metrics.counter_value("shed")
        shed_delta = shed - self._shed_seen
        self._shed_seen = shed
        with self._lock:
            active = sum(1 for s in self._slots if s is not None)
        decision = a.observe(self.batcher.qsize(), active,
                             self._logical_replicas,
                             shed_delta=int(shed_delta))
        if decision == 0:
            return
        self._logical_replicas += decision
        if decision > 0:
            with obs_trace.span("serve/scale_up", cat="serve",
                                kind="decode",
                                replicas=self._logical_replicas):
                self._autoscale_cb(1, self._logical_replicas)
            self.metrics.inc("scale_ups")
        else:
            with obs_trace.span("serve/scale_down", cat="serve",
                                kind="decode",
                                replicas=self._logical_replicas):
                self._autoscale_cb(-1, self._logical_replicas)
            self.metrics.inc("scale_downs")

    def _supervise(self) -> None:
        """Respawn the decode loop if it dies outright (a crash its own
        handler could not absorb) — in-flight requests are retried or
        failed, never stranded."""
        while not self._stop.wait(0.05):
            with self._lock:
                if self._shutdown:
                    return
                t = self._thread
            self._autoscale_tick()
            if t is not None and not t.is_alive():
                obs_trace.instant("serve/replica_crash", cat="serve",
                                  kind="decode_loop_dead")
                self.metrics.inc("replica_crashes")
                self._drain_crashed(ReplicaCrashError(
                    "decode loop thread died"))
                with self._lock:
                    if self._shutdown:
                        return
                self.metrics.inc("replica_respawns")
                self._start_loop()

    def _loop(self, gen: int) -> None:
        if self.role == "prefill":
            # Prefill hosts are throughput-oriented: drop the loop
            # thread to lowest scheduling priority so a co-located
            # decode-role host keeps its inter-token latency through
            # prompt bursts (TTFT of queued prefills is the explicit
            # trade).  On a dedicated prefill machine there is no
            # competitor and this changes nothing; a thread may always
            # raise its own nice value on Linux.
            try:
                os.setpriority(os.PRIO_PROCESS,
                               threading.get_native_id(), 19)
            except (AttributeError, OSError):  # pragma: no cover
                pass
        while True:
            with self._lock:
                if self._shutdown or gen != self._generation:
                    return
            try:
                worked = self._admit_some()
                if self.prefill_chunk is not None:
                    # at most ONE chunk of prefill work per iteration,
                    # so the decode dispatch below never waits behind
                    # more than prefill_chunk prompt tokens
                    worked = self._prefill_chunk_step() or worked
                if self._draft_program is not None:
                    stepped = self._spec_step_once()
                elif self.decode_horizon > 1:
                    stepped = self._step_fused_once()
                else:
                    stepped = self._step_once()
                worked = stepped or worked
            except Exception as e:
                obs_trace.instant("serve/replica_crash", cat="serve",
                                  kind="decode_step",
                                  error=type(e).__name__)
                self.metrics.inc("replica_crashes")
                self._drain_crashed(e)
                continue
            if not worked:
                self.batcher.wait_for_work(0.05)

    # -- radix prefix cache (host-side trie; loop thread + _lock) ----------

    def _iter_trie(self):
        stack = list(self._prefix_root.children.values())
        while stack:
            nd = stack.pop()
            stack.extend(nd.children.values())
            yield nd

    def _prefix_lookup(self, prompt: np.ndarray) -> List[_PrefixNode]:
        """Longest page-aligned prefix match, capped at (n-1)//page_size
        pages so the suffix prefill always has >= 1 real token (the
        last prompt token's logits seed the first sample)."""
        ps = self.program.page_size
        cap = (int(prompt.shape[0]) - 1) // ps
        node, nodes = self._prefix_root, []
        for j in range(cap):
            child = node.children.get(
                tuple(int(x) for x in prompt[j * ps:(j + 1) * ps]))
            if child is None:
                break
            nodes.append(child)
            node = child
        return nodes

    def _prefix_insert(self, s: _Slot, now: float) -> None:
        """Move the pages fully covered by ``s``'s prompt (beyond the
        matched prefix) from the slot's private list into the trie,
        refcount 1 (held by ``s`` until finish).  Runs on the loop
        thread right after a FINITE first sampled token, so the trie
        never holds rows from a poisoned prefill, and before any later
        admission — a same-prompt request in the same admit batch hits.
        Pages fully covered by the prompt are never written again (the
        first generated token lands at position n_prompt), so inserted
        pages are immutable from this point on."""
        ps = self.program.page_size
        prompt = s.spec.prompt
        node = s.shared_nodes[-1] if s.shared_nodes else self._prefix_root
        inserted = 0
        for j in range(s.n_matched, int(prompt.shape[0]) // ps):
            key = tuple(int(x) for x in prompt[j * ps:(j + 1) * ps])
            if key in node.children:
                # the match was suffix-capped below an existing node —
                # our duplicate page stays private, stop extending
                break
            child = _PrefixNode(key, s.page_ids.pop(0), node)
            child.refs = 1
            child.last_used = now
            node.children[key] = child
            s.shared_nodes.append(child)
            node = child
            inserted += 1
        if inserted:
            self._trie_pages += inserted
            self.metrics.inc("prefix_inserts", inserted)
            self.metrics.shared_pages.set(self._trie_pages)

    def _prefix_evict(self, need: int) -> int:
        """LRU eviction of refcount-zero LEAF nodes (a refs-0 node's
        children are refs-0 too, so leaves free first and parents become
        evictable as their subtree drains).  Evicted pages return to the
        free list WITHOUT a scrub: trie rows were validated finite at
        insert, and garbage-but-finite freed pages are the pool-wide
        convention.  ``last_used`` comes from the injectable engine
        clock (GC201)."""
        import heapq
        heap = [(nd.last_used, nd.page_id, nd) for nd in self._iter_trie()
                if nd.refs <= 0 and not nd.children]
        heapq.heapify(heap)
        freed = 0
        while heap and freed < need:
            _, _, nd = heapq.heappop(heap)
            if nd.children or nd.refs > 0 or nd.detached:
                continue
            nd.parent.children.pop(nd.key, None)
            nd.detached = True
            self._trie_pages -= 1
            self._free_pages.append(nd.page_id)
            freed += 1
            p = nd.parent
            if p is not self._prefix_root and p.refs <= 0 and not p.children:
                heapq.heappush(heap, (p.last_used, p.page_id, p))
        if freed:
            self.metrics.inc("prefix_evictions", freed)
            self.metrics.shared_pages.set(self._trie_pages)
        return freed

    def _debug_page_state(self) -> dict:
        """Diagnostic partition of page ids 1..total_pages-1: every page
        is exactly one of free / slot-private / trie-resident (the
        accounting invariant the hardening tests assert)."""
        with self._lock:
            return {
                "free": sorted(self._free_pages),
                "private": sorted(p for s in self._slots if s is not None
                                  for p in s.page_ids),
                "trie": sorted(nd.page_id for nd in self._iter_trie()),
            }

    def _admit_some(self) -> bool:
        """Join queued requests to the running batch: allocate pages +
        a slot (attaching the longest matching prefix read-only when the
        prefix cache is on), prefill, sample the first token (TTFT).
        Stops at the first request the pool cannot hold yet (FIFO order
        preserved)."""
        from ..ops.kv_cache import pages_for

        with self._lock:
            free = [i for i, s in enumerate(self._slots) if s is None]
        if not free:
            return False
        # chunked prefill's batch-formation rule: one admit round never
        # pulls in more prompt tokens than one chunk budget, so a wall
        # of long prompts enters the engine at the pace the chunk loop
        # can interleave (the head request is still always admitted)
        reqs = self.batcher.admit(len(free),
                                  token_budget=self.prefill_chunk)
        if not reqs:
            return False
        prog = self.program
        leftovers: List[Any] = []
        worked = False
        for r in reqs:
            if leftovers:           # keep FIFO once one request stalls
                leftovers.append(r)
                continue
            spec = r.payload
            handoff = getattr(spec, "handoff", None)
            transfer = None
            with self._lock:
                if r.model is None:
                    slot_tag = self._serve_tag
                else:
                    slot_tag = self._model_tags.get(r.model)
                    if slot_tag is not None:
                        self._model_last_used[r.model] = self.clock()
            if slot_tag is None:
                # evicted between admission and slot assignment: typed
                # failure, retryable at fleet level (demand reload)
                self.metrics.inc("errors")
                _fail_safe(r.future, ModelNotLoadedError(
                    f"model {r.model!r} was evicted from this decode host"))
                continue
            if handoff is not None:
                try:
                    # validate BEFORE any allocation: a corrupt transfer
                    # fails typed with the free list untouched
                    transfer = self._check_handoff(spec, handoff)
                except ValueError as e:
                    self.metrics.inc("errors")
                    _fail_safe(r.future, e)
                    continue
            if self.role == "prefill":
                # a prefill host never decodes — the slot only needs the
                # prompt's pages, exported and freed at handoff
                need_total = pages_for(int(spec.prompt.shape[0]),
                                       prog.page_size)
            else:
                max_total = min(int(spec.prompt.shape[0]) + spec.max_new,
                                prog.max_len)
                need_total = pages_for(max_total, prog.page_size)
            t_attach = self.clock()
            with self._lock:
                if not free:
                    leftovers.append(r)
                    continue
                matched = (self._prefix_lookup(spec.prompt)
                           if self._prefix_on else [])
                m = len(matched)
                need = need_total - m
                if len(self._free_pages) < need:
                    self._prefix_evict(need - len(self._free_pages))
                if len(self._free_pages) < need:
                    # no incref has happened yet, so a requeued request
                    # holds nothing — re-admission matches afresh (the
                    # no-double-decref-by-construction invariant)
                    leftovers.append(r)
                    continue
                i = free.pop(0)
                now = self.clock()
                for nd in matched:
                    nd.refs += 1
                    nd.last_used = now
                ids = [self._free_pages.popleft() for _ in range(need)]
                self._page_table[i] = 0
                self._page_table[i, :m] = [nd.page_id for nd in matched]
                self._page_table[i, m:m + need] = ids
                slot = _Slot(r, slot_tag, ids, spec.max_new)
                slot.shared_nodes = matched
                slot.n_matched = m
                self._slots[i] = slot
                self.metrics.active_slots.set(
                    sum(1 for s in self._slots if s is not None))
                self.metrics.pages_in_use.set(
                    self.total_pages - 1 - len(self._free_pages))
                self._refresh_pool_gauges_locked()
            if self._prefix_on:
                if m:
                    self.metrics.inc("prefix_hits")
                    self.metrics.inc("prefix_hit_tokens",
                                     m * prog.page_size)
                else:
                    self.metrics.inc("prefix_misses")
                obs_trace.complete_at(
                    "serve/prefix_attach", t_attach, self.clock(),
                    cat="serve", slot=i, matched_pages=m,
                    matched_tokens=m * prog.page_size)
            self.metrics.inc("requests")
            if transfer is not None:
                self._attach_handoff(i, transfer)
            elif self.role == "prefill":
                self._prefill_export(i)
            elif self.prefill_chunk is not None:
                # defer to the chunk loop: the slot holds its pages but
                # is not steppable until the last chunk samples token 0
                slot.n_prefilled = m * prog.page_size
            else:
                self._prefill_slot(i)
            worked = True
        for r in reversed(leftovers):
            self.batcher.requeue_front(r)
        return worked

    def _check_handoff(self, spec, handoff):
        """Unpack + shape-check an inbound transfer against THIS pool's
        layout (layers / page dims / kv dtype).  Raises ``ValueError``
        on any mismatch or corruption — called before page allocation so
        failure leaves the free list and page table untouched."""
        import jax

        from ..ops.kv_cache import pages_for, unpack_transfer

        transfer = unpack_transfer(handoff.pages)
        want = pages_for(int(spec.prompt.shape[0]), self.program.page_size)
        if transfer.n_pages != want:
            raise ValueError(
                f"handoff carries {transfer.n_pages} pages; a prompt of "
                f"{int(spec.prompt.shape[0])} tokens needs {want}")
        kp, _ = self._cache
        ref = jax.tree_util.tree_leaves(kp)
        got = jax.tree_util.tree_leaves(transfer.k)
        if len(ref) != len(got) or any(
                tuple(g.shape[2:]) != tuple(a.shape[2:])
                or g.dtype != a.dtype or g.shape[0] != a.shape[0]
                or g.shape[1] != transfer.n_pages
                for g, a in zip(got, ref)):
            raise ValueError(
                "handoff page payload does not match this engine's pool "
                "layout (n_layers / page dims / kv_dtype)")
        return transfer

    def _bucket_for(self, n: int) -> int:
        for b in self.prompt_buckets:
            if n <= b:
                return b
        return self.prompt_buckets[-1]

    def _prefill_slot(self, i: int) -> None:
        s = self._slots[i]
        spec = s.spec
        n = s.n_prompt
        m = s.n_matched * self.program.page_size   # matched prefix tokens
        t0 = self.clock()
        kp, vp = self._cache
        if m:
            # prefix-cache hit: prefill ONLY the unmatched suffix; the
            # shared pages already hold the prefix rows and the suffix
            # rows attend over them (prefill_at) — same per-row math as
            # a cold prefill, so the logits are bit-identical
            suffix = n - m
            bucket = self._bucket_for(suffix)
            padded = np.zeros((bucket,), np.int32)
            padded[:suffix] = spec.prompt[m:]
            kp, vp, lg = self._compiled[("prefill_at", bucket)](
                self._versions[s.tag], kp, vp, self._page_table[i], padded,
                np.int32(suffix), np.int32(m))
        else:
            bucket = self._bucket_for(n)
            padded = np.zeros((bucket,), np.int32)
            padded[:n] = spec.prompt
            kp, vp, lg = self._compiled[("prefill", bucket)](
                self._versions[s.tag], kp, vp, self._page_table[i], padded,
                np.int32(n))
        tok, fin = self._compiled[("sample1",)](
            lg, np.float32(spec.temperature), np.int32(spec.top_k),
            np.float32(spec.top_p), np.uint32(spec.seed), np.int32(0))
        self._cache = (kp, vp)
        if self._draft_program is not None:
            # mirror the prompt into the draft pool (same page ids, the
            # draft's dims) so proposals start from the right state
            dkp, dvp = self._draft_cache
            if m:
                dkp, dvp, _ = self._compiled[("draft_prefill_at", bucket)](
                    self._draft_params, dkp, dvp, self._page_table[i],
                    padded, np.int32(n - m), np.int32(m))
            else:
                dkp, dvp, _ = self._compiled[("draft_prefill", bucket)](
                    self._draft_params, dkp, dvp, self._page_table[i],
                    padded, np.int32(n))
            self._draft_cache = (dkp, dvp)
        tok_h = int(np.asarray(tok))
        fin_h = bool(np.asarray(fin))
        lg_h = np.asarray(lg) if spec.echo_logits else None
        t1 = self.clock()
        obs_trace.complete_at("serve/prefill", t0, t1, cat="serve", slot=i,
                              bucket=bucket, prompt_tokens=n, model=s.tag)
        self.metrics.inc("prefills")
        self.metrics.ttft.record((t1 - s.req.t_submit) * 1e3)
        s.t_first = t1
        if self._prefix_on and fin_h:
            # insert BEFORE recording the token so a same-prompt request
            # admitted next hits; gated on a finite first sample so a
            # poisoned prefill's rows never enter the trie
            with self._lock:
                self._prefix_insert(s, t1)
        self._record_token(i, tok_h, fin_h, lg_h, t1)

    def _prefill_chunk_step(self) -> bool:
        """Advance ONE pending chunked prefill by one chunk (at most
        ``prefill_chunk`` prompt tokens through the ``prefill_at``
        offset entry point), round-robin across slots mid-prefill so no
        single long prompt starves another.  The final chunk runs the
        ``_prefill_slot`` tail — sample token 0, TTFT, prefix insert —
        and the slot becomes steppable.  Chunk rows attend over all
        earlier rows already in the pool (same per-row math as a cold
        prefill), so the final logits are bit-identical to an unchunked
        prefill of the whole prompt."""
        with self._lock:
            pending = [i for i, s in enumerate(self._slots)
                       if s is not None and s.n_prefilled is not None]
            if not pending:
                return False
            start = self._chunk_cursor
            i = min(pending, key=lambda x: (x - start) % self.max_slots)
            self._chunk_cursor = (i + 1) % self.max_slots
            s = self._slots[i]
        spec = s.spec
        n = s.n_prompt
        p = s.n_prefilled
        first_offset = s.n_matched * self.program.page_size
        take = min(self.prefill_chunk, n - p)
        bucket = self._bucket_for(take)
        padded = np.zeros((bucket,), np.int32)
        padded[:take] = spec.prompt[p:p + take]
        t0 = self.clock()
        kp, vp = self._cache
        kp, vp, lg = self._compiled[("prefill_at", bucket)](
            self._versions[s.tag], kp, vp, self._page_table[i], padded,
            np.int32(take), np.int32(p))
        self._cache = (kp, vp)
        self.metrics.inc("prefill_chunks")
        if p + take < n:
            t1 = self.clock()
            obs_trace.complete_at(
                "serve/prefill", t0, t1, cat="serve", slot=i,
                bucket=bucket, prompt_tokens=take, offset=p, model=s.tag)
            s.n_prefilled = p + take
            return True
        # final chunk — the _prefill_slot tail
        tok, fin = self._compiled[("sample1",)](
            lg, np.float32(spec.temperature), np.int32(spec.top_k),
            np.float32(spec.top_p), np.uint32(spec.seed), np.int32(0))
        tok_h = int(np.asarray(tok))
        fin_h = bool(np.asarray(fin))
        lg_h = np.asarray(lg) if spec.echo_logits else None
        t1 = self.clock()
        obs_trace.complete_at(
            "serve/prefill", t0, t1, cat="serve", slot=i, bucket=bucket,
            prompt_tokens=take, offset=p, model=s.tag)
        self.metrics.inc("prefills")
        if p > first_offset:
            self.metrics.inc("chunked_prefills")   # took >= 2 chunks
        self.metrics.ttft.record((t1 - s.req.t_submit) * 1e3)
        s.t_first = t1
        s.n_prefilled = None
        if self._prefix_on and fin_h:
            with self._lock:
                self._prefix_insert(s, t1)
        self._record_token(i, tok_h, fin_h, lg_h, t1)
        return True

    def _attach_handoff(self, i: int, transfer) -> None:
        """Decode-stage admission: scatter the prefill host's exported
        page payload into this slot's freshly-allocated private pages
        (rows below a local prefix match are deduped — they target the
        scratch page and the shared pages serve those rows), then record
        the already-sampled first token.  One AOT dispatch; position and
        sampling-step bookkeeping land exactly where a local prefill
        would have left them, so the continuation is bit-identical."""
        s = self._slots[i]
        h = s.spec.handoff
        pps = self.program.pages_per_slot
        m = s.n_matched
        p_pro = transfer.n_pages
        t0 = self.clock()
        ids = np.zeros((pps,), np.int32)        # scratch: write discarded
        ids[m:p_pro] = self._page_table[i][m:p_pro]

        def _pad(side):
            import jax

            def one(a):
                full = np.zeros((a.shape[0], pps) + tuple(a.shape[2:]),
                                a.dtype)
                full[:, m:p_pro] = a[:, m:p_pro]
                return full
            return jax.tree_util.tree_map(one, side)

        kp, vp = self._cache
        kp, vp = self._compiled[("attach",)](
            kp, vp, ids, _pad(transfer.k), _pad(transfer.v))
        self._cache = (kp, vp)
        t1 = self.clock()
        obs_trace.complete_at("serve/prefill", t0, t1, cat="serve", slot=i,
                              bucket=0, prompt_tokens=s.n_prompt,
                              model=s.tag, attached_pages=p_pro - m)
        self.metrics.inc("prefills")
        self.metrics.inc("handoffs_in")
        self.metrics.inc("pages_attached", p_pro - m)
        if m:
            self.metrics.inc("pages_deduped", m)
        self.metrics.ttft.record((t1 - s.req.t_submit) * 1e3)
        s.t_first = t1
        fin_h = bool(h.finite)
        if self._prefix_on and fin_h:
            with self._lock:
                self._prefix_insert(s, t1)
        lg_h = (np.asarray(h.logits0, np.float32)
                if s.spec.echo_logits and h.logits0 is not None else None)
        self._record_token(i, int(h.first_token), fin_h, lg_h, t1)

    def _prefill_export(self, i: int) -> None:
        """Prefill-role terminal: run the standard prefill + first-token
        sample, then EXPORT the slot — gather the prompt's KV pages into
        a packed transfer, resolve the future with a
        ``PrefillHandoff``, and free the slot immediately (a prefill
        host never decodes).  A poisoned prefill is isolated HERE and
        never crosses the wire."""
        import jax

        from ..ops.kv_cache import PageTransfer, pack_transfer, pages_for

        s = self._slots[i]
        spec = s.spec
        n = s.n_prompt
        m = s.n_matched * self.program.page_size
        t0 = self.clock()
        kp, vp = self._cache
        if m:
            suffix = n - m
            bucket = self._bucket_for(suffix)
            padded = np.zeros((bucket,), np.int32)
            padded[:suffix] = spec.prompt[m:]
            kp, vp, lg = self._compiled[("prefill_at", bucket)](
                self._versions[s.tag], kp, vp, self._page_table[i], padded,
                np.int32(suffix), np.int32(m))
        else:
            bucket = self._bucket_for(n)
            padded = np.zeros((bucket,), np.int32)
            padded[:n] = spec.prompt
            kp, vp, lg = self._compiled[("prefill", bucket)](
                self._versions[s.tag], kp, vp, self._page_table[i], padded,
                np.int32(n))
        tok, fin = self._compiled[("sample1",)](
            lg, np.float32(spec.temperature), np.int32(spec.top_k),
            np.float32(spec.top_p), np.uint32(spec.seed), np.int32(0))
        self._cache = (kp, vp)
        tok_h = int(np.asarray(tok))
        fin_h = bool(np.asarray(fin))
        t1 = self.clock()
        obs_trace.complete_at("serve/prefill", t0, t1, cat="serve", slot=i,
                              bucket=bucket, prompt_tokens=n, model=s.tag)
        self.metrics.inc("prefills")
        self.metrics.ttft.record((t1 - s.req.t_submit) * 1e3)
        s.t_first = t1
        if not fin_h:
            self.metrics.inc("poison_isolated")
            self._scrub_pages(s.page_ids)
            self._finish(i, t1, error=PoisonInputError(
                f"prefill produced non-finite logits (slot {i}) — "
                "handoff suppressed, request isolated"))
            return
        if self._prefix_on:
            with self._lock:
                self._prefix_insert(s, t1)
        p_pro = pages_for(n, self.program.page_size)
        k_pages, v_pages = self._compiled[("extract",)](
            kp, vp, self._page_table[i])
        k_np = jax.tree_util.tree_map(
            lambda a: np.asarray(a)[:, :p_pro].copy(), k_pages)
        v_np = jax.tree_util.tree_map(
            lambda a: np.asarray(a)[:, :p_pro].copy(), v_pages)
        payload = pack_transfer(PageTransfer(n_pages=p_pro, k=k_np, v=v_np))
        handoff = PrefillHandoff(
            prompt=spec.prompt, max_new=s.max_new,
            temperature=spec.temperature, top_k=spec.top_k,
            top_p=spec.top_p, seed=spec.seed,
            echo_logits=spec.echo_logits, first_token=tok_h, finite=True,
            n_pages=p_pro, pages=payload,
            logits0=np.asarray(lg).copy() if spec.echo_logits else None,
            model_tag=s.tag)
        self.metrics.inc("handoffs_out")
        self.metrics.inc("pages_exported", p_pro)
        now = self.clock()
        with self._lock:
            self._slots[i] = None
            self._free_pages.extend(s.page_ids)
            for nd in reversed(s.shared_nodes):
                nd.refs -= 1
                nd.last_used = now
            s.shared_nodes = []
            self._page_table[i] = 0
            live_tags = {sl.tag for sl in self._slots if sl is not None}
            live_tags.add(self._serve_tag)
            live_tags.update(self._model_tags.values())
            for t in [t for t in self._versions if t not in live_tags]:
                del self._versions[t]
            self.metrics.active_slots.set(
                sum(1 for sl in self._slots if sl is not None))
            self.metrics.pages_in_use.set(
                self.total_pages - 1 - len(self._free_pages))
            self._refresh_pool_gauges_locked()
        _set_safe(s.req.future, handoff)
        obs_trace.complete_at("serve/request", s.req.t_submit, now,
                              cat="serve", kind="prefill_handoff",
                              tokens=1, finish="handoff")

    def _step_once(self) -> bool:
        """One decode step per distinct active version tag (same
        executable, that tag's params, that tag's slots active) — the
        no-version-mixing hot-swap invariant lives here."""
        s_n = self.max_slots
        with self._lock:
            tags: List[str] = []
            for s in self._slots:
                if (s is not None and s.n_prefilled is None
                        and s.tag not in tags):
                    tags.append(s.tag)
            crash = self._crash_next
            self._crash_next = False
        if crash:
            raise ReplicaCrashError("injected decode-batch crash (test hook)")
        if not tags:
            return False
        for tag in tags:
            toks_in = np.zeros((s_n,), np.int32)
            pos = np.zeros((s_n,), np.int32)
            act = np.zeros((s_n,), bool)
            temps = np.zeros((s_n,), np.float32)
            tks = np.zeros((s_n,), np.int32)
            tps = np.ones((s_n,), np.float32)
            seeds = np.zeros((s_n,), np.uint32)
            steps = np.zeros((s_n,), np.int32)
            group: List[int] = []
            echo = False
            with self._lock:
                params = self._versions.get(tag)
                if params is None:
                    continue
                for i, s in enumerate(self._slots):
                    if (s is None or s.tag != tag
                            or s.n_prefilled is not None):
                        continue
                    group.append(i)
                    toks_in[i] = s.last_token
                    pos[i] = s.pos
                    act[i] = True
                    temps[i] = s.spec.temperature
                    tks[i] = s.spec.top_k
                    tps[i] = s.spec.top_p
                    seeds[i] = s.spec.seed
                    steps[i] = s.n_out
                    echo = echo or s.logits is not None
            if not group:
                continue
            t0 = self.clock()
            kp, vp = self._cache
            kp, vp, lgs = self._compiled[("step",)](
                params, kp, vp, self._page_table, toks_in, pos, act)
            t_step = self.clock()
            toks, fin = self._compiled[("sample",)](
                lgs, temps, tks, tps, seeds, steps)
            self._cache = (kp, vp)
            toks_h = np.asarray(toks)
            fin_h = np.asarray(fin)
            lgs_h = np.asarray(lgs) if echo else None
            t1 = self.clock()
            obs_trace.complete_at("serve/decode_step", t0, t1, cat="serve",
                                  n_active=len(group), model=tag, tokens=1,
                                  step_ms=round((t_step - t0) * 1e3, 3),
                                  sample_ms=round((t1 - t_step) * 1e3, 3))
            if getattr(self.program, "tp", 1) > 1:
                obs_trace.complete_at(
                    "serve/shard_step", t0, t1, cat="serve",
                    n_active=len(group), shards=int(self.program.tp),
                    model=tag)
            self.metrics.inc("decode_steps")
            self.metrics.step_time.record((t1 - t0) * 1e3)
            for i in group:
                with self._lock:
                    s = self._slots[i]
                if s is not None:
                    s.pos += 1
                    self._record_token(
                        i, int(toks_h[i]), bool(fin_h[i]),
                        lgs_h[i].copy() if (lgs_h is not None
                                            and s.logits is not None)
                        else None, t1)
        return True

    def _step_fused_once(self) -> bool:
        """One FUSED dispatch per distinct active version tag: H =
        ``decode_horizon`` decode steps plus device-resident sampling
        run inside the single ``("step_multi", H)`` executable, and the
        host syncs once per H tokens.  Host bookkeeping then replays
        the H (token, finite) pairs through ``_record_token`` exactly
        as H plain steps would have — a slot that stops mid-horizon
        (EOS / budget / poison / deadline) frees at the same token, and
        the device's post-stop overrun (≤ H-1 tokens, routed to the
        scratch page on device) is simply not recorded.  Host slot
        state is only mutated AFTER the dispatch returns, so a crash
        anywhere inside the horizon retries from the last committed
        token and regenerates identical bits (seeded counter-based
        sampling)."""
        s_n = self.max_slots
        H = self.decode_horizon
        with self._lock:
            tags: List[str] = []
            for s in self._slots:
                if (s is not None and s.n_prefilled is None
                        and s.tag not in tags):
                    tags.append(s.tag)
            crash = self._crash_next
            self._crash_next = False
        if crash and not tags:
            raise ReplicaCrashError("injected decode-batch crash (test hook)")
        if not tags:
            return False
        eos = np.int32(self.eos_id if self.eos_id is not None else -1)
        for tag in tags:
            toks_in = np.zeros((s_n,), np.int32)
            pos = np.zeros((s_n,), np.int32)
            act = np.zeros((s_n,), bool)
            temps = np.zeros((s_n,), np.float32)
            tks = np.zeros((s_n,), np.int32)
            tps = np.ones((s_n,), np.float32)
            seeds = np.zeros((s_n,), np.uint32)
            steps = np.zeros((s_n,), np.int32)
            budgets = np.ones((s_n,), np.int32)
            group: List[int] = []
            echo = False
            with self._lock:
                params = self._versions.get(tag)
                if params is None:
                    continue
                for i, s in enumerate(self._slots):
                    if (s is None or s.tag != tag
                            or s.n_prefilled is not None):
                        continue
                    group.append(i)
                    toks_in[i] = s.last_token
                    pos[i] = s.pos
                    act[i] = True
                    temps[i] = s.spec.temperature
                    tks[i] = s.spec.top_k
                    tps[i] = s.spec.top_p
                    seeds[i] = s.spec.seed
                    steps[i] = s.n_out
                    budgets[i] = max(1, s.max_new - s.n_out)
                    echo = echo or s.logits is not None
            if not group:
                continue
            t0 = self.clock()
            kp, vp = self._cache
            kp, vp, toks, fins, lgs = self._compiled[("step_multi", H)](
                params, kp, vp, self._page_table, toks_in, pos, act,
                temps, tks, tps, seeds, steps, budgets, eos,
                np.arange(H, dtype=np.int32))
            self._cache = (kp, vp)
            toks_h = np.asarray(toks)      # [H, S]
            fins_h = np.asarray(fins)
            lgs_h = np.asarray(lgs) if echo else None
            t1 = self.clock()
            if crash:
                # "mid-horizon" from the host's view: the device has
                # advanced H tokens but NONE are committed — recovery
                # must retry from the last committed token
                raise ReplicaCrashError(
                    "injected decode-batch crash (test hook)")
            obs_trace.complete_at("serve/decode_step", t0, t1, cat="serve",
                                  n_active=len(group), model=tag, tokens=H,
                                  step_ms=round((t1 - t0) * 1e3, 3),
                                  sample_ms=0.0)
            if getattr(self.program, "tp", 1) > 1:
                obs_trace.complete_at(
                    "serve/shard_step", t0, t1, cat="serve",
                    n_active=len(group), shards=int(self.program.tp),
                    model=tag)
            self.metrics.inc("decode_steps")
            self.metrics.inc("fused_dispatches")
            self.metrics.step_time.record((t1 - t0) * 1e3)
            committed = 0
            for i in group:
                for j in range(H):
                    with self._lock:
                        s = self._slots[i]
                    if s is None:
                        break       # stopped mid-horizon; drop overrun
                    s.pos += 1
                    fin_j = bool(fins_h[j, i])
                    self._record_token(
                        i, int(toks_h[j, i]), fin_j,
                        lgs_h[j, i].copy() if (lgs_h is not None
                                               and s.logits is not None)
                        else None, t1)
                    if fin_j:
                        committed += 1
            self.metrics.inc("tokens_per_dispatch", committed)
        return True

    def _spec_step_once(self) -> bool:
        """One speculative round per distinct active version tag: k
        sequential draft steps propose tokens, the target verifies all
        k+1 rows in ONE fixed-shape ``spec_step`` dispatch
        (``serve/spec_verify``), and seeded rejection sampling commits
        1..k+1 tokens per slot.  Rejected rows' K/V garbage is always
        overwritten before it can be unmasked (the next round's writes
        start at the new position and cover the old speculative range).
        After a FULL acceptance the draft pool is one row behind, so a
        catch-up draft step writes the last proposal's row — without it
        every fully-accepted round would degrade later proposals."""
        s_n = self.max_slots
        k = self.speculate_k
        with self._lock:
            tags: List[str] = []
            for s in self._slots:
                if s is not None and s.tag not in tags:
                    tags.append(s.tag)
            crash = self._crash_next
            self._crash_next = False
        if crash:
            raise ReplicaCrashError("injected decode-batch crash (test hook)")
        if not tags:
            return False
        for tag in tags:
            toks_in = np.zeros((s_n,), np.int32)
            pos = np.zeros((s_n,), np.int32)
            act = np.zeros((s_n,), bool)
            temps = np.zeros((s_n,), np.float32)
            tks = np.zeros((s_n,), np.int32)
            tps = np.ones((s_n,), np.float32)
            seeds = np.zeros((s_n,), np.uint32)
            steps = np.zeros((s_n,), np.int32)
            group: List[int] = []
            echo = False
            with self._lock:
                params = self._versions.get(tag)
                if params is None:
                    continue
                for i, s in enumerate(self._slots):
                    if s is None or s.tag != tag:
                        continue
                    group.append(i)
                    toks_in[i] = s.last_token
                    pos[i] = s.pos
                    act[i] = True
                    temps[i] = s.spec.temperature
                    tks[i] = s.spec.top_k
                    tps[i] = s.spec.top_p
                    seeds[i] = s.spec.seed
                    steps[i] = s.n_out
                    echo = echo or s.logits is not None
            if not group:
                continue
            t0 = self.clock()
            dkp, dvp = self._draft_cache
            cur = toks_in
            d_toks_dev, d_probs_dev = [], []
            for j in range(k):
                dkp, dvp, dlgs = self._compiled[("draft_step",)](
                    self._draft_params, dkp, dvp, self._page_table, cur,
                    pos + j, act)
                d_tok, d_prob = self._compiled[("propose",)](
                    dlgs, temps, tks, tps, seeds, steps + j)
                d_toks_dev.append(d_tok)
                d_probs_dev.append(d_prob)
                cur = d_tok
            self._draft_cache = (dkp, dvp)
            d_toks = np.stack([np.asarray(t) for t in d_toks_dev],
                              1).astype(np.int32)          # [S, k]
            spec_tokens = np.concatenate([toks_in[:, None], d_toks], 1)
            kp, vp = self._cache
            tv0 = self.clock()
            kp, vp, lgs = self._compiled[("spec_step",)](
                params, kp, vp, self._page_table, spec_tokens, pos, act)
            n_commit, commit, fin = self._compiled[("spec_accept",)](
                lgs, d_toks,
                np.stack([np.asarray(p) for p in d_probs_dev], 1),
                temps, tks, tps, seeds, steps)
            self._cache = (kp, vp)
            nc_h = np.asarray(n_commit)
            cm_h = np.asarray(commit)
            fin_h = np.asarray(fin)
            lgs_h = np.asarray(lgs) if echo else None
            t1 = self.clock()
            obs_trace.complete_at("serve/spec_verify", tv0, t1, cat="serve",
                                  n_active=len(group), k=k, model=tag)
            self.metrics.inc("decode_steps")
            self.metrics.step_time.record((t1 - t0) * 1e3)
            self.metrics.inc("spec_steps")
            self.metrics.inc("spec_proposed", k * len(group))
            committed = 0
            catchup = np.zeros((s_n,), bool)
            cu_tok = np.zeros((s_n,), np.int32)
            for i in group:
                c = int(nc_h[i])
                self.metrics.inc("spec_accepted", c - 1)
                for j in range(c):
                    with self._lock:
                        s = self._slots[i]
                    if s is None:      # stopped mid-commit (eos/max/...)
                        break
                    s.pos += 1
                    committed += 1
                    self._record_token(
                        i, int(cm_h[i, j]), bool(fin_h[i]),
                        lgs_h[i, j].copy() if (lgs_h is not None
                                               and s.logits is not None)
                        else None, t1)
                with self._lock:
                    alive = self._slots[i] is not None
                if alive and c == k + 1:
                    catchup[i] = True
                    cu_tok[i] = d_toks[i, k - 1]
            self.metrics.inc("spec_committed", committed)
            if catchup.any():
                dkp, dvp = self._draft_cache
                dkp, dvp, _ = self._compiled[("draft_step",)](
                    self._draft_params, dkp, dvp, self._page_table, cu_tok,
                    pos + k, catchup)
                self._draft_cache = (dkp, dvp)
        return True

    # -- per-token bookkeeping + stop conditions ---------------------------

    def _record_token(self, i: int, token: int, finite: bool,
                      logits_row: Optional[np.ndarray], now: float) -> None:
        s = self._slots[i]
        if s is None:
            return
        if not finite:
            self.metrics.inc("poison_isolated")
            self._scrub_pages(s.page_ids)
            self._finish(i, now, error=PoisonInputError(
                f"decode produced non-finite logits at token {s.n_out} "
                f"(slot {i}) — request isolated, co-batched slots "
                "unaffected"))
            return
        s.tokens.append(token)
        s.n_out += 1
        s.last_token = token
        s.t_last = now
        if s.logits is not None and logits_row is not None:
            s.logits.append(logits_row)
        self.metrics.inc("tokens_out")
        if self.eos_id is not None and token == self.eos_id:
            self._finish(i, now, reason="eos")
        elif s.n_out >= s.max_new:
            self._finish(i, now, reason="max_tokens")
        elif now > s.deadline:
            # mid-decode deadline is a STOP condition, not an error: the
            # caller gets the tokens produced inside the budget
            self._finish(i, now, reason="deadline")

    def _scrub_pages(self, page_ids: List[int]) -> None:
        """Zero freed pages that may hold non-finite rows — a NaN left
        behind would poison the page's next tenant (0 * NaN = NaN).
        Only ever called with a slot's PRIVATE pages: shared prefix
        pages are read-only to their holders and validated finite at
        insert, so a scrub can never hit a page another request still
        references — the no-scrub-while-shared discipline."""
        pps = self.program.pages_per_slot
        ids = np.full((pps,), page_ids[0], np.int32)
        ids[:len(page_ids)] = page_ids
        kp, vp = self._cache
        self._cache = self._compiled[("scrub",)](kp, vp, ids)
        if self._draft_program is not None:
            dkp, dvp = self._draft_cache
            self._draft_cache = self._compiled[("draft_scrub",)](
                dkp, dvp, ids)

    def _finish(self, i: int, now: float, reason: Optional[str] = None,
                error: Optional[BaseException] = None) -> None:
        with self._lock:
            s = self._slots[i]
            if s is None:
                return
            self._slots[i] = None
            self._free_pages.extend(s.page_ids)
            for nd in reversed(s.shared_nodes):
                # decref, never free: trie pages stay resident for the
                # next shared-prefix request until LRU eviction
                nd.refs -= 1
                nd.last_used = now
            s.shared_nodes = []
            self._page_table[i] = 0
            live_tags = {sl.tag for sl in self._slots if sl is not None}
            live_tags.add(self._serve_tag)
            live_tags.update(self._model_tags.values())
            for t in [t for t in self._versions if t not in live_tags]:
                del self._versions[t]
            self.metrics.active_slots.set(
                sum(1 for sl in self._slots if sl is not None))
            self.metrics.pages_in_use.set(
                self.total_pages - 1 - len(self._free_pages))
            self._refresh_pool_gauges_locked()
        if error is not None:
            self.metrics.inc("errors")
            _fail_safe(s.req.future, error)
        else:
            self.metrics.inc({"eos": "eos_stops",
                              "max_tokens": "max_token_stops",
                              "deadline": "deadline_stops"}[reason])
            tpot = ((s.t_last - s.t_first) * 1e3 / (s.n_out - 1)
                    if s.n_out > 1 else None)
            if tpot is not None:
                self.metrics.tpot.record(tpot)
            _set_safe(s.req.future, GenerationResult(
                tokens=list(s.tokens), n_prompt=s.n_prompt,
                finish_reason=reason, model_tag=s.tag,
                ttft_ms=round((s.t_first - s.req.t_submit) * 1e3, 3),
                tpot_ms=round(tpot, 3) if tpot is not None else None,
                logits=np.stack(s.logits) if s.logits else None))
        obs_trace.complete_at("serve/request", s.req.t_submit, now,
                              cat="serve", kind="generate", tokens=s.n_out,
                              finish=reason or "error")

    # -- crash recovery ----------------------------------------------------

    def _drain_crashed(self, exc: BaseException) -> None:
        """Fail or retry every in-flight request after a decode-batch
        crash, reset the pool, keep serving.  Retries regenerate the
        identical sequence (seeded counter-based sampling), so a retry
        is indistinguishable from a slow first attempt."""
        with self._lock:
            in_flight = [s for s in self._slots if s is not None]
            self._slots = [None] * self.max_slots
            self._free_pages = deque(range(1, self.total_pages))
            self._page_table[:] = 0
            # the prefix trie dies with the pool: slots are wiped WITHOUT
            # decref and the trie is rebuilt empty, so a retried
            # prefix-hit request re-matches from scratch — a crash-retry
            # can never double-decref a shared page
            self._prefix_root = _PrefixNode((), None, None)
            self._trie_pages = 0
            self.metrics.shared_pages.set(0)
            self.metrics.active_slots.set(0)
            self.metrics.pages_in_use.set(0)
            self._refresh_pool_gauges_locked()
        # the crash may have left non-finite rows anywhere — zero the pool
        kp, vp = self._cache
        self._cache = self._compiled[("reset",)](kp, vp)
        if self._draft_program is not None:
            dkp, dvp = self._draft_cache
            self._draft_cache = self._compiled[("draft_reset",)](dkp, dvp)
        now = self.clock()
        for s in in_flight:
            r = s.req
            r.retries += 1
            if r.retries <= self.max_retries and r.deadline > now \
                    and not r.future.done():
                self.metrics.inc("retries")
                obs_trace.instant("serve/retry", cat="serve", kind="decode",
                                  retries=r.retries)
                self.batcher.requeue_front(r)
            else:
                self.metrics.inc("errors")
                _fail_safe(r.future, ReplicaCrashError(
                    f"decode batch crashed ({type(exc).__name__}: {exc}) "
                    f"after {s.n_out} tokens; retry budget exhausted"))

    # -- observability / shutdown ------------------------------------------

    def _refresh_pool_gauges_locked(self) -> None:
        """Keep the free-capacity gauges live — the fleet router scores
        decode sinks by them (docs/SERVING.md "Disaggregated and
        sharded decode").  Caller holds ``self._lock``."""
        self.metrics.free_pages.set(len(self._free_pages))
        self.metrics.free_slots.set(
            sum(1 for s in self._slots if s is None))

    def metrics_snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        with self._lock:
            snap["model"] = self._serve_tag
            snap["versions"] = sorted(self._versions)
            snap["models"] = {"": self._serve_tag, **self._model_tags}
        if self.tenants is not None:
            snap["tenants"] = self.tenants.snapshot()
        with self._lock:
            snap["queue_depth"] = self.batcher.qsize()
            snap["free_pages"] = len(self._free_pages)
            snap["free_slots"] = sum(1 for s in self._slots if s is None)
        snap["compile_cache_size"] = self.compile_cache_size()
        snap["prompt_buckets"] = list(self.prompt_buckets)
        snap["max_slots"] = self.max_slots
        snap["total_pages"] = self.total_pages
        snap["pages_per_slot"] = self.program.pages_per_slot
        snap["prefix_cache"] = self._prefix_on
        snap["speculate_k"] = (self.speculate_k
                               if self._draft_program is not None else 0)
        snap["kv_dtype"] = self._kv_dtype or "float32"
        snap["role"] = self.role
        snap["tp"] = int(getattr(self.program, "tp", 1))
        snap["decode_horizon"] = self.decode_horizon
        snap["prefill_chunk"] = self.prefill_chunk
        return snap

    def health_snapshot(self) -> dict:
        with self._lock:
            t = self._thread
            ready = (self._loaded and not self._shutdown
                     and t is not None and t.is_alive())
        return {"status": "ready" if ready else "unready", "ready": ready,
                "kind": "decode", "model": self.current_tag}

    def begin_drain(self) -> None:
        """Stop admission (new submissions shed → 429) while queued and
        in-flight generations complete — the decode half of the
        graceful SIGTERM drain (docs/SERVING.md)."""
        self.batcher.begin_drain()
        self.metrics.inc("drains")
        obs_trace.instant("serve/drain", cat="serve")

    def shutdown(self) -> None:
        """Idempotent; every queued AND in-flight future resolves."""
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            self._generation += 1
            in_flight = [s for s in self._slots if s is not None]
            self._slots = [None] * self.max_slots
        self._stop.set()
        self.batcher.close(fail_pending=True)
        for s in in_flight:
            _fail_safe(s.req.future,
                       RuntimeError("serving engine is shut down"))
        for t in (self._thread, self._supervisor):
            if t is not None and t is not threading.current_thread():
                t.join(timeout=5)
