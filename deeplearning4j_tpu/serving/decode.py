"""Autoregressive decode engine: paged KV-cache + continuous batching.

A genuinely different execution mode from the one-shot ``Engine``:
stateful (the KV-cache carries across steps), multi-step (one request
spans many device dispatches), and shape-bucketed in TWO dimensions
(prompt length at prefill, slot count at decode).  The design:

  prefill/decode split
      A request's prompt runs ONCE through a bucketed prefill program
      (one AOT-compiled executable per prompt bucket) that writes K/V
      for every prompt position into the request's cache pages and
      samples the first token — so TTFT is one prefill dispatch, not
      ``n_prompt`` decode steps.  After that, every token costs one
      fixed-shape decode step.

  iteration-level continuous batching
      The decode step always runs over ALL ``max_slots`` slots with an
      active mask (masked slots write to the scratch page — see
      ops/kv_cache.py), so its compiled shape never changes and a new
      request can join the running batch at the NEXT step boundary
      (``ContinuousBatcher.admit``) instead of waiting for the batch to
      drain.  Zero serve-time compiles is therefore structural: the
      serve path only ever calls executables built at ``load()``
      (``compile_cache_size()`` is the witness, same contract as the
      one-shot engine).

  per-request stop conditions
      EOS / max-tokens / deadline are checked after every sampled
      token; a stopped request resolves immediately and its cache pages
      go back to the free list the same step — the pool oversubscribes
      slots when request lengths vary.

  resilience (the PR-7 supervisor patterns, decode-shaped)
      A crash anywhere in the decode loop fails or RETRIES every
      in-flight request (sampling is seeded + counter-based, so a retry
      regenerates the identical sequence), resets the pool, and keeps
      serving; a supervisor thread respawns the loop if it dies
      outright.  Poison isolation is per-slot: non-finite logits fail
      only that slot's request (its pages are scrubbed — a NaN left in
      a freed page would contaminate the next tenant), co-batched slots
      never notice.  Every future resolves on every path.

  hot-swap without version mixing
      ``swap_model`` flips the tag NEW admissions use; in-flight slots
      keep decoding under the version that prefilled them (the decode
      step runs once per distinct active tag — same executable,
      different params), so no request ever mixes versions and a swap
      never stalls the batch.  ``attach_registry`` wires this to
      ``ModelRegistry.set_alias``.

Sampling is greedy / temperature / top-k / top-p, seeded and
deterministic: the PRNG key is ``fold_in(PRNGKey(seed), token_index)``,
so a sequence is a pure function of (params, prompt, sampling spec) —
the property the retry path and the A/B bit-identity gate both lean on.

TTFT and time-per-output-token are first-class (``DecodeMetrics``,
``serve/prefill`` / ``serve/decode_step`` spans — docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..obs import trace as obs_trace
from .batcher import ContinuousBatcher, pow2_buckets
from .engine import PoisonInputError, ReplicaCrashError, _fail_safe, _set_safe
from .metrics import DecodeMetrics

FINISH_REASONS = ("eos", "max_tokens", "deadline")


@dataclass
class GenerationResult:
    """One finished generation.  ``tokens`` are the GENERATED ids only
    (prompt excluded; a terminating EOS is included).  ``logits`` is
    [n_tokens, vocab] float32 when the request asked ``echo_logits``
    (the bit-identity gate's evidence), else None."""

    tokens: List[int]
    n_prompt: int
    finish_reason: str
    model_tag: str
    ttft_ms: float
    tpot_ms: Optional[float]
    logits: Optional[np.ndarray] = None


@dataclass(frozen=True)
class _GenSpec:
    """Immutable request payload — a crash-retry re-runs exactly this."""

    prompt: np.ndarray
    max_new: int
    temperature: float
    top_k: int
    top_p: float
    seed: int
    echo_logits: bool


class _Slot:
    """Host-side state of one occupied decode slot."""

    __slots__ = ("req", "spec", "tag", "page_ids", "n_prompt", "pos",
                 "last_token", "tokens", "n_out", "max_new", "deadline",
                 "t_first", "t_last", "logits")

    def __init__(self, req, tag: str, page_ids: List[int], max_new: int):
        self.req = req
        self.spec = req.payload
        self.tag = tag
        self.page_ids = page_ids
        self.n_prompt = int(self.spec.prompt.shape[0])
        self.pos = self.n_prompt      # where the NEXT input token lands
        self.last_token = 0
        self.tokens: List[int] = []
        self.n_out = 0
        self.max_new = max_new
        self.deadline = req.deadline
        self.t_first = 0.0
        self.t_last = 0.0
        self.logits: Optional[List[np.ndarray]] = \
            [] if self.spec.echo_logits else None


def _make_samplers(vocab_size: int):
    """(sample_one, sample_batch) pure fns.  Deterministic: the key is
    ``fold_in(PRNGKey(seed), step)`` — same (seed, step) → same draw.
    temperature <= 0 is greedy; top_k == 0 and top_p >= 1 disable those
    filters.  Also returns the all-finite flag the poison check reads.
    """
    import jax
    import jax.numpy as jnp

    def sample_one(lg, t, k, p, seed, step):
        finite = jnp.all(jnp.isfinite(lg))
        greedy = jnp.argmax(lg).astype(jnp.int32)
        scaled = lg / jnp.maximum(t, 1e-6)
        srt = jnp.sort(scaled)[::-1]
        kk = jnp.clip(jnp.where(k > 0, k, vocab_size), 1, vocab_size)
        thr_k = srt[kk - 1]
        probs = jax.nn.softmax(srt)
        cum_excl = jnp.cumsum(probs) - probs   # mass BEFORE each entry
        keep = cum_excl < jnp.clip(p, 1e-6, 1.0)  # top-1 always kept
        thr_p = jnp.min(jnp.where(keep, srt, jnp.inf))
        thr = jnp.maximum(thr_k, thr_p)
        masked = jnp.where(scaled >= thr, scaled, -jnp.inf)
        g = jax.random.gumbel(
            jax.random.fold_in(jax.random.PRNGKey(seed), step), lg.shape)
        sampled = jnp.argmax(masked + g).astype(jnp.int32)
        return jnp.where(t <= 0.0, greedy, sampled), finite

    def sample_batch(lgs, ts, ks, ps, seeds, steps):
        return jax.vmap(sample_one)(lgs, ts, ks, ps, seeds, steps)

    return sample_one, sample_batch


class DecodeEngine:
    """``DecodeEngine(lm).load()`` then ``generate(prompt_ids, ...)``.

    ``model`` provides ``decode_program()`` (ShardedTransformerLM) — the
    pure prefill/step/re-encode functions of ops/kv_cache.DecodeProgram.
    ``clock`` is injectable (monotonic seconds) so deadline/TTFT logic
    is testable without sleeping.
    """

    def __init__(self, model, *, max_slots: int = 4, page_size: int = 16,
                 max_len: Optional[int] = None,
                 total_pages: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 eos_id: Optional[int] = None, slo_ms: float = 30_000.0,
                 max_queue: int = 256, admission: str = "block",
                 max_retries: int = 1, default_max_new: int = 32,
                 clock=time.monotonic, tag: str = "v0",
                 metrics: Optional[DecodeMetrics] = None):
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        self.program = model.decode_program(page_size=page_size,
                                            max_len=max_len)
        prog = self.program
        self.max_slots = int(max_slots)
        self.eos_id = eos_id
        self.max_retries = int(max_retries)
        self.default_max_new = int(default_max_new)
        self.clock = clock
        self.total_pages = int(
            total_pages if total_pages is not None
            else 1 + self.max_slots * prog.pages_per_slot)
        if self.total_pages < 1 + prog.pages_per_slot:
            raise ValueError(
                f"total_pages {self.total_pages} cannot hold even one "
                f"full-length request ({prog.pages_per_slot} pages) plus "
                "the scratch page")
        self.metrics = metrics or DecodeMetrics()
        self.batcher = ContinuousBatcher(
            max_batch=self.max_slots, slo_ms=slo_ms, max_queue=max_queue,
            admission=admission, metrics=self.metrics, clock=clock)
        buckets = sorted(set(int(b) for b in (prompt_buckets
                                              or pow2_buckets(prog.max_len))))
        self.prompt_buckets = [b for b in buckets if 0 < b <= prog.max_len]
        if not self.prompt_buckets:
            raise ValueError("no prompt bucket <= max_len "
                             f"{prog.max_len}: {buckets}")
        self.max_prompt = min(self.prompt_buckets[-1], prog.max_len - 1)

        params = getattr(model, "params", model)
        self._versions: Dict[str, Any] = {tag: params}
        self._serve_tag = tag
        self._slots: List[Optional[_Slot]] = [None] * self.max_slots
        self._page_table = np.zeros(
            (self.max_slots, prog.pages_per_slot), np.int32)
        self._free_pages = deque(range(1, self.total_pages))
        self._cache = None
        self._compiled: Dict[tuple, Any] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._loaded = False
        self._shutdown = False
        self._generation = 0
        self._crash_next = False   # test hook: raise inside the next step
        self._thread: Optional[threading.Thread] = None
        self._supervisor: Optional[threading.Thread] = None
        self._autoscaler = None             # see enable_autoscale()
        self._autoscale_cb = None
        self._autoscale_interval_s = 0.25
        self._last_autoscale_t: Optional[float] = None
        self._shed_seen = 0.0
        self._logical_replicas = 1

    # -- load / warmup -----------------------------------------------------

    def load(self, warm_bundle: Optional[str] = None) -> "DecodeEngine":
        """Allocate the pool and AOT-compile + run every serve-path
        executable: one prefill per prompt bucket, the decode step, the
        two samplers, the pool reset, and the page scrub.  After this,
        ``compile_cache_size()`` must not grow while serving — the
        zero-serve-time-compiles contract.

        ``warm_bundle`` points at a bundle written by
        :meth:`save_warmup_bundle` (serving/warmcache.py): each
        executable deserializes instead of compiling, with per-key
        fallback to compile on any miss.  Bundle hits are still executed
        once below, so the donated pool state flows identically to a
        cold load."""
        import jax

        from ..ops.kv_cache import alloc_cache
        from .warmcache import load_bundle

        prog = self.program
        params = self._versions[self._serve_tag]
        s_n, pps, v_n = self.max_slots, prog.pages_per_slot, prog.vocab_size
        kp, vp = alloc_cache(prog.n_layers, self.total_pages, prog.page_size,
                             prog.n_heads, prog.d_head)
        bundle = load_bundle(warm_bundle) if warm_bundle else {}
        hits = misses = 0

        def _get(key, build):
            nonlocal hits, misses
            exe = bundle.get(key)
            if exe is not None:
                hits += 1
                return exe
            misses += 1
            return build()

        t0 = self.clock()
        with obs_trace.span("serve/warmup", cat="serve", kind="decode",
                            tag=self._serve_tag):
            step_c = _get("step", lambda: jax.jit(
                prog.step, donate_argnums=(1, 2)).lower(
                    params, kp, vp, np.zeros((s_n, pps), np.int32),
                    np.zeros((s_n,), np.int32), np.zeros((s_n,), np.int32),
                    np.zeros((s_n,), bool)).compile())
            kp, vp, lgs = step_c(
                params, kp, vp, np.zeros((s_n, pps), np.int32),
                np.zeros((s_n,), np.int32), np.zeros((s_n,), np.int32),
                np.zeros((s_n,), bool))
            self._compiled[("step",)] = step_c

            lg1 = None
            prefill_jit = jax.jit(prog.prefill, donate_argnums=(1, 2))
            for b in self.prompt_buckets:
                pf = _get(f"prefill:{b}", lambda b=b: prefill_jit.lower(
                    params, kp, vp, np.zeros((pps,), np.int32),
                    np.zeros((b,), np.int32), np.int32(1)).compile())
                kp, vp, lg1 = pf(params, kp, vp, np.zeros((pps,), np.int32),
                                 np.zeros((b,), np.int32), np.int32(1))
                self._compiled[("prefill", b)] = pf

            one, batch = _make_samplers(v_n)
            s1 = _get("sample1", lambda: jax.jit(one).lower(
                lg1, np.float32(0), np.int32(0), np.float32(1), np.uint32(0),
                np.int32(0)).compile())
            tok, _ = s1(lg1, np.float32(0), np.int32(0), np.float32(1),
                        np.uint32(0), np.int32(0))
            np.asarray(tok)
            self._compiled[("sample1",)] = s1
            sb = _get("sample", lambda: jax.jit(batch).lower(
                lgs, np.zeros((s_n,), np.float32), np.zeros((s_n,), np.int32),
                np.ones((s_n,), np.float32), np.zeros((s_n,), np.uint32),
                np.zeros((s_n,), np.int32)).compile())
            toks, _ = sb(lgs, np.zeros((s_n,), np.float32),
                         np.zeros((s_n,), np.int32),
                         np.ones((s_n,), np.float32),
                         np.zeros((s_n,), np.uint32),
                         np.zeros((s_n,), np.int32))
            np.asarray(toks)
            self._compiled[("sample",)] = sb

            def _reset(k, v):
                import jax.numpy as jnp
                return jnp.zeros_like(k), jnp.zeros_like(v)

            def _scrub(k, v, ids):
                # zero the given pages (padded with repeats — idempotent)
                return k.at[:, ids].set(0.0), v.at[:, ids].set(0.0)

            reset_c = _get("reset", lambda: jax.jit(
                _reset, donate_argnums=(0, 1)).lower(kp, vp).compile())
            kp, vp = reset_c(kp, vp)
            self._compiled[("reset",)] = reset_c
            scrub_c = _get("scrub", lambda: jax.jit(
                _scrub, donate_argnums=(0, 1)).lower(
                    kp, vp, np.zeros((pps,), np.int32)).compile())
            kp, vp = scrub_c(kp, vp, np.zeros((pps,), np.int32))
            self._compiled[("scrub",)] = scrub_c
        self.metrics.inc("bundle_hits", hits)
        self.metrics.inc("bundle_misses", misses)
        self.metrics.inc("warmup_seconds_total", self.clock() - t0)

        self._cache = (kp, vp)
        self._loaded = True
        self._start_loop()
        self._supervisor = threading.Thread(
            target=self._supervise, name="decode-supervisor", daemon=True)
        self._supervisor.start()
        return self

    def save_warmup_bundle(self, path: str) -> str:
        """Export every serve-path executable as a warmup bundle
        (serving/warmcache.py) so a fresh process — a scaled-up decode
        host, a respawn — deserializes in milliseconds via
        ``load(warm_bundle=path)`` instead of paying the XLA compiles."""
        from .warmcache import save_bundle
        if not self._loaded:
            raise RuntimeError("load() the engine before bundling")
        entries = {":".join(str(p) for p in key): exe
                   for key, exe in self._compiled.items()}
        return save_bundle(path, self._serve_tag, entries)

    def compile_cache_size(self) -> int:
        """Executables backing the serve path.  Must not grow after
        ``load()`` while serving — watched by ``continuous_batching_ab``."""
        return len(self._compiled)

    @property
    def current_tag(self) -> str:
        with self._lock:
            return self._serve_tag

    # -- request path ------------------------------------------------------

    def generate_async(self, prompt_ids, *, max_new_tokens: Optional[int] = None,
                       temperature: float = 0.0, top_k: int = 0,
                       top_p: float = 1.0, seed: int = 0,
                       slo_ms: Optional[float] = None,
                       deadline: Optional[float] = None,
                       echo_logits: bool = False) -> Future:
        """Enqueue one generation; the Future resolves to a
        ``GenerationResult`` (or a typed serving error).  Joins the
        running decode batch at the next step boundary."""
        if not self._loaded:
            raise RuntimeError("DecodeEngine.load() must run before generate")
        prog = self.program
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.shape[0] < 1 or prompt.shape[0] > self.max_prompt:
            raise ValueError(
                f"prompt length {prompt.shape[0]} outside [1, "
                f"{self.max_prompt}] (largest warmed bucket, < max_len "
                f"{prog.max_len})")
        if prompt.min() < 0 or prompt.max() >= prog.vocab_size:
            raise ValueError(f"prompt ids outside [0, {prog.vocab_size})")
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.default_max_new)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        max_new = min(max_new, prog.max_len - int(prompt.shape[0]))
        if temperature < 0:
            raise ValueError("temperature must be >= 0 (0 = greedy)")
        if not (0 <= top_k <= prog.vocab_size):
            raise ValueError(f"top_k outside [0, {prog.vocab_size}]")
        if not (0 < top_p <= 1.0):
            raise ValueError("top_p must be in (0, 1]")
        spec = _GenSpec(prompt=prompt, max_new=max_new,
                        temperature=float(temperature), top_k=int(top_k),
                        top_p=float(top_p), seed=int(seed),
                        echo_logits=bool(echo_logits))
        return self.batcher.submit_request(spec, slo_ms=slo_ms,
                                           deadline=deadline)

    def generate(self, prompt_ids, **kw) -> GenerationResult:
        """Blocking ``generate_async``."""
        return self.generate_async(prompt_ids, **kw).result()

    # -- hot-swap ----------------------------------------------------------

    def swap_model(self, model, tag: str) -> None:
        """Flip the version NEW admissions decode under; in-flight slots
        finish under the version that prefilled them (the step runs per
        distinct active tag), so no request mixes versions and nothing
        drains.  The incoming params must match the loaded shapes/dtypes
        — the AOT executables are shared across versions."""
        import jax

        params = getattr(model, "params", model)
        ref = self._versions[self._serve_tag]
        try:
            mismatch = jax.tree_util.tree_map(
                lambda a, b: (np.shape(a) != np.shape(b)
                              or np.asarray(a).dtype != np.asarray(b).dtype),
                ref, params)
        except ValueError as e:
            raise ValueError(f"incoming model {tag!r} has a different "
                             f"parameter tree: {e}") from e
        if any(jax.tree_util.tree_leaves(mismatch)):
            raise ValueError(
                f"incoming model {tag!r} has mismatched parameter "
                "shapes/dtypes — decode versions must share the compiled "
                "executables")
        with self._lock:
            self._versions[tag] = params
            self._serve_tag = tag
        self.metrics.inc("swaps")
        obs_trace.instant("serve/swap", cat="serve", incoming=tag,
                          kind="decode")

    def attach_registry(self, registry, name: str,
                        alias: str = "prod") -> "DecodeEngine":
        """Serve (name, alias) from a ModelRegistry and follow every
        ``set_alias`` move with a no-drain ``swap_model``."""
        version, model = registry.resolve(name, alias)
        self.swap_model(model, f"{name}:v{version}")
        registry.subscribe(
            name, alias,
            lambda ver, mod: self.swap_model(mod, f"{name}:v{ver}"))
        return self

    # -- decode loop -------------------------------------------------------

    def _start_loop(self) -> None:
        with self._lock:
            self._generation += 1
            gen = self._generation
            self._thread = threading.Thread(
                target=self._loop, args=(gen,),
                name=f"decode-loop-{gen}", daemon=True)
            self._thread.start()

    def enable_autoscale(self, on_scale, autoscaler=None, *,
                         min_replicas: int = 1, max_replicas: int = 4,
                         interval_s: float = 0.25,
                         **knobs) -> "DecodeEngine":
        """Arm the load controller over the decode queue.  Unlike the
        predict engine, decode slot capacity is COMPILE-SHAPE-FIXED
        (the step executable is compiled for ``max_slots``), so the
        actuator is a callback, not an in-process replica birth: the
        fleet tier owns physical decode scaling (a new `serve` host
        warming from this engine's warmup bundle — docs/SERVING.md
        "Cold start & autoscaling").  ``on_scale(delta, replicas)`` is
        called with +1/-1 and the new logical replica count; spans and
        scale counters are emitted here either way."""
        from .autoscale import ReplicaAutoscaler
        if autoscaler is None:
            autoscaler = ReplicaAutoscaler(
                min_replicas=int(min_replicas),
                max_replicas=int(max_replicas),
                clock=self.clock, **knobs)
        self._autoscale_interval_s = float(interval_s)
        self._shed_seen = self.metrics.counter_value("shed")
        self._autoscale_cb = on_scale
        self._autoscaler = autoscaler
        return self

    def _autoscale_tick(self) -> None:
        a = self._autoscaler
        if a is None or not self._loaded or self._shutdown:
            return
        now = self.clock()
        if (self._last_autoscale_t is not None
                and now - self._last_autoscale_t < self._autoscale_interval_s):
            return
        self._last_autoscale_t = now
        shed = self.metrics.counter_value("shed")
        shed_delta = shed - self._shed_seen
        self._shed_seen = shed
        with self._lock:
            active = sum(1 for s in self._slots if s is not None)
        decision = a.observe(self.batcher.qsize(), active,
                             self._logical_replicas,
                             shed_delta=int(shed_delta))
        if decision == 0:
            return
        self._logical_replicas += decision
        if decision > 0:
            with obs_trace.span("serve/scale_up", cat="serve",
                                kind="decode",
                                replicas=self._logical_replicas):
                self._autoscale_cb(1, self._logical_replicas)
            self.metrics.inc("scale_ups")
        else:
            with obs_trace.span("serve/scale_down", cat="serve",
                                kind="decode",
                                replicas=self._logical_replicas):
                self._autoscale_cb(-1, self._logical_replicas)
            self.metrics.inc("scale_downs")

    def _supervise(self) -> None:
        """Respawn the decode loop if it dies outright (a crash its own
        handler could not absorb) — in-flight requests are retried or
        failed, never stranded."""
        while not self._stop.wait(0.05):
            with self._lock:
                if self._shutdown:
                    return
                t = self._thread
            self._autoscale_tick()
            if t is not None and not t.is_alive():
                obs_trace.instant("serve/replica_crash", cat="serve",
                                  kind="decode_loop_dead")
                self.metrics.inc("replica_crashes")
                self._drain_crashed(ReplicaCrashError(
                    "decode loop thread died"))
                with self._lock:
                    if self._shutdown:
                        return
                self.metrics.inc("replica_respawns")
                self._start_loop()

    def _loop(self, gen: int) -> None:
        while True:
            with self._lock:
                if self._shutdown or gen != self._generation:
                    return
            try:
                worked = self._admit_some()
                worked = self._step_once() or worked
            except Exception as e:
                obs_trace.instant("serve/replica_crash", cat="serve",
                                  kind="decode_step",
                                  error=type(e).__name__)
                self.metrics.inc("replica_crashes")
                self._drain_crashed(e)
                continue
            if not worked:
                self.batcher.wait_for_work(0.05)

    def _admit_some(self) -> bool:
        """Join queued requests to the running batch: allocate pages +
        a slot, prefill, sample the first token (TTFT).  Stops at the
        first request the pool cannot hold yet (FIFO order preserved)."""
        from ..ops.kv_cache import pages_for

        with self._lock:
            free = [i for i, s in enumerate(self._slots) if s is None]
        if not free:
            return False
        reqs = self.batcher.admit(len(free))
        if not reqs:
            return False
        prog = self.program
        leftovers: List[Any] = []
        worked = False
        for r in reqs:
            if leftovers:           # keep FIFO once one request stalls
                leftovers.append(r)
                continue
            spec = r.payload
            max_total = min(int(spec.prompt.shape[0]) + spec.max_new,
                            prog.max_len)
            need = pages_for(max_total, prog.page_size)
            with self._lock:
                if not free or len(self._free_pages) < need:
                    leftovers.append(r)
                    continue
                i = free.pop(0)
                ids = [self._free_pages.popleft() for _ in range(need)]
                self._page_table[i] = 0
                self._page_table[i, :need] = ids
                slot = _Slot(r, self._serve_tag, ids, spec.max_new)
                self._slots[i] = slot
                self.metrics.active_slots.set(
                    sum(1 for s in self._slots if s is not None))
                self.metrics.pages_in_use.set(
                    self.total_pages - 1 - len(self._free_pages))
            self.metrics.inc("requests")
            self._prefill_slot(i)
            worked = True
        for r in reversed(leftovers):
            self.batcher.requeue_front(r)
        return worked

    def _bucket_for(self, n: int) -> int:
        for b in self.prompt_buckets:
            if n <= b:
                return b
        return self.prompt_buckets[-1]

    def _prefill_slot(self, i: int) -> None:
        s = self._slots[i]
        spec = s.spec
        n = s.n_prompt
        bucket = self._bucket_for(n)
        padded = np.zeros((bucket,), np.int32)
        padded[:n] = spec.prompt
        t0 = self.clock()
        kp, vp = self._cache
        kp, vp, lg = self._compiled[("prefill", bucket)](
            self._versions[s.tag], kp, vp, self._page_table[i], padded,
            np.int32(n))
        tok, fin = self._compiled[("sample1",)](
            lg, np.float32(spec.temperature), np.int32(spec.top_k),
            np.float32(spec.top_p), np.uint32(spec.seed), np.int32(0))
        self._cache = (kp, vp)
        tok_h = int(np.asarray(tok))
        fin_h = bool(np.asarray(fin))
        lg_h = np.asarray(lg) if spec.echo_logits else None
        t1 = self.clock()
        obs_trace.complete_at("serve/prefill", t0, t1, cat="serve", slot=i,
                              bucket=bucket, prompt_tokens=n, model=s.tag)
        self.metrics.inc("prefills")
        self.metrics.ttft.record((t1 - s.req.t_submit) * 1e3)
        s.t_first = t1
        self._record_token(i, tok_h, fin_h, lg_h, t1)

    def _step_once(self) -> bool:
        """One decode step per distinct active version tag (same
        executable, that tag's params, that tag's slots active) — the
        no-version-mixing hot-swap invariant lives here."""
        s_n = self.max_slots
        with self._lock:
            tags: List[str] = []
            for s in self._slots:
                if s is not None and s.tag not in tags:
                    tags.append(s.tag)
            crash = self._crash_next
            self._crash_next = False
        if crash:
            raise ReplicaCrashError("injected decode-batch crash (test hook)")
        if not tags:
            return False
        for tag in tags:
            toks_in = np.zeros((s_n,), np.int32)
            pos = np.zeros((s_n,), np.int32)
            act = np.zeros((s_n,), bool)
            temps = np.zeros((s_n,), np.float32)
            tks = np.zeros((s_n,), np.int32)
            tps = np.ones((s_n,), np.float32)
            seeds = np.zeros((s_n,), np.uint32)
            steps = np.zeros((s_n,), np.int32)
            group: List[int] = []
            echo = False
            with self._lock:
                params = self._versions.get(tag)
                if params is None:
                    continue
                for i, s in enumerate(self._slots):
                    if s is None or s.tag != tag:
                        continue
                    group.append(i)
                    toks_in[i] = s.last_token
                    pos[i] = s.pos
                    act[i] = True
                    temps[i] = s.spec.temperature
                    tks[i] = s.spec.top_k
                    tps[i] = s.spec.top_p
                    seeds[i] = s.spec.seed
                    steps[i] = s.n_out
                    echo = echo or s.logits is not None
            if not group:
                continue
            t0 = self.clock()
            kp, vp = self._cache
            kp, vp, lgs = self._compiled[("step",)](
                params, kp, vp, self._page_table, toks_in, pos, act)
            toks, fin = self._compiled[("sample",)](
                lgs, temps, tks, tps, seeds, steps)
            self._cache = (kp, vp)
            toks_h = np.asarray(toks)
            fin_h = np.asarray(fin)
            lgs_h = np.asarray(lgs) if echo else None
            t1 = self.clock()
            obs_trace.complete_at("serve/decode_step", t0, t1, cat="serve",
                                  n_active=len(group), model=tag)
            self.metrics.inc("decode_steps")
            self.metrics.step_time.record((t1 - t0) * 1e3)
            for i in group:
                with self._lock:
                    s = self._slots[i]
                if s is not None:
                    s.pos += 1
                    self._record_token(
                        i, int(toks_h[i]), bool(fin_h[i]),
                        lgs_h[i].copy() if (lgs_h is not None
                                            and s.logits is not None)
                        else None, t1)
        return True

    # -- per-token bookkeeping + stop conditions ---------------------------

    def _record_token(self, i: int, token: int, finite: bool,
                      logits_row: Optional[np.ndarray], now: float) -> None:
        s = self._slots[i]
        if s is None:
            return
        if not finite:
            self.metrics.inc("poison_isolated")
            self._scrub_pages(s.page_ids)
            self._finish(i, now, error=PoisonInputError(
                f"decode produced non-finite logits at token {s.n_out} "
                f"(slot {i}) — request isolated, co-batched slots "
                "unaffected"))
            return
        s.tokens.append(token)
        s.n_out += 1
        s.last_token = token
        s.t_last = now
        if s.logits is not None and logits_row is not None:
            s.logits.append(logits_row)
        self.metrics.inc("tokens_out")
        if self.eos_id is not None and token == self.eos_id:
            self._finish(i, now, reason="eos")
        elif s.n_out >= s.max_new:
            self._finish(i, now, reason="max_tokens")
        elif now > s.deadline:
            # mid-decode deadline is a STOP condition, not an error: the
            # caller gets the tokens produced inside the budget
            self._finish(i, now, reason="deadline")

    def _scrub_pages(self, page_ids: List[int]) -> None:
        """Zero freed pages that may hold non-finite rows — a NaN left
        behind would poison the page's next tenant (0 * NaN = NaN)."""
        pps = self.program.pages_per_slot
        ids = np.full((pps,), page_ids[0], np.int32)
        ids[:len(page_ids)] = page_ids
        kp, vp = self._cache
        self._cache = self._compiled[("scrub",)](kp, vp, ids)

    def _finish(self, i: int, now: float, reason: Optional[str] = None,
                error: Optional[BaseException] = None) -> None:
        with self._lock:
            s = self._slots[i]
            if s is None:
                return
            self._slots[i] = None
            self._free_pages.extend(s.page_ids)
            self._page_table[i] = 0
            live_tags = {sl.tag for sl in self._slots if sl is not None}
            live_tags.add(self._serve_tag)
            for t in [t for t in self._versions if t not in live_tags]:
                del self._versions[t]
            self.metrics.active_slots.set(
                sum(1 for sl in self._slots if sl is not None))
            self.metrics.pages_in_use.set(
                self.total_pages - 1 - len(self._free_pages))
        if error is not None:
            self.metrics.inc("errors")
            _fail_safe(s.req.future, error)
        else:
            self.metrics.inc({"eos": "eos_stops",
                              "max_tokens": "max_token_stops",
                              "deadline": "deadline_stops"}[reason])
            tpot = ((s.t_last - s.t_first) * 1e3 / (s.n_out - 1)
                    if s.n_out > 1 else None)
            if tpot is not None:
                self.metrics.tpot.record(tpot)
            _set_safe(s.req.future, GenerationResult(
                tokens=list(s.tokens), n_prompt=s.n_prompt,
                finish_reason=reason, model_tag=s.tag,
                ttft_ms=round((s.t_first - s.req.t_submit) * 1e3, 3),
                tpot_ms=round(tpot, 3) if tpot is not None else None,
                logits=np.stack(s.logits) if s.logits else None))
        obs_trace.complete_at("serve/request", s.req.t_submit, now,
                              cat="serve", kind="generate", tokens=s.n_out,
                              finish=reason or "error")

    # -- crash recovery ----------------------------------------------------

    def _drain_crashed(self, exc: BaseException) -> None:
        """Fail or retry every in-flight request after a decode-batch
        crash, reset the pool, keep serving.  Retries regenerate the
        identical sequence (seeded counter-based sampling), so a retry
        is indistinguishable from a slow first attempt."""
        with self._lock:
            in_flight = [s for s in self._slots if s is not None]
            self._slots = [None] * self.max_slots
            self._free_pages = deque(range(1, self.total_pages))
            self._page_table[:] = 0
            self.metrics.active_slots.set(0)
            self.metrics.pages_in_use.set(0)
        # the crash may have left non-finite rows anywhere — zero the pool
        kp, vp = self._cache
        self._cache = self._compiled[("reset",)](kp, vp)
        now = self.clock()
        for s in in_flight:
            r = s.req
            r.retries += 1
            if r.retries <= self.max_retries and r.deadline > now \
                    and not r.future.done():
                self.metrics.inc("retries")
                obs_trace.instant("serve/retry", cat="serve", kind="decode",
                                  retries=r.retries)
                self.batcher.requeue_front(r)
            else:
                self.metrics.inc("errors")
                _fail_safe(r.future, ReplicaCrashError(
                    f"decode batch crashed ({type(exc).__name__}: {exc}) "
                    f"after {s.n_out} tokens; retry budget exhausted"))

    # -- observability / shutdown ------------------------------------------

    def metrics_snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        with self._lock:
            snap["model"] = self._serve_tag
            snap["versions"] = sorted(self._versions)
            snap["queue_depth"] = self.batcher.qsize()
        snap["compile_cache_size"] = self.compile_cache_size()
        snap["prompt_buckets"] = list(self.prompt_buckets)
        snap["max_slots"] = self.max_slots
        snap["total_pages"] = self.total_pages
        return snap

    def health_snapshot(self) -> dict:
        with self._lock:
            t = self._thread
            ready = (self._loaded and not self._shutdown
                     and t is not None and t.is_alive())
        return {"status": "ready" if ready else "unready", "ready": ready,
                "kind": "decode", "model": self.current_tag}

    def begin_drain(self) -> None:
        """Stop admission (new submissions shed → 429) while queued and
        in-flight generations complete — the decode half of the
        graceful SIGTERM drain (docs/SERVING.md)."""
        self.batcher.begin_drain()
        self.metrics.inc("drains")
        obs_trace.instant("serve/drain", cat="serve")

    def shutdown(self) -> None:
        """Idempotent; every queued AND in-flight future resolves."""
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            self._generation += 1
            in_flight = [s for s in self._slots if s is not None]
            self._slots = [None] * self.max_slots
        self._stop.set()
        self.batcher.close(fail_pending=True)
        for s in in_flight:
            _fail_safe(s.req.future,
                       RuntimeError("serving engine is shut down"))
        for t in (self._thread, self._supervisor):
            if t is not None and t is not threading.current_thread():
                t.join(timeout=5)
