"""Zero-cold-start plumbing: persistent compile cache + warmup bundles.

Two independent mechanisms, both optional and both silent-on-miss:

1. **Persistent compilation cache** — `enable_compile_cache()` points the
   process-wide JAX compilation cache at a directory (explicit argument
   wins, else the ``DL4J_TPU_COMPILE_CACHE`` env var).  Every
   ``jax.jit`` compile in the process — train, serve, launch workers,
   bench — then reads/writes XLA executables on disk, so a respawned
   process recompiles nothing it has compiled before.

2. **Warmup bundles** — explicit AOT executables serialized with
   ``jax.experimental.serialize_executable`` into a zip written next to
   the checkpoint (``model.zip`` → ``model.zip.warm``), keyed by
   (version tag, executable key, device fingerprint, jax version) with
   sha256 integrity digests per entry (same idiom as the checkpoint
   serializer).  A fresh ``Engine.load()`` / ``DecodeEngine.load()``
   deserializes instead of compiling; ANY miss — absent file, corrupt
   entry, truncated zip, wrong tag, wrong device fingerprint, wrong jax
   version — falls back to compiling, never raises.  A missing bundle
   is silent (the normal first-run case); an unusable one logs exactly
   one warning.

The executables inside a bundle are device-committed: they only run on
the device set they were compiled for.  Callers route accordingly (see
``Engine._run_forward``).

Decode-engine bundle keys: the base set is ``prefill:<bucket>`` per
prompt bucket plus ``step`` / ``sample1`` / ``sample`` / ``reset`` /
``scrub``; the decode-side
optimizations add ``prefill_at:<bucket>`` (prefix cache AND chunked
prefill: prefill resuming at an offset), ``step_multi:<H>`` (fused
multi-step decode at horizon H — one entry per configured horizon),
and — when a draft model is configured — ``draft_prefill:<bucket>`` /
``draft_prefill_at:<bucket>`` / ``draft_step`` / ``draft_reset`` /
``draft_scrub`` plus the verification trio ``spec_step`` /
``propose`` / ``spec_accept``.  All of them ride the same
serialize/deserialize path, so speculative, prefix-cached and fused
engines warm-load compile-free too.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import warnings
import zipfile
from typing import Any, Dict, Optional

import jax

ENV_VAR = "DL4J_TPU_COMPILE_CACHE"
BUNDLE_FORMAT_VERSION = 1
BUNDLE_SUFFIX = ".warm"

_enabled_dir: Optional[str] = None


def _harden_cache_writes() -> None:
    """Make jax's file-system compile-cache writes atomic.

    jax's LRU file cache ``put`` is a bare ``write_bytes`` (its
    filelock only engages when eviction is on), so a process killed
    mid-write — exactly what a preempted or chaos-killed fleet worker
    is — strands a HALF-WRITTEN executable that a later process
    deserializes as garbage and crashes on.  Route every put through
    write-to-temp + ``os.replace`` in the same directory: an entry is
    either absent or complete, never partial.  Identical concurrent
    writers are benign (same HLO key ⇒ same bytes; last rename wins).
    """
    try:
        from jax._src import lru_cache as _lru
    except Exception:
        # best-effort: a jax without this private module keeps stock
        # writes — the cache still works, just unhardened
        return
    if getattr(_lru.LRUCache.put, "_dl4j_atomic", False):
        return

    def _atomic_put(self, key, val):
        if not key:
            raise ValueError("key cannot be empty")
        cache_path = self.path / f"{key}{_lru._CACHE_SUFFIX}"
        if cache_path.exists():
            return
        tmp = self.path / f"{key}.tmp.{os.getpid()}"
        tmp.write_bytes(val)
        os.replace(tmp, cache_path)

    _atomic_put._dl4j_atomic = True
    _lru.LRUCache.put = _atomic_put


def enable_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Enable the JAX persistent compilation cache process-wide.

    Explicit ``cache_dir`` wins; otherwise the ``DL4J_TPU_COMPILE_CACHE``
    env var; if neither is set this is a no-op returning None.  The
    min-compile-time threshold is dropped to 0 so even the small CPU
    test executables persist.  The env var is (re)exported so forked
    workers (``launch``) inherit the setting.  Idempotent.
    """
    global _enabled_dir
    d = cache_dir or os.environ.get(ENV_VAR)
    if not d:
        return None
    d = os.path.abspath(d)
    if _enabled_dir == d:
        return d
    _harden_cache_writes()
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    # jax latches the cache state at the process's FIRST compile: if one
    # already happened (e.g. the cache is enabled mid-run), the new dir
    # is ignored until the cache re-initializes — force that here
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc,
        )
        _cc.reset_cache()
    # graftcheck: disable=GC404 (best-effort: a jax build without reset_cache keeps the first-compile latch; the dir is still set for up-front enables)
    except Exception:
        pass
    os.environ[ENV_VAR] = d
    _enabled_dir = d
    return d


def device_fingerprint(mesh: Optional[Any] = None) -> str:
    """Identity of the device set an AOT executable is valid for.

    Serialized executables are XLA programs compiled for specific
    hardware; loading one on a different backend/topology is undefined.
    The fingerprint pins backend platform, device kind, device count,
    and the jax version that produced the serialization format.

    ``mesh`` (optional) appends a ``mesh(axis=size,...)`` component for
    executables compiled against a named mesh — sharded-decode programs
    are partitioned per mesh topology, so a bundle built on
    ``data=2`` must never load into a ``data=4`` (or unmeshed) process.
    Omitting it keeps the historical 4-field format, so single-device
    bundles stay loadable across this change.
    """
    devs = jax.devices()
    kind = devs[0].device_kind if devs else "none"
    parts = [jax.default_backend(), str(kind), str(len(devs)),
             jax.__version__]
    if mesh is not None:
        axes = ",".join(f"{n}={int(s)}"
                        for n, s in dict(mesh.shape).items())
        parts.append(f"mesh({axes})")
    return "|".join(parts)


def bundle_path_for(checkpoint_path: str) -> str:
    """Warmup-bundle path convention: next to the checkpoint zip."""
    return str(checkpoint_path) + BUNDLE_SUFFIX


def save_bundle(path: str, tag: str, entries: Dict[str, Any],
                mesh: Optional[Any] = None) -> str:
    """Serialize AOT ``entries`` ({key: compiled executable}) to ``path``.

    Zip layout mirrors the checkpoint serializer: a ``meta.json``
    carrying tag / device fingerprint / jax version / key mapping /
    per-entry sha256 integrity digests, plus one pickled
    ``(payload, in_tree, out_tree)`` blob per executable.  Written
    atomically (tmp + rename) so a crash mid-save never leaves a
    half-bundle where a valid one was.  ``mesh``: pass the named mesh
    the executables were partitioned over (sharded decode) so the
    fingerprint pins its topology; None for single-device programs.
    """
    from jax.experimental import serialize_executable as _se

    names: Dict[str, str] = {}
    blobs: Dict[str, bytes] = {}
    for i, key in enumerate(sorted(entries)):
        payload, in_tree, out_tree = _se.serialize(entries[key])
        ename = f"exec_{i}.bin"
        names[ename] = key
        blobs[ename] = pickle.dumps((payload, in_tree, out_tree))
    meta = {
        "format_version": BUNDLE_FORMAT_VERSION,
        "tag": tag,
        "fingerprint": device_fingerprint(mesh),
        "jax_version": jax.__version__,
        "entries": names,
        "integrity": {e: hashlib.sha256(b).hexdigest() for e, b in blobs.items()},
    }
    tmp = str(path) + ".tmp"
    with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("meta.json", json.dumps(meta, indent=2))
        for ename, blob in blobs.items():
            z.writestr(ename, blob)
    os.replace(tmp, path)
    return str(path)


class _BundleMiss(Exception):
    """Internal: a specific reason the bundle can't be used."""


def load_bundle(path: Optional[str], tag: Optional[str] = None,
                mesh: Optional[Any] = None) -> Dict[str, Any]:
    """Load a warmup bundle; return {} on ANY miss, never raise.

    An absent file is the normal cold-start case and stays silent.  An
    existing-but-unusable bundle (truncated/corrupt zip, integrity or
    fingerprint or tag or jax-version mismatch, undeserializable entry)
    emits exactly one ``RuntimeWarning`` naming the reason, then returns
    {} so the caller compiles as if no bundle existed.  ``mesh`` must
    match what the bundle was saved with (the fingerprint carries the
    mesh topology component) — a differently-meshed bundle falls back
    to compile under the same one-warning contract.
    """
    if not path or not os.path.exists(path):
        return {}
    from jax.experimental import serialize_executable as _se

    try:
        with zipfile.ZipFile(path, "r") as z:
            meta = json.loads(z.read("meta.json"))
            if meta.get("format_version") != BUNDLE_FORMAT_VERSION:
                raise _BundleMiss(
                    f"format_version {meta.get('format_version')!r}"
                )
            if tag is not None and meta.get("tag") != tag:
                raise _BundleMiss(f"tag {meta.get('tag')!r} != wanted {tag!r}")
            if meta.get("jax_version") != jax.__version__:
                raise _BundleMiss(
                    f"jax {meta.get('jax_version')!r} != {jax.__version__!r}"
                )
            fp = device_fingerprint(mesh)
            if meta.get("fingerprint") != fp:
                raise _BundleMiss(
                    f"device fingerprint {meta.get('fingerprint')!r} != {fp!r}"
                )
            integrity = meta.get("integrity", {})
            out: Dict[str, Any] = {}
            for ename, key in meta.get("entries", {}).items():
                blob = z.read(ename)
                if integrity.get(ename) != hashlib.sha256(blob).hexdigest():
                    raise _BundleMiss(f"integrity mismatch on {ename}")
                payload, in_tree, out_tree = pickle.loads(blob)
                out[key] = _se.deserialize_and_load(payload, in_tree, out_tree)
            return out
    except Exception as exc:  # noqa: BLE001 — fallback-to-compile contract:
        # any unusable bundle must degrade to a cold compile, not an error.
        warnings.warn(
            f"warmup bundle {path!r} unusable ({exc!r}); falling back to "
            "compile",
            RuntimeWarning,
            stacklevel=2,
        )
        return {}
