"""The production flywheel: continuous train→eval→canary→fleet-promote.

Every prior layer proved train and serve in isolation; this controller
runs the full lifecycle end-to-end and *repeatedly* (docs/LIFECYCLE.md):

    TRAIN ──▶ EVAL ──▶ REGISTER ──▶ CANARY ──▶ ROLL ──▶ PROMOTED
                │                      │          │
                └──────────────────────┴──────────┴────▶ ROLLED_BACK

* **TRAIN** — launch/resume a training run.  ``train_fn(generation)``
  returns an :class:`~..parallel.elastic.ElasticTrainer` (its
  ``run_id`` / ``final_checkpoint_path`` seam stamps lineage without
  filename parsing), or a ``{"model": ..., "run_id": ...,
  "checkpoint_path": ...}`` dict, or a bare model.
* **EVAL** — an explicit-threshold gate (:class:`EvalGate`) over the
  ``earlystopping`` score calculators; a non-finite score fails the
  gate by construction (the InvalidScore guard for NaN-params runs).
* **REGISTER** — the version lands in the :class:`ModelRegistry` with
  a **lineage** provenance record (run id, data-slice fingerprint,
  parent version, eval score, weights sha); eval *failures* are also
  registered — flagged ``eval_passed=False`` — as an audit trail, and
  ``ModelRegistry.rollback_target`` skips them.  The warm bundle is
  built here, at save time (PR 15 seam), so every later swap
  deserializes instead of compiling.
* **CANARY** — ``set_alias(..., canary=frac, raise_on_reject=True)``:
  subscribed engines judge the candidate on mirrored live traffic; a
  rejection surfaces as a typed :class:`CanaryRejectedError`.
* **ROLL** — ``FleetRouter.rolling_swap(warm_bundle=)`` rolls the
  version host-by-host under live traffic; a mid-roll host death
  aborts the generation (the fleet machinery already rolled the
  survivors back).
* **ROLLED_BACK** — any failure re-aliases to the registry's
  *lineage-selected* rollback target (the last eval-passing ancestor,
  not version−1) and re-rolls the fleet if it is not already serving
  that version.

Every stage runs with bounded retries and a per-stage wall-clock
deadline, journaling progress to an append-only JSON-lines
:class:`PipelineJournal` — a crash of the controller *itself* resumes
mid-flywheel from the journal (same discipline as ElasticTrainer's
checkpoint-resume).  ``pipeline/*`` spans and a ``lifecycle`` metrics
collector make the flywheel observable; ``scripts/train_promote_soak.py``
(bench config ``train_promote_loop``) proves it under chaos.
"""

from __future__ import annotations

import hashlib
import json
import logging
import math
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..obs import trace as obs_trace
from ..obs.metrics import get_registry
from .registry import CanaryRejectedError, ModelRegistry

logger = logging.getLogger("deeplearning4j_tpu")

# -- the typed state machine -------------------------------------------------

TRAIN = "TRAIN"
EVAL = "EVAL"
REGISTER = "REGISTER"
CANARY = "CANARY"
ROLL = "ROLL"
PROMOTED = "PROMOTED"
ROLLED_BACK = "ROLLED_BACK"

#: stage execution order; terminals are PROMOTED / ROLLED_BACK
STAGE_ORDER = (TRAIN, EVAL, REGISTER, CANARY, ROLL)
TERMINAL_STATES = (PROMOTED, ROLLED_BACK)


class PipelineStageError(RuntimeError):
    """A pipeline stage failed for good (retries exhausted, deadline
    blown, or a roll that reported failure) — the generation rolls
    back."""

    def __init__(self, stage: str, generation: int, reason: str):
        super().__init__(f"generation {generation} {stage}: {reason}")
        self.stage = stage
        self.generation = generation
        self.reason = reason


class StageDeadlineError(PipelineStageError):
    """A stage exceeded its wall-clock deadline budget."""


# -- provenance helpers ------------------------------------------------------

def weights_sha(model) -> str:
    """Content hash ("git of weights") of a model's parameters:
    sha256 over the tree structure plus every leaf's dtype/shape/bytes
    in tree order.  Two versions with identical weights hash identically
    regardless of which checkpoint file they came from."""
    import jax

    h = hashlib.sha256()
    h.update(str(jax.tree_util.tree_structure(model.params)).encode())
    for leaf in jax.tree_util.tree_leaves(model.params):
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def data_fingerprint(*slices) -> str:
    """Fingerprint of the data slice a run trained/evaluated on: sha256
    over each slice's arrays (ndarrays directly; DataSet-likes via
    their features/labels).  Stamped into lineage so "which data
    produced this version" is answerable from the registry."""
    h = hashlib.sha256()

    def eat(a) -> None:
        if a is None:
            return
        arr = np.asarray(a)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())

    for s in slices:
        if hasattr(s, "features"):
            eat(s.features)
            eat(getattr(s, "labels", None))
        else:
            eat(s)
    return h.hexdigest()[:16]


# -- the eval gate -----------------------------------------------------------

class EvalGate:
    """Explicit-threshold eval gate over an ``earlystopping`` score
    calculator (``DataSetLossCalculator``, ``AccuracyScoreCalculator``,
    or anything with ``calculate_score(model) -> float``).

    ``minimize`` inherits the calculator's ``minimize_score`` direction
    when omitted.  A non-finite score always fails — the
    InvalidScoreIterationTerminationCondition semantics applied at the
    gate, which is what catches a NaN-params run before it ever
    reaches a canary."""

    def __init__(self, calculator, threshold: float,
                 minimize: Optional[bool] = None):
        self.calculator = calculator
        self.threshold = float(threshold)
        self.minimize = (bool(getattr(calculator, "minimize_score", True))
                         if minimize is None else bool(minimize))

    def check(self, model) -> dict:
        """→ ``{"score", "passed", "reason"}`` (reason None on pass)."""
        score = float(self.calculator.calculate_score(model))
        if not math.isfinite(score):
            return {"score": score, "passed": False,
                    "reason": f"non-finite eval score {score!r} "
                              "(invalid-score guard)"}
        passed = (score <= self.threshold if self.minimize
                  else score >= self.threshold)
        reason = None if passed else (
            f"eval score {score:.6g} "
            f"{'above' if self.minimize else 'below'} "
            f"threshold {self.threshold:.6g}")
        return {"score": score, "passed": bool(passed), "reason": reason}


# -- the persistent journal --------------------------------------------------

class PipelineJournal:
    """Append-only JSON-lines journal of pipeline progress.

    Each ``append`` writes one fsynced line, so a controller crash
    leaves at worst a torn FINAL line; ``replay`` drops it (with a
    warning) and returns every intact record — the resume contract
    mirrors CheckpointManager's atomic-write discipline, scaled down
    to one line per state transition."""

    def __init__(self, path: str):
        self.path = str(path)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)

    def append(self, record: dict) -> None:
        line = json.dumps(record, sort_keys=True)
        with open(self.path, "a") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())

    def replay(self) -> List[dict]:
        if not os.path.exists(self.path):
            return []
        out: List[dict] = []
        with open(self.path) as f:
            lines = f.read().splitlines()
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                # a torn line can only be the last (appends are fsynced
                # in order); anything else is corruption worth hearing
                # about — either way the intact prefix is the truth
                logger.warning("journal %s: dropping unparsable line %d "
                               "(%r)", self.path, i + 1, line[:80])
        return out


def _normalize_train_result(res: Any, generation: int) -> dict:
    """Accept the three train_fn return shapes (ElasticTrainer / dict /
    bare model) and normalize to one record."""
    if isinstance(res, dict):
        return {"model": res["model"],
                "run_id": res.get("run_id") or f"gen{generation}",
                "checkpoint_path": res.get("checkpoint_path"),
                "ckpt_manager": res.get("checkpoint_manager")}
    if hasattr(res, "run_id") and hasattr(res, "net"):
        # the ElasticTrainer seam: run_id + final checkpoint, no
        # filename parsing
        return {"model": res.net, "run_id": res.run_id,
                "checkpoint_path": res.final_checkpoint_path,
                "ckpt_manager": getattr(res, "ckpt", None)}
    return {"model": res, "run_id": f"gen{generation}",
            "checkpoint_path": getattr(res, "_checkpoint_path", None),
            "ckpt_manager": None}


class PromotionPipeline:
    """The flywheel controller (module docstring has the state machine).

    >>> pipe = PromotionPipeline(registry, fleet, "m", train_fn, gate,
    ...                          journal_path="runs/pipeline.jsonl")
    >>> reports = pipe.run(generations=5)

    ``train_fn(generation)`` produces the candidate (see
    ``_normalize_train_result`` for accepted shapes).  ``fleet`` may be
    None for a canary-only deployment (promotion ends at the alias
    flip).  A second controller constructed over the same
    ``journal_path`` resumes mid-flywheel: completed generations are
    skipped, a partially-complete generation continues from its first
    unfinished stage (TRAIN results are recovered from the journaled
    checkpoint path — never retrained).

    ``stage_retries`` / ``stage_deadline_s`` take a single value or a
    per-stage dict ({"TRAIN": 2, ...}).  Deadlines are wall-clock
    budgets checked when the stage completes (hang detection *inside* a
    stage belongs to the stage's own machinery, e.g. ElasticTrainer's
    ``step_timeout``).  ``stage_hook(stage, generation)`` is the
    chaos/test seam, called before each stage attempt — raising from it
    simulates a controller crash mid-flywheel.
    """

    def __init__(self, registry: ModelRegistry, fleet, name: str,
                 train_fn: Callable[[int], Any], eval_gate: EvalGate, *,
                 alias: str = "prod",
                 journal_path: str,
                 canary_frac: float = 0.2,
                 canary_window: int = 32,
                 canary_timeout_s: float = 60.0,
                 canary_thresholds: Optional[Dict[str, Any]] = None,
                 build_warm_bundle: bool = True,
                 bundle_engine_kwargs: Optional[Dict[str, Any]] = None,
                 stage_retries: Any = 1,
                 stage_deadline_s: Any = None,
                 drain_timeout_s: float = 30.0,
                 data_slice: Any = None,
                 loader: Optional[Callable[[str], Any]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 stage_hook: Optional[Callable[[str, int], None]] = None):
        self.registry = registry
        self.fleet = fleet
        self.name = name
        self.alias = alias
        self.train_fn = train_fn
        self.eval_gate = eval_gate
        self.journal = PipelineJournal(journal_path)
        self.canary_frac = canary_frac
        self.canary_window = canary_window
        self.canary_timeout_s = canary_timeout_s
        self.canary_thresholds = dict(canary_thresholds or {})
        self.build_warm_bundle = build_warm_bundle
        self.bundle_engine_kwargs = dict(bundle_engine_kwargs
                                         or {"max_batch": 8, "replicas": 1,
                                             "slo_ms": 30_000.0})
        self._retries = stage_retries
        self._deadlines = stage_deadline_s
        self.drain_timeout_s = drain_timeout_s
        # the data slice this pipeline trains/evals on — a fingerprint
        # string, arrays/DataSets (fingerprinted once), or a callable
        # (generation -> either), stamped into every lineage record
        self.data_slice = data_slice
        self.loader = loader or self._default_loader
        self.clock = clock
        self.stage_hook = stage_hook

        self._resumed = False
        self._completed: Dict[int, dict] = {}    # gen -> terminal record
        self._partial: Optional[dict] = None     # in-flight gen state
        self._history: List[dict] = []           # this controller's reports
        self._current: Optional[dict] = None     # live {gen, stage} view

        reg = get_registry()
        self._m_generations = reg.counter("pipeline_generations_total")
        self._m_promoted = reg.counter("pipeline_promoted_total")
        self._m_rolled_back = reg.counter("pipeline_rolled_back_total")
        self._m_canary_rej = reg.counter("pipeline_canary_rejected_total")
        self._m_eval_failed = reg.counter("pipeline_eval_failed_total")
        self._m_retries = reg.counter("pipeline_stage_retries_total")
        self._m_resumes = reg.counter("pipeline_resumes_total")
        self.resumes = 0
        reg.register_collector("lifecycle", self.stats, unique=True)

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """Structured flywheel state (the ``lifecycle`` collector)."""
        outcomes = [r.get("outcome") for r in self._completed.values()]
        return {"name": self.name, "alias": self.alias,
                "generations_done": len(self._completed),
                "promoted": outcomes.count(PROMOTED),
                "rolled_back": outcomes.count(ROLLED_BACK),
                "resumes": self.resumes,
                "current": dict(self._current) if self._current else None}

    @property
    def completed(self) -> Dict[int, dict]:
        """Terminal records by generation number (a copy)."""
        return dict(self._completed)

    # -- configuration helpers ---------------------------------------------

    @staticmethod
    def _default_loader(path: str):
        from ..utils.serializer import load_model
        model = load_model(path)
        model._checkpoint_path = str(path)
        return model

    def _per_stage(self, spec: Any, stage: str, default: Any):
        if isinstance(spec, dict):
            return spec.get(stage, default)
        return default if spec is None else spec

    def _data_fingerprint(self, generation: int) -> Optional[str]:
        s = self.data_slice
        if callable(s):
            s = s(generation)
        if s is None or isinstance(s, str):
            return s
        if isinstance(s, (list, tuple)):
            return data_fingerprint(*s)
        return data_fingerprint(s)

    # -- journal replay / resume -------------------------------------------

    def resume(self) -> dict:
        """Replay the journal and rebuild flywheel state; called
        implicitly by ``run``/``run_generation`` on first use.  Returns
        ``{"completed": [...], "partial": gen|None}``."""
        records = self.journal.replay()
        self._completed.clear()
        partial: Dict[int, dict] = {}
        for rec in records:
            g = int(rec.get("gen", 0))
            stage = rec.get("stage")
            if stage in TERMINAL_STATES:
                partial.pop(g, None)
                rec = dict(rec)
                rec["outcome"] = stage
                self._completed[g] = rec
                continue
            if rec.get("status") != "done":
                continue
            st = partial.setdefault(g, self._fresh_state(g))
            st["done"].append(stage)
            for k in ("run_id", "checkpoint_path", "eval_score",
                      "eval_passed", "eval_reason", "version",
                      "parent_version", "weights_sha", "bundle_path"):
                if k in rec:
                    st[k] = rec[k]
        self._partial = (partial[max(partial)] if partial else None)
        was_fresh = not records
        self._resumed = True
        if not was_fresh:
            self.resumes += 1
            self._m_resumes.inc()
            obs_trace.instant("pipeline/resume", cat="pipeline",
                              completed=len(self._completed),
                              partial=(self._partial or {}).get("gen"))
        return {"completed": sorted(self._completed),
                "partial": (self._partial or {}).get("gen")}

    def _ensure_resumed(self) -> None:
        if not self._resumed:
            self.resume()

    def _fresh_state(self, gen: int) -> dict:
        return {"gen": gen, "done": [], "model": None, "ckpt_manager": None,
                "run_id": None, "checkpoint_path": None,
                "eval_score": None, "eval_passed": None, "eval_reason": None,
                "version": None, "parent_version": None,
                "weights_sha": None, "bundle_path": None}

    def _next_generation_number(self) -> int:
        gens = list(self._completed)
        if self._partial is not None:
            gens.append(self._partial["gen"])
        return max(gens, default=0) + 1

    # -- stage machinery ---------------------------------------------------

    def _journal_done(self, st: dict, stage: str, **fields) -> None:
        st["done"].append(stage)
        rec = {"gen": st["gen"], "stage": stage, "status": "done"}
        rec.update(fields)
        self.journal.append(rec)

    def _attempt(self, st: dict, stage: str, fn: Callable[[], Any],
                 no_retry: tuple = ()) -> Any:
        """One stage body under the retry budget + deadline.  Exceptions
        in ``no_retry`` (verdicts, not faults) propagate immediately."""
        retries = int(self._per_stage(self._retries, stage, 1))
        deadline = self._per_stage(self._deadlines, stage, None)
        attempt = 0
        while True:
            attempt += 1
            self._current = {"gen": st["gen"], "stage": stage,
                             "attempt": attempt}
            if self.stage_hook is not None:
                # the chaos seam runs OUTSIDE the retry try: a raise here
                # is a controller crash, not a stage failure
                self.stage_hook(stage, st["gen"])
            t0 = self.clock()
            try:
                with obs_trace.span("pipeline/stage", cat="pipeline",
                                    stage=stage, generation=st["gen"],
                                    attempt=attempt):
                    out = fn()
                elapsed = self.clock() - t0
                if deadline is not None and elapsed > float(deadline):
                    raise StageDeadlineError(
                        stage, st["gen"],
                        f"took {elapsed:.2f}s > deadline {deadline:.2f}s")
                return out
            except no_retry:
                raise
            except Exception as exc:
                if attempt > retries:
                    if isinstance(exc, PipelineStageError):
                        raise
                    raise PipelineStageError(
                        stage, st["gen"],
                        f"failed after {attempt} attempts: "
                        f"{type(exc).__name__}: {exc}") from exc
                logger.warning("pipeline %s gen %d attempt %d failed: %s — "
                               "retrying", stage, st["gen"], attempt, exc)
                obs_trace.instant("pipeline/retry", cat="pipeline",
                                  stage=stage, generation=st["gen"],
                                  attempt=attempt)
                self._m_retries.inc()

    def _model_for(self, st: dict):
        """The generation's candidate model, recovered on resume from
        the journaled checkpoint (never retrained) or the registry."""
        if st["model"] is not None:
            return st["model"]
        if st["version"] is not None:
            try:
                st["model"] = self.registry.resolve(self.name,
                                                    st["version"])[1]
                return st["model"]
            # graftcheck: disable=GC403 (registry.resolve is a model-version lookup, not a future resolution; fresh-process resume falls through to the checkpoint on disk)
            except KeyError:
                pass
        if st["checkpoint_path"]:
            st["model"] = self.loader(st["checkpoint_path"])
            return st["model"]
        raise PipelineStageError(
            st.get("_stage", EVAL), st["gen"],
            "no model recoverable: journal has neither a registered "
            "version nor a checkpoint path")

    # -- stages ------------------------------------------------------------

    def _do_train(self, st: dict) -> None:
        if TRAIN in st["done"]:
            return
        res = self._attempt(st, TRAIN,
                            lambda: self.train_fn(st["gen"]))
        tr = _normalize_train_result(res, st["gen"])
        st["model"] = tr["model"]
        st["run_id"] = tr["run_id"]
        st["checkpoint_path"] = tr["checkpoint_path"]
        st["ckpt_manager"] = tr["ckpt_manager"]
        self._journal_done(st, TRAIN, run_id=st["run_id"],
                           checkpoint_path=st["checkpoint_path"])

    def _do_eval(self, st: dict) -> None:
        if EVAL in st["done"]:
            return
        verdict = self._attempt(
            st, EVAL, lambda: self.eval_gate.check(self._model_for(st)))
        st["eval_score"] = verdict["score"]
        st["eval_passed"] = verdict["passed"]
        st["eval_reason"] = verdict["reason"]
        if not verdict["passed"]:
            self._m_eval_failed.inc()
        self._journal_done(st, EVAL, eval_score=st["eval_score"],
                           eval_passed=st["eval_passed"],
                           eval_reason=st["eval_reason"])

    def _do_register(self, st: dict) -> None:
        if REGISTER in st["done"] and st["version"] is not None \
                and st["version"] in self.registry.versions(self.name):
            return

        def register():
            # idempotency across crash-resume: the run may already have
            # landed (crash between registry call and journal append)
            sha = st["weights_sha"] or weights_sha(self._model_for(st))
            st["weights_sha"] = sha
            for rec in self.registry.lineage(self.name):
                if rec.get("run_id") == st["run_id"] \
                        and rec.get("weights_sha") == sha:
                    return rec["version"], rec.get("parent_version")
            try:
                parent = self.registry.resolve(self.name, self.alias)[0]
            # graftcheck: disable=GC403 (registry.resolve is a model-version lookup, not a future resolution; no alias yet means no parent)
            except KeyError:
                parent = None
            lineage = {"run_id": st["run_id"],
                       "data_fingerprint":
                           self._data_fingerprint(st["gen"]),
                       "parent_version": parent,
                       "eval_score": st["eval_score"],
                       "eval_passed": st["eval_passed"],
                       "weights_sha": sha}
            if st["checkpoint_path"]:
                v = self.registry.load(self.name, st["checkpoint_path"],
                                       version=st["version"],
                                       lineage=lineage)
            else:
                v = self.registry.register(self.name, self._model_for(st),
                                           version=st["version"],
                                           lineage=lineage)
            return v, parent

        st["version"], st["parent_version"] = self._attempt(
            st, REGISTER, register)
        # downstream stages serve the REGISTRY's copy (it carries the
        # checkpoint provenance canary/bundle seams key on)
        st["model"] = self.registry.resolve(self.name, st["version"])[1]
        ckpt_mgr = st.get("ckpt_manager")
        if ckpt_mgr is not None and st["checkpoint_path"] \
                and hasattr(ckpt_mgr, "note_registered"):
            ckpt_mgr.note_registered(st["checkpoint_path"], self.name,
                                     st["version"])
        if self.build_warm_bundle and st["checkpoint_path"] \
                and st["eval_passed"]:
            st["bundle_path"] = self._build_bundle(st)
        self._journal_done(st, REGISTER, version=st["version"],
                           parent_version=st["parent_version"],
                           weights_sha=st["weights_sha"],
                           bundle_path=st["bundle_path"])

    def _build_bundle(self, st: dict) -> Optional[str]:
        """Warm bundle at save time: compile the candidate's executables
        ONCE here, pipeline-side, so every fleet host's swap
        deserializes instead of compiling (zero serve-time compiles)."""
        from . import warmcache
        from .engine import Engine

        path = warmcache.bundle_path_for(st["checkpoint_path"])
        if os.path.exists(path):
            return path
        tag = f"{self.name}:v{st['version']}"
        eng = Engine(self._model_for(st), **self.bundle_engine_kwargs)
        try:
            eng.load()
            # the bundle tag must match the tag hosts swap under, or
            # the load-side tag check rejects it
            with eng._vlock:
                eng._current.tag = tag
            return eng.save_warmup_bundle(path)
        except Exception as exc:
            # bundle building is an optimization: a model the Engine
            # can't AOT-warm still promotes, it just compiles at swap
            logger.warning("warm-bundle build failed for %s (%s) — hosts "
                           "will compile at swap", tag, exc)
            return None
        finally:
            eng.shutdown()

    def _do_canary(self, st: dict) -> None:
        v = st["version"]
        try:
            cur = self.registry.resolve(self.name, self.alias)[0]
        # graftcheck: disable=GC403 (registry.resolve is a model-version lookup, not a future resolution; no alias yet means no incumbent)
        except KeyError:
            cur = None
        if CANARY in st["done"] or cur == v:
            return
        self._attempt(
            st, CANARY,
            lambda: self.registry.set_alias(
                self.name, self.alias, v,
                canary=self.canary_frac,
                canary_window=self.canary_window,
                canary_timeout_s=self.canary_timeout_s,
                canary_thresholds=self.canary_thresholds,
                raise_on_reject=True),
            no_retry=(CanaryRejectedError,))
        self._journal_done(st, CANARY, promoted_from=cur)

    def _fleet_on(self, tag: str) -> bool:
        """True iff EVERY up fleet host serves ``tag`` (per-host tags,
        not ``current_tag`` — a canary host that self-swapped ahead of
        the roll must not make the whole fleet look promoted)."""
        if hasattr(self.fleet, "tags"):
            tags = self.fleet.tags()
            return bool(tags) and all(t == tag for t in tags.values())
        return self.fleet.current_tag == tag

    def _do_roll(self, st: dict) -> None:
        if ROLL in st["done"] or self.fleet is None:
            if ROLL not in st["done"]:
                self._journal_done(st, ROLL, skipped="no fleet")
            return
        v = st["version"]
        tag = f"{self.name}:v{v}"
        if self._fleet_on(tag):
            # resume idempotency: every up host already serves the
            # candidate (the roll completed but its journal line was
            # lost to the crash)
            self._journal_done(st, ROLL, already_current=True)
            return
        parent = st["parent_version"]
        rollback_model = rollback_tag = None
        if parent is not None and parent in self.registry.versions(self.name):
            rollback_model = self.registry.resolve(self.name, parent)[1]
            rollback_tag = f"{self.name}:v{parent}"
        bundle = st["bundle_path"] if (st["bundle_path"]
                                       and os.path.exists(st["bundle_path"])
                                       ) else None
        report = self._attempt(
            st, ROLL,
            lambda: self.fleet.rolling_swap(
                self._model_for(st), tag,
                rollback_model=rollback_model, rollback_tag=rollback_tag,
                drain_timeout_s=self.drain_timeout_s, warm_bundle=bundle))
        if not report.get("ok"):
            # a mid-roll host death is a verdict, not a transient: the
            # fleet already rolled its survivors back — abort the
            # generation (retrying onto a degraded fleet is a policy
            # decision the operator makes, not this controller)
            raise PipelineStageError(
                ROLL, st["gen"],
                f"rolling swap failed on host {report.get('failed_host')}: "
                f"{report.get('error')}")
        self._journal_done(st, ROLL, swapped=report.get("swapped"))

    # -- terminals ---------------------------------------------------------

    def _finish_promoted(self, st: dict) -> dict:
        rec = {"gen": st["gen"], "stage": PROMOTED, "outcome": PROMOTED,
               "version": st["version"], "run_id": st["run_id"],
               "eval_score": st["eval_score"],
               "parent_version": st["parent_version"]}
        self.journal.append(rec)
        self._completed[st["gen"]] = rec
        self._partial = None
        self._current = None
        self._m_generations.inc()
        self._m_promoted.inc()
        obs_trace.instant("pipeline/promoted", cat="pipeline",
                          generation=st["gen"], version=st["version"],
                          eval_score=st["eval_score"])
        self._history.append(rec)
        return dict(rec)

    def _finish_rolled_back(self, st: dict, reason: str,
                            canary_record: Optional[dict] = None) -> dict:
        target = self._rollback(st)
        rec = {"gen": st["gen"], "stage": ROLLED_BACK,
               "outcome": ROLLED_BACK, "reason": reason,
               "version": st["version"], "run_id": st["run_id"],
               "eval_score": st["eval_score"], "rolled_back_to": target}
        if canary_record is not None:
            rec["canary"] = {"promoted": canary_record.get("promoted"),
                             "from": canary_record.get("from"),
                             "to": canary_record.get("to")}
        self.journal.append(rec)
        self._completed[st["gen"]] = rec
        self._partial = None
        self._current = None
        self._m_generations.inc()
        self._m_rolled_back.inc()
        obs_trace.instant("pipeline/rolled_back", cat="pipeline",
                          generation=st["gen"], version=st["version"],
                          target=target, reason=reason)
        self._history.append(rec)
        return dict(rec)

    def _rollback(self, st: dict) -> Optional[int]:
        """Re-alias to the lineage-selected target and re-roll the fleet
        onto it if it is serving anything else.  Returns the target
        version (None = nothing promoted yet, nothing to restore)."""
        name, alias = self.name, self.alias
        try:
            cur = self.registry.resolve(name, alias)[0]
        # graftcheck: disable=GC403 (registry.resolve is a model-version lookup, not a future resolution; no alias yet means nothing to restore)
        except KeyError:
            cur = None
        if st["version"] is not None \
                and st["version"] in self.registry.versions(name):
            target = self.registry.rollback_target(name,
                                                   version=st["version"])
        else:
            target = cur
        if target is None:
            return None
        if cur != target:
            # the candidate's canary flip (or a partial promote) moved
            # the alias — put it back on the lineage target; subscribed
            # engines follow the plain set_alias swap
            self.registry.set_alias(name, alias, target)
        if self.fleet is not None:
            ttag = f"{name}:v{target}"
            if not self._fleet_on(ttag):
                bundle = None
                ckpt = self.registry.checkpoint_path(name, target)
                if ckpt:
                    from . import warmcache
                    bp = warmcache.bundle_path_for(ckpt)
                    bundle = bp if os.path.exists(bp) else None
                model = self.registry.resolve(name, target)[1]
                try:
                    self.fleet.rolling_swap(model, ttag,
                                            drain_timeout_s=
                                            self.drain_timeout_s,
                                            warm_bundle=bundle)
                except Exception as exc:
                    # rollback must land the terminal state even when the
                    # fleet is too degraded to re-roll — the alias (the
                    # source of truth) is already on the target
                    logger.error("rollback re-roll to %s failed: %s",
                                 ttag, exc)
        return target

    # -- driving the flywheel ----------------------------------------------

    def run_generation(self) -> dict:
        """Run ONE generation to a terminal state (resuming a partial
        generation from the journal first) and return its report."""
        self._ensure_resumed()
        if self._partial is not None:
            st = self._partial
            st.setdefault("model", None)
            st.setdefault("ckpt_manager", None)
        else:
            st = self._fresh_state(self._next_generation_number())
            self._partial = st
        with obs_trace.span("pipeline/generation", cat="pipeline",
                            generation=st["gen"]):
            try:
                self._do_train(st)
                self._do_eval(st)
                self._do_register(st)
                if not st["eval_passed"]:
                    return self._finish_rolled_back(
                        st, f"eval gate failed: {st['eval_reason']}")
                self._do_canary(st)
                self._do_roll(st)
                return self._finish_promoted(st)
            except CanaryRejectedError as exc:
                self._m_canary_rej.inc()
                return self._finish_rolled_back(
                    st, f"canary rejected: {'; '.join(exc.reasons)}",
                    canary_record=exc.record)
            except PipelineStageError as exc:
                return self._finish_rolled_back(st, str(exc))

    def run(self, generations: int) -> List[dict]:
        """Drive the flywheel until ``generations`` generations have
        reached a terminal state (journaled generations count), and
        return every generation's terminal record, oldest first."""
        self._ensure_resumed()
        while len(self._completed) < generations:
            self.run_generation()
        return [dict(self._completed[g]) for g in sorted(self._completed)]
