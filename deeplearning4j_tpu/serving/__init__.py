"""Serving (L5): the production inference subsystem.

Supersedes the old ``parallel/inference.py`` batched-queue toy (which
remains as a thin back-compat shim over this engine).  Pieces:

  batcher.py   deadline-aware dynamic micro-batching, pow2 shape
               buckets, admission control (block/shed)
  registry.py  versioned model registry, alias pinning ("prod" -> v7),
               hot-swap that drains in-flight batches, rollback = alias
               move, canary promotion with auto-rollback
               (set_alias(..., canary=frac)); loads serializer
               FORMAT_VERSION 1-4 checkpoints
  engine.py    N engine replicas over jax.local_devices(), round-robin
               dispatch with per-replica in-flight caps, AOT warmup of
               every (bucket, dtype) pair at load; replica supervision
               (crash/hang detect → retry elsewhere → respawn+re-warm,
               per-replica circuit breaker), poison-input bisection,
               typed request errors — every future always resolves
  metrics.py   fixed-bucket latency histograms + counters (incl. retry/
               respawn/circuit/canary/poison), exported on ui/server.py's
               /metrics endpoint (health on /healthz)
  decode.py    autoregressive decode engine for the transformer LM:
               paged KV-cache (ops/kv_cache.py), bucketed prefill/decode
               split, iteration-level continuous batching, seeded
               deterministic sampling, per-request stop conditions,
               crash-retry/poison-isolation/hot-swap decode-shaped;
               TTFT + time-per-output-token first-class (DecodeMetrics);
               prefill/decode disaggregation (role=..., PrefillHandoff
               KV-page transfer) + tensor-parallel sharded decode
  warmcache.py zero-cold-start: process-wide JAX persistent compile
               cache (DL4J_TPU_COMPILE_CACHE / --compile-cache) +
               warmup bundles (serialized AOT executables next to the
               checkpoint zip; silent fallback to compile on any miss)
  autoscale.py load-driven replica autoscaling controller (hysteresis +
               cooldown + bounds, injectable clock); actuated by the
               engine supervisor loops via PR-7 birth/retire machinery
  tenancy.py   multi-tenant admission: TenantConfig SLO classes
               (slo_ms, fair-share weight, qps/concurrent quotas,
               shed/block policy), TenantTable atomic check-and-charge,
               typed TenantOverloadedError carrying the tenant — the
               batchers' per-tenant weighted-fair lanes read this table
  placement.py traffic-driven (model, host) placement over one fleet:
               per-model EWMA demand + the autoscale control law widen
               hot models, narrow/evict cold ones (warm-bundle loads),
               and demand-reload on a router model miss
  lifecycle.py the production flywheel: PromotionPipeline runs
               TRAIN → EVAL → REGISTER → CANARY → ROLL repeatedly with
               lineage-provenance registration, warm-bundle-at-save,
               bounded retries/deadlines, a crash-resumable journal,
               and lineage-aware regression rollback (docs/LIFECYCLE.md)

Reference lineage: DL4J's ParallelInference BATCHED mode + the model-
server role; design cf. the serving sections of "TensorFlow: A system
for large-scale machine learning" and TPU serving practice (PAPERS.md).
See docs/SERVING.md.
"""

from .autoscale import ReplicaAutoscaler
from .batcher import (
    ADMISSION_POLICIES, ContinuousBatcher, DeadlineExceededError,
    DynamicBatcher, OverloadedError, pow2_buckets,
)
from .decode import DecodeEngine, GenerationResult, PrefillHandoff
from .engine import (
    Engine, ModelNotLoadedError, PoisonInputError, ReplicaCrashError,
    ReplicaHungError, ServingUnavailableError,
)
from .fleet import FleetHost, FleetRouter, FleetTimeoutError, HttpHost
from .placement import PlacementController
from .tenancy import TenantConfig, TenantOverloadedError, TenantTable
from .lifecycle import (
    EvalGate, PipelineJournal, PipelineStageError, PromotionPipeline,
    StageDeadlineError, data_fingerprint, weights_sha,
)
from .metrics import (DecodeMetrics, FleetMetrics, LatencyHistogram,
                      ServingMetrics)
from .registry import CanaryRejectedError, ModelRegistry
from .warmcache import (
    bundle_path_for, device_fingerprint, enable_compile_cache, load_bundle,
    save_bundle,
)

__all__ = [
    "ADMISSION_POLICIES", "CanaryRejectedError", "ContinuousBatcher",
    "DeadlineExceededError",
    "DecodeEngine", "DecodeMetrics", "DynamicBatcher", "Engine",
    "EvalGate",
    "FleetHost", "FleetMetrics", "FleetRouter", "FleetTimeoutError",
    "GenerationResult", "HttpHost", "LatencyHistogram",
    "ModelNotLoadedError", "ModelRegistry",
    "OverloadedError", "PipelineJournal", "PipelineStageError",
    "PlacementController", "PoisonInputError", "PrefillHandoff",
    "PromotionPipeline", "ReplicaAutoscaler",
    "ReplicaCrashError", "ReplicaHungError", "ServingMetrics",
    "ServingUnavailableError", "StageDeadlineError", "TenantConfig",
    "TenantOverloadedError", "TenantTable", "bundle_path_for",
    "data_fingerprint", "device_fingerprint",
    "enable_compile_cache", "load_bundle", "pow2_buckets", "save_bundle",
    "weights_sha",
]
