"""Serving observability: fixed-bucket latency histograms + counters.

The reference stack exports serving metrics through its model-server's
/metrics-style endpoints; here a `ServingMetrics` instance is owned by one
`serving.Engine` and exported two ways: `snapshot()` (a plain dict, the
test/API surface) and the `ui/server.py` `/metrics` JSON endpoint.

Histograms are FIXED-bucket (exponential ms boundaries), not reservoirs:
recording is O(#buckets) worst case, lock-held time is tiny, and snapshots
are mergeable across engines — the properties a hot serving path needs.
Percentiles are estimated by linear interpolation inside the winning
bucket, so p99 on a 17-bucket histogram is approximate by design; tests
that need exact latencies read `count`/`sum_ms` or time externally.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

# 0.1 ms .. 10 s — covers a jitted forward on any sane hardware on the
# left and a pathological queue stall on the right; +inf is implicit
DEFAULT_BUCKETS_MS: Sequence[float] = (
    0.1, 0.2, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0,
    200.0, 500.0, 1000.0, 2000.0, 5000.0, 10000.0,
)


class LatencyHistogram:
    """Fixed-boundary histogram over milliseconds (thread-safe)."""

    def __init__(self, buckets_ms: Sequence[float] = DEFAULT_BUCKETS_MS):
        self.bounds = tuple(sorted(buckets_ms))
        self._counts = [0] * (len(self.bounds) + 1)  # +1 = overflow
        self.count = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0
        self._lock = threading.Lock()

    def record(self, ms: float) -> None:
        i = 0
        for i, b in enumerate(self.bounds):
            if ms <= b:
                break
        else:
            i = len(self.bounds)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum_ms += ms
            if ms > self.max_ms:
                self.max_ms = ms

    def percentile(self, p: float) -> Optional[float]:
        """Approximate p-th percentile (0<p<=100) via in-bucket linear
        interpolation; None when empty.  Overflow-bucket hits report the
        max seen (there is no upper boundary to interpolate against)."""
        with self._lock:
            if not self.count:
                return None
            rank = p / 100.0 * self.count
            seen = 0
            for i, c in enumerate(self._counts):
                if not c:
                    continue
                if seen + c >= rank:
                    if i >= len(self.bounds):
                        return self.max_ms
                    lo = self.bounds[i - 1] if i else 0.0
                    hi = self.bounds[i]
                    frac = (rank - seen) / c
                    return lo + (hi - lo) * frac
                seen += c
            return self.max_ms

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            count, total, mx = self.count, self.sum_ms, self.max_ms
        out = {"count": count, "sum_ms": round(total, 3),
               "max_ms": round(mx, 3),
               "mean_ms": round(total / count, 3) if count else None,
               "buckets_ms": list(self.bounds), "counts": counts}
        for p in (50, 90, 99):
            v = self.percentile(p)
            out[f"p{p}_ms"] = round(v, 3) if v is not None else None
        return out


class ServingMetrics:
    """Per-engine metric set: three latency histograms (queue wait,
    device time, end-to-end) + batching/admission counters.

    Batch occupancy (padding waste) is the satellite-regression metric:
    ``padded_rows / (rows + padded_rows)`` should stay near zero when
    request sizes align with buckets — a drain that overshoots
    ``max_batch`` before bucketing (the old ``ParallelInference._run``
    bug) shows up here as waste and as ``max_batch_rows`` > max_batch."""

    def __init__(self, buckets_ms: Sequence[float] = DEFAULT_BUCKETS_MS):
        self.queue_wait = LatencyHistogram(buckets_ms)
        self.device_time = LatencyHistogram(buckets_ms)
        self.e2e = LatencyHistogram(buckets_ms)
        self._lock = threading.Lock()
        self._c: Dict[str, int] = {
            "requests": 0, "rows": 0, "batches": 0, "padded_rows": 0,
            "shed": 0, "deadline_missed": 0, "errors": 0, "swaps": 0,
            "unwarmed_serves": 0,
            # resilience counters (docs/SERVING.md "Failure model"):
            # supervisor interventions, request retries, poison isolation,
            # breaker trips, and canary promotion decisions
            "replica_crashes": 0, "replica_hangs": 0, "replica_respawns": 0,
            "retries": 0, "poison_isolated": 0, "circuit_opens": 0,
            "canary_promotions": 0, "canary_rollbacks": 0,
            "canary_mirrored_batches": 0,
        }
        self._batch_rows_max = 0
        self._t0 = time.monotonic()

    def inc(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._c[key] = self._c.get(key, 0) + n

    def record_batch(self, n_requests: int, rows: int, padded_rows: int,
                     device_ms: float) -> None:
        with self._lock:
            self._c["batches"] += 1
            self._c["requests"] += n_requests
            self._c["rows"] += rows
            self._c["padded_rows"] += padded_rows
            if rows > self._batch_rows_max:
                self._batch_rows_max = rows
        self.device_time.record(device_ms)

    def snapshot(self) -> dict:
        with self._lock:
            c = dict(self._c)
            rows_max = self._batch_rows_max
            elapsed = time.monotonic() - self._t0
        total = c["rows"] + c["padded_rows"]
        return {
            "counters": c,
            "max_batch_rows": rows_max,
            "batch_occupancy": round(c["rows"] / total, 4) if total else None,
            "requests_per_sec": round(c["requests"] / elapsed, 2)
            if elapsed > 0 else None,
            "uptime_sec": round(elapsed, 3),
            "queue_wait_ms": self.queue_wait.snapshot(),
            "device_time_ms": self.device_time.snapshot(),
            "e2e_ms": self.e2e.snapshot(),
        }
