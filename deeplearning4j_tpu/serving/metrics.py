"""Serving observability, on the unified registry (obs/metrics.py).

The reference stack exports serving metrics through its model-server's
/metrics-style endpoints; here a `ServingMetrics` instance is owned by one
`serving.Engine` and exported three ways: `snapshot()` (the legacy plain
dict — the test/API surface, schema unchanged since PR 4), the per-engine
``registry`` (typed instruments, one schema with every other subsystem),
and the process-global ``obs.metrics.get_registry()`` — each
ServingMetrics registers itself as a collector there, so one
``MetricsRegistry.snapshot()`` / one ``UIServer /metrics`` response
carries every live engine alongside the elastic / input-pipeline /
launcher stats (docs/OBSERVABILITY.md).

Histograms are FIXED-bucket (exponential ms boundaries), not reservoirs:
recording is O(#buckets) worst case, lock-held time is tiny, and snapshots
are mergeable across engines — the properties a hot serving path needs.
Percentiles are estimated by linear interpolation inside the winning
bucket, so p99 on a 17-bucket histogram is approximate by design; tests
that need exact latencies read `count`/`sum_ms` or time externally.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Sequence

from ..obs.metrics import (
    DEFAULT_LATENCY_BUCKETS_MS, Histogram, MetricsRegistry, get_registry,
)

# kept as the serving-local name; one source of truth in obs/metrics.py
DEFAULT_BUCKETS_MS: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS


class LatencyHistogram(Histogram):
    """The unified fixed-bucket histogram with the serving-legacy
    millisecond surface: ``record(ms)``, ``count``/``sum_ms``/``max_ms``
    attributes, and the ``*_ms``-keyed ``snapshot()`` schema the serving
    tests and A/B scripts read."""

    def __init__(self, buckets_ms: Sequence[float] = DEFAULT_BUCKETS_MS,
                 name: str = "latency_ms"):
        super().__init__(name, buckets_ms)

    def _unlabeled(self):
        with self._lock:
            return self._series.get(())

    @property
    def count(self) -> int:
        s = self._unlabeled()
        return s.count if s else 0

    @property
    def sum_ms(self) -> float:
        s = self._unlabeled()
        return s.total if s else 0.0

    @property
    def max_ms(self) -> float:
        s = self._unlabeled()
        return s.max_value if s else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            s = self._series.get(())
            counts = list(s.counts) if s else [0] * (len(self.bounds) + 1)
            count = s.count if s else 0
            total = s.total if s else 0.0
            mx = s.max_value if s else 0.0
        out = {"count": count, "sum_ms": round(total, 3),
               "max_ms": round(mx, 3),
               "mean_ms": round(total / count, 3) if count else None,
               "buckets_ms": list(self.bounds), "counts": counts}
        for p in (50, 90, 99):
            v = self.percentile(p)
            out[f"p{p}_ms"] = round(v, 3) if v is not None else None
        return out


# every counter a fresh engine reports as zero (docs/SERVING.md: the
# batching/admission set, then the resilience + canary set, then the
# cold-start/autoscale set)
_COUNTER_KEYS = (
    "requests", "rows", "batches", "padded_rows",
    "shed", "deadline_missed", "errors", "swaps", "unwarmed_serves",
    "replica_crashes", "replica_hangs", "replica_respawns",
    "respawn_failures",
    "retries", "poison_isolated", "circuit_opens",
    "canary_promotions", "canary_rollbacks", "canary_mirrored_batches",
    "warmup_seconds_total", "bundle_hits", "bundle_misses",
    "scale_ups", "scale_downs",
    "model_loads", "model_evictions",
)


class ServingMetrics:
    """Per-engine metric set: three latency histograms (queue wait,
    device time, end-to-end) + batching/admission/resilience counters —
    all typed instruments in the per-engine ``registry``.

    Batch occupancy (padding waste) is the satellite-regression metric:
    ``padded_rows / (rows + padded_rows)`` should stay near zero when
    request sizes align with buckets — a drain that overshoots
    ``max_batch`` before bucketing (the old ``ParallelInference._run``
    bug) shows up here as waste and as ``max_batch_rows`` > max_batch."""

    def __init__(self, buckets_ms: Sequence[float] = DEFAULT_BUCKETS_MS,
                 registry: MetricsRegistry = None):
        self.registry = registry or MetricsRegistry()
        self.queue_wait = self.registry.register(
            LatencyHistogram(buckets_ms, name="queue_wait_ms"))
        self.device_time = self.registry.register(
            LatencyHistogram(buckets_ms, name="device_time_ms"))
        self.e2e = self.registry.register(
            LatencyHistogram(buckets_ms, name="e2e_ms"))
        self._counters = {k: self.registry.counter(k) for k in _COUNTER_KEYS}
        self._lock = threading.Lock()
        self._batch_rows_max = 0
        self._rows_max_gauge = self.registry.gauge("max_batch_rows")
        self._rows_max_gauge.set(0)
        self._t0 = time.monotonic()
        # one process-wide surface: every live engine's snapshot rides
        # the global registry (weakly — a dropped engine unregisters)
        self.global_name = get_registry().register_collector(
            "serving", self.snapshot, unique=True)

    def inc(self, key: str, n: int = 1, tenant: str = None) -> None:
        c = self._counters.get(key)
        if c is None:        # open key set, as before the migration
            with self._lock:
                c = self._counters.get(key)
                if c is None:
                    c = self._counters[key] = self.registry.counter(key)
        c.inc(n)
        if tenant:
            # a per-tenant label slice of the same instrument — shows up
            # in the registry snapshot as ``key{tenant=...}`` (the
            # unlabeled series above stays the all-tenants total)
            c.inc(n, tenant=tenant)

    def counter_value(self, key: str, tenant: str = None) -> float:
        """Current value of one counter (0.0 if never incremented) — the
        cheap read the autoscaler's shed-delta signal polls.  With
        ``tenant``, reads that tenant's label slice."""
        c = self._counters.get(key)
        if c is None:
            return 0.0
        return float(c.value(tenant=tenant) if tenant else c.value())

    def record_batch(self, n_requests: int, rows: int, padded_rows: int,
                     device_ms: float) -> None:
        self._counters["batches"].inc()
        self._counters["requests"].inc(n_requests)
        self._counters["rows"].inc(rows)
        self._counters["padded_rows"].inc(padded_rows)
        with self._lock:
            if rows > self._batch_rows_max:
                self._batch_rows_max = rows
                self._rows_max_gauge.set(rows)
        self.device_time.record(device_ms)

    def snapshot(self) -> dict:
        c: Dict[str, int] = {}
        for k, counter in list(self._counters.items()):
            v = counter.value()
            c[k] = int(v) if float(v).is_integer() else v
        with self._lock:
            rows_max = self._batch_rows_max
        elapsed = time.monotonic() - self._t0
        total = c["rows"] + c["padded_rows"]
        return {
            "counters": c,
            "max_batch_rows": rows_max,
            "batch_occupancy": round(c["rows"] / total, 4) if total else None,
            "requests_per_sec": round(c["requests"] / elapsed, 2)
            if elapsed > 0 else None,
            "uptime_sec": round(elapsed, 3),
            "queue_wait_ms": self.queue_wait.snapshot(),
            "device_time_ms": self.device_time.snapshot(),
            "e2e_ms": self.e2e.snapshot(),
        }


# every counter a fresh fleet router reports as zero (docs/SERVING.md
# fleet section: dispatch set, then failover, then swap/drain lifecycle)
_FLEET_COUNTER_KEYS = (
    "requests", "dispatched", "delivered", "retries", "shed", "failed",
    "timeouts", "late_discards", "affinity_routed",
    "host_failures", "host_down", "host_up",
    "drains", "preempt_drains", "rolling_swaps", "swap_hosts", "rollbacks",
    "disagg_requests", "page_transfers", "transfer_bytes",
    "placements", "placement_evictions", "demand_loads", "model_misses",
)


class FleetMetrics:
    """Per-router metric set for the fleet router (serving/fleet.py):
    fleet end-to-end latency (submit → delivered, across retries and
    failover) plus dispatch/failover/swap counters and host-population
    gauges.  Exported like ``ServingMetrics``: a plain ``snapshot()``
    dict, a typed per-router registry, and a collector named ``fleet``
    on the process-global registry so one ``/metrics`` response carries
    the router beside every per-host engine (docs/OBSERVABILITY.md)."""

    def __init__(self, buckets_ms: Sequence[float] = DEFAULT_BUCKETS_MS,
                 registry: MetricsRegistry = None):
        self.registry = registry or MetricsRegistry()
        self.e2e = self.registry.register(
            LatencyHistogram(buckets_ms, name="fleet_e2e_ms"))
        self._counters = {k: self.registry.counter(k)
                          for k in _FLEET_COUNTER_KEYS}
        self._lock = threading.Lock()
        self.hosts_up = self.registry.gauge("hosts_up")
        self.hosts_up.set(0)
        self.hosts_total = self.registry.gauge("hosts_total")
        self.hosts_total.set(0)
        self._t0 = time.monotonic()
        self.global_name = get_registry().register_collector(
            "fleet", self.snapshot, unique=True)

    def inc(self, key: str, n: int = 1, tenant: str = None) -> None:
        c = self._counters.get(key)
        if c is None:        # open key set, matching ServingMetrics
            with self._lock:
                c = self._counters.get(key)
                if c is None:
                    c = self._counters[key] = self.registry.counter(key)
        c.inc(n)
        if tenant:
            c.inc(n, tenant=tenant)

    def snapshot(self) -> dict:
        c: Dict[str, int] = {}
        for k, counter in list(self._counters.items()):
            v = counter.value()
            c[k] = int(v) if float(v).is_integer() else v
        elapsed = time.monotonic() - self._t0
        return {
            "counters": c,
            "hosts_up": int(self.hosts_up.value()),
            "hosts_total": int(self.hosts_total.value()),
            "requests_per_sec": round(c["requests"] / elapsed, 2)
            if elapsed > 0 else None,
            "uptime_sec": round(elapsed, 3),
            "fleet_e2e_ms": self.e2e.snapshot(),
        }


# every counter a fresh decode engine reports as zero (docs/SERVING.md
# decode section: throughput set, then stop conditions, then resilience,
# then the cold-start set, then the decode-speed set — prefix cache and
# speculation counters stay registered-at-zero when the features are off
# so dashboards never see a key appear mid-flight)
_DECODE_COUNTER_KEYS = (
    "requests", "tokens_out", "prefills", "decode_steps",
    "eos_stops", "max_token_stops", "deadline_stops",
    "shed", "deadline_missed", "errors", "retries",
    "poison_isolated", "replica_crashes", "replica_respawns", "swaps",
    "warmup_seconds_total", "bundle_hits", "bundle_misses",
    "scale_ups", "scale_downs",
    "prefix_hits", "prefix_misses", "prefix_inserts",
    "prefix_evictions", "prefix_hit_tokens",
    "spec_steps", "spec_proposed", "spec_accepted", "spec_committed",
    "handoffs_out", "handoffs_in",
    "pages_exported", "pages_attached", "pages_deduped",
    # host-overhead elimination (docs/SERVING.md): fused multi-step
    # decode dispatches, tokens committed by them (tokens_per_dispatch /
    # fused_dispatches = realized amortization), and chunked-prefill
    # prompt/chunk counts
    "fused_dispatches", "tokens_per_dispatch",
    "chunked_prefills", "prefill_chunks",
)


class DecodeMetrics:
    """Per-decode-engine metric set: TTFT and time-per-output-token are
    the first-class histograms (the serving numbers that matter for
    generative inference — PAPERS.md Gemma-on-TPU framing), plus
    per-step device time, throughput/stop/resilience counters, and
    pool-occupancy gauges.  Exported like ``ServingMetrics``: a legacy
    ``snapshot()`` dict, a typed per-engine registry, and a collector on
    the process-global registry (one ``/metrics`` response carries every
    live engine — docs/OBSERVABILITY.md)."""

    def __init__(self, buckets_ms: Sequence[float] = DEFAULT_BUCKETS_MS,
                 registry: MetricsRegistry = None):
        self.registry = registry or MetricsRegistry()
        self.ttft = self.registry.register(
            LatencyHistogram(buckets_ms, name="ttft_ms"))
        self.tpot = self.registry.register(
            LatencyHistogram(buckets_ms, name="tpot_ms"))
        self.step_time = self.registry.register(
            LatencyHistogram(buckets_ms, name="decode_step_ms"))
        self._counters = {k: self.registry.counter(k)
                          for k in _DECODE_COUNTER_KEYS}
        self._lock = threading.Lock()
        self.active_slots = self.registry.gauge("active_slots")
        self.active_slots.set(0)
        self.pages_in_use = self.registry.gauge("pages_in_use")
        self.pages_in_use.set(0)
        self.shared_pages = self.registry.gauge("shared_pages")
        self.shared_pages.set(0)
        self.free_pages = self.registry.gauge("free_pages")
        self.free_pages.set(0)
        self.free_slots = self.registry.gauge("free_slots")
        self.free_slots.set(0)
        self._t0 = time.monotonic()
        self.global_name = get_registry().register_collector(
            "decode", self.snapshot, unique=True)

    def inc(self, key: str, n: int = 1, tenant: str = None) -> None:
        c = self._counters.get(key)
        if c is None:        # open key set, matching ServingMetrics
            with self._lock:
                c = self._counters.get(key)
                if c is None:
                    c = self._counters[key] = self.registry.counter(key)
        c.inc(n)
        if tenant:
            c.inc(n, tenant=tenant)

    def counter_value(self, key: str, tenant: str = None) -> float:
        """Current value of one counter (0.0 if never incremented) — the
        cheap read the autoscaler's shed-delta signal polls.  With
        ``tenant``, reads that tenant's label slice."""
        c = self._counters.get(key)
        if c is None:
            return 0.0
        return float(c.value(tenant=tenant) if tenant else c.value())

    def snapshot(self) -> dict:
        c: Dict[str, int] = {}
        for k, counter in list(self._counters.items()):
            v = counter.value()
            c[k] = int(v) if float(v).is_integer() else v
        elapsed = time.monotonic() - self._t0
        return {
            "counters": c,
            "active_slots": int(self.active_slots.value()),
            "pages_in_use": int(self.pages_in_use.value()),
            "shared_pages": int(self.shared_pages.value()),
            "free_pages": int(self.free_pages.value()),
            "free_slots": int(self.free_slots.value()),
            "accepted_tokens_per_step": round(
                c["spec_committed"] / c["spec_steps"], 4)
            if c.get("spec_steps") else None,
            "tokens_per_sec": round(c["tokens_out"] / elapsed, 2)
            if elapsed > 0 else None,
            "uptime_sec": round(elapsed, 3),
            "ttft_ms": self.ttft.snapshot(),
            "tpot_ms": self.tpot.snapshot(),
            "decode_step_ms": self.step_time.snapshot(),
        }
