"""Command-line interface: train / evaluate / predict / summary.

Parity target: the reference ecosystem's CLI umbrella
(deeplearning4j-cli-parent — train/eval entry points over serialized
configs).  Usage:

    python -m deeplearning4j_tpu train --zoo lenet --data mnist \\
        --epochs 2 --batch-size 128 --output model.zip --dashboard out.html
    python -m deeplearning4j_tpu train --zoo lenet --data mnist \\
        --mesh data=4,model=2 ...   # sharded (ParallelWrapperMain role)
    python -m deeplearning4j_tpu train --config conf.json --data data.npz ...
    python -m deeplearning4j_tpu evaluate --model model.zip --data mnist
    python -m deeplearning4j_tpu predict --model model.zip --input x.npz \\
        --output preds.npz
    python -m deeplearning4j_tpu serve --model model.zip --max-batch 32 \\
        --slo-ms 50 --replicas -1 --admission shed --port 9000
    python -m deeplearning4j_tpu generate --model lm.zip \\
        --prompt "the " --max-tokens 64 --temperature 0.8 --seed 7
    python -m deeplearning4j_tpu launch --nprocs 2 --devices-per-proc 4 \\
        -- train --zoo lenet --data mnist --elastic-dir ckpts
    python -m deeplearning4j_tpu summary --model model.zip
    python -m deeplearning4j_tpu flywheel --generations 3 \\
        --eval-threshold 3.0 --canary 1.0 --chaos nan,regression

``--data`` accepts a built-in name (mnist / cifar10 / iris / emnist /
svhn / uci) or a .npz file with arrays ``x`` and ``y`` (one-hot or class
indices).  Configs are the framework's JSON (MultiLayerConfiguration
to_dict format).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Tuple

import numpy as np


def _num_classes_of(net) -> Optional[int]:
    """Model's output width, so index labels one-hot to the RIGHT width
    even when a split doesn't contain the highest class."""
    layers = getattr(net.conf, "layers", None)
    if layers:
        return getattr(layers[-1], "n_out", None) or None
    specs = getattr(net.conf, "vertices", None)
    if specs:
        by_name = {s.name: s for s in specs}
        out = by_name.get(net.conf.network_outputs[0])
        layer = getattr(getattr(out, "vertex", None), "layer", None)
        return getattr(layer, "n_out", None) or None
    return None


def _load_data(spec: str, train: bool = True,
               num_classes: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    from .datasets import fetchers

    builtin = {
        "mnist": lambda: fetchers.load_mnist(train=train),
        "cifar10": lambda: fetchers.load_cifar10(train=train),
        "iris": lambda: fetchers.load_iris(),
        "emnist": lambda: fetchers.load_emnist(train=train),
        "svhn": lambda: fetchers.load_svhn(train=train),
        "uci": lambda: fetchers.load_uci_synthetic_control(train=train),
    }
    if spec in builtin:
        xs, ys = builtin[spec]()
    else:
        data = np.load(spec)
        if "x" not in data or "y" not in data:
            raise SystemExit(f"{spec}: .npz must contain arrays 'x' and 'y'")
        xs, ys = data["x"], data["y"]
    if ys.ndim == 1:  # class indices → one-hot
        width = num_classes or int(ys.max()) + 1
        if int(ys.max()) >= width:
            raise SystemExit(f"label {int(ys.max())} out of range for "
                             f"{width} classes")
        ys = np.eye(width, dtype=np.float32)[ys.astype(np.int64)]
    return np.asarray(xs, np.float32), np.asarray(ys, np.float32)


def _build_model(args):
    if args.zoo:
        from .models import ZOO

        name = args.zoo.lower()
        if name not in ZOO:
            raise SystemExit(f"unknown zoo model '{args.zoo}' — one of "
                             f"{sorted(ZOO)}")
        kw = json.loads(args.zoo_args) if args.zoo_args else {}
        net = ZOO[name](**kw)
        if not getattr(net, "params", None):
            net.init()
        return net
    if args.config:
        from .nn.multilayer import MultiLayerConfiguration, MultiLayerNetwork

        with open(args.config) as f:
            conf = MultiLayerConfiguration.from_dict(json.load(f))
        net = MultiLayerNetwork(conf)
        net.init()
        return net
    raise SystemExit("pass --zoo NAME or --config conf.json")


def _load_model(path: str):
    from .utils.serializer import load_model

    return load_model(path)


def _parse_mesh(spec: str) -> tuple:
    """'data=4,model=2[,schedule=1f1b][,compress=threshold]' →
    ({"data": 4, "model": 2}, schedule, compress) (-1 = infer; schedule
    defaults to "gpipe", compress to None).  Resolves -1 against the
    visible device count and guarantees a 'data' axis (ShardedTrainer's
    batch sharding names it), so every failure mode here is a clean
    one-line CLI error, not a jax traceback.  The ``schedule`` token
    picks the pipeline microbatch order for nets that pipeline over a
    ``pipe`` axis (parallel/pipeline.py); the ``compress`` token enables
    the DCN-tier compressed gradient exchange for meshes with a ``dcn``
    axis (ops/compression.py)."""
    from .ops.compression import METHODS
    from .parallel.pipeline import SCHEDULES

    axes = {}
    schedule = "gpipe"
    compress = None
    seen_schedule = False
    for part in spec.split(","):
        name, _, size = part.partition("=")
        name = name.strip()
        if name == "schedule":
            if seen_schedule:
                raise SystemExit(
                    f"bad --mesh {spec!r}: duplicate schedule token")
            if size.strip() not in SCHEDULES:
                raise SystemExit(
                    f"bad --mesh {spec!r}: schedule must be one of "
                    f"{'/'.join(SCHEDULES)}, got {size.strip()!r}")
            schedule = size.strip()
            seen_schedule = True
            continue
        if name == "compress":
            if compress is not None:
                raise SystemExit(
                    f"bad --mesh {spec!r}: duplicate compress token")
            if size.strip() not in METHODS:
                raise SystemExit(
                    f"bad --mesh {spec!r}: compress must be one of "
                    f"{'/'.join(METHODS)}, got {size.strip()!r}")
            compress = size.strip()
            continue
        if name in axes:
            raise SystemExit(f"bad --mesh {spec!r}: duplicate axis {name!r}")
        try:
            axes[name] = int(size)
        except ValueError:
            size = ""  # fall through to the shared message
        if not name or not size or axes.get(name) == 0 or (
                axes.get(name, 0) < -1):
            raise SystemExit(
                f"bad --mesh {spec!r}: expected name=size[,name=size...] "
                "with positive integer sizes (or one -1 to infer), "
                "e.g. 'data=8', 'data=4,model=2' or "
                "'data=2,pipe=4,schedule=1f1b'")
    axes.setdefault("data", 1)
    if list(axes.values()).count(-1) > 1:
        raise SystemExit(f"bad --mesh {spec!r}: at most one -1 (infer) axis")
    if -1 in axes.values():
        import jax

        known = 1
        for s in axes.values():
            if s != -1:
                known *= s
        n = jax.device_count()
        if known == 0 or n % known:
            raise SystemExit(f"bad --mesh {spec!r}: cannot infer -1 axis "
                             f"from {n} device(s)")
        axes = {k: (n // known if s == -1 else s) for k, s in axes.items()}
    if compress is not None and "dcn" not in axes:
        raise SystemExit(f"bad --mesh {spec!r}: compress={compress} needs a "
                         "dcn axis, e.g. 'dcn=2,data=4,compress=threshold'")
    return axes, schedule, compress


def _parse_prefetch(spec: str):
    """'DEPTH[,DEVICE]' → (depth, device_spec|None).  depth 0 = the
    synchronous path (bitwise-unchanged pre-prefetch behavior); DEVICE is
    'platform[:index]' or a bare device index.  Every parse failure is a
    one-line CLI error, not a traceback."""
    depth_s, _, dev = spec.partition(",")
    try:
        depth = int(depth_s)
        if depth < 0:
            raise ValueError
    except ValueError:
        raise SystemExit(f"bad --prefetch {spec!r}: expected "
                         "DEPTH[,DEVICE] with DEPTH >= 0, e.g. '2' or "
                         "'2,tpu:0' (0 = synchronous feeding)")
    dev = dev.strip() or None
    if depth == 0 and dev:
        raise SystemExit(f"bad --prefetch {spec!r}: a device makes no "
                         "sense with depth 0 (synchronous feeding)")
    return depth, dev


def _resolve_device(spec: str):
    """'tpu:0' / 'cpu' / '1' → a jax.Device (clean CLI errors)."""
    import jax

    try:
        if spec.isdigit():
            return jax.devices()[int(spec)]
        plat, _, idx = spec.partition(":")
        return jax.devices(plat)[int(idx) if idx else 0]
    except (RuntimeError, IndexError, ValueError) as e:
        raise SystemExit(f"bad --prefetch device {spec!r}: {e}")


def _parse_chaos(spec: str):
    """'kind@step[,kind@step...][,seed=S][,hang=SECONDS][,slow=SECONDS]' →
    (FaultSchedule, seed, hang_seconds, slow_seconds).  Fault kinds are
    the parallel/chaos.py FaultKind names (device_loss, ckpt_write_crash,
    ckpt_truncate, ckpt_bitflip, hung_step, nan_grads, proc_kill,
    proc_hang, preempt_notice, coord_kill, slow_worker); ``slow=`` is the
    per-step drag a scheduled slow_worker adds (default: the hang
    seconds); every parse failure is a one-line CLI error, not a
    traceback."""
    from .parallel.chaos import FaultKind, FaultSchedule

    faults: dict = {}
    seed, hang, slow = 0, 5.0, None
    for part in spec.split(","):
        part = part.strip()
        if "=" in part and "@" not in part:
            key, _, val = part.partition("=")
            try:
                if key == "seed":
                    seed = int(val)
                elif key == "hang":
                    hang = float(val)
                elif key == "slow":
                    slow = float(val)
                else:
                    raise SystemExit(f"bad --chaos {spec!r}: unknown option "
                                     f"{key!r} (seed=, hang=, slow=)")
            except ValueError:
                raise SystemExit(f"bad --chaos {spec!r}: {key}= needs a "
                                 "number")
            continue
        kind, _, step = part.partition("@")
        if kind not in FaultKind.ALL:
            raise SystemExit(f"bad --chaos {spec!r}: unknown fault kind "
                             f"{kind!r} — one of {'/'.join(FaultKind.ALL)}")
        try:
            step_i = int(step)
            if step_i < 1:
                raise ValueError
        except ValueError:
            raise SystemExit(f"bad --chaos {spec!r}: {kind} needs a positive "
                             f"step, e.g. '{kind}@5'")
        faults.setdefault(step_i, []).append(kind)
    if not faults:
        raise SystemExit(f"bad --chaos {spec!r}: no faults — expected "
                         "kind@step[,kind@step...], e.g. "
                         "'device_loss@5,nan_grads@9,seed=1'")
    return FaultSchedule(faults), seed, hang, slow


def _setup_trace(args):
    """Arm span tracing (docs/OBSERVABILITY.md) from ``--trace PATH`` or
    the launcher's ``DL4J_TPU_TRACE_DIR`` env contract (each worker
    incarnation writes its own ``worker{i}.inc{j}.trace.json``, which
    ``launch --trace`` merges into one pod timeline).  Returns the armed
    output path, or None when tracing stays off."""
    import os

    from .parallel.distributed import (
        ENV_INCARNATION, ENV_TRACE_DIR, resolve_process_index,
    )
    path = getattr(args, "trace", None)
    if path:
        path = path.replace("{process}", str(resolve_process_index()))
    else:
        trace_dir = os.environ.get(ENV_TRACE_DIR)
        if trace_dir:
            inc = os.environ.get(ENV_INCARNATION, "0")
            path = os.path.join(
                trace_dir,
                f"worker{resolve_process_index()}.inc{inc}.trace.json")
    if not path:
        return None
    from .obs import trace as obs_trace
    obs_trace.enable_tracing(path=path)
    return path


def _flush_trace(trace_path) -> None:
    if not trace_path:
        return
    from .obs import trace as obs_trace
    written = obs_trace.flush()
    if written:
        print(f"trace: {written} (chrome://tracing / ui.perfetto.dev)")


def cmd_train(args) -> int:
    from .datasets import DataSet, ListDataSetIterator
    from .optimize import ScoreIterationListener
    from .parallel.launcher import Heartbeat, maybe_bootstrap_from_env

    # under `launch`: join the jax.distributed cluster when the launcher
    # exported a coordinator (bounded timeout — a dead coordinator is a
    # CoordinatorUnreachableError, not a hang), and beat the shared
    # membership so the launcher can tell wedged from working
    if maybe_bootstrap_from_env():
        from .parallel import distributed
        print(f"distributed: process {distributed.process_index()}/"
              f"{distributed.process_count()}")
    heartbeat = Heartbeat.start_from_env()
    trace_path = _setup_trace(args)
    from .serving.warmcache import enable_compile_cache
    cache_dir = enable_compile_cache(getattr(args, "compile_cache", None))
    if cache_dir:
        print(f"compile cache: {cache_dir}")

    net = _build_model(args)
    xs, ys = _load_data(args.data, train=True, num_classes=_num_classes_of(net))
    batches = DataSet(xs, ys).shuffle(args.seed).batch_by(args.batch_size)
    mesh_axes, schedule, compress = (_parse_mesh(args.mesh) if args.mesh
                                     else (None, "gpipe", None))
    if mesh_axes:
        # XLA needs static shapes divisible by the data axis — drop the
        # ragged tail batch instead of erroring mid-epoch
        dp = mesh_axes["data"] * mesh_axes.get("dcn", 1)
        if args.batch_size % dp:
            raise SystemExit(f"--batch-size {args.batch_size} not divisible "
                             f"by mesh data axis {dp}")
        full = [b for b in batches if len(b.features) == args.batch_size]
        dropped = len(xs) - len(full) * args.batch_size
        if not full:
            raise SystemExit(
                f"dataset ({len(xs)} samples) has no full batch of "
                f"{args.batch_size}; lower --batch-size for --mesh training")
        if dropped:
            print(f"mesh training drops the ragged tail: {dropped} of "
                  f"{len(xs)} samples not in a full batch of "
                  f"{args.batch_size}")
        batches = full
    it = ListDataSetIterator(batches)
    listeners = [ScoreIterationListener(args.print_every)]
    storage = None
    if args.dashboard:
        from .ui import InMemoryStatsStorage, StatsListener

        storage = InMemoryStatsStorage()
        listeners.append(StatsListener(storage, session_id="cli_train"))
    net.set_listeners(*listeners)
    # the launcher injects per-worker chaos via env (cleared on relaunch,
    # so a scheduled proc_kill fires once per run, not per incarnation)
    import os as _os

    from .parallel.distributed import ENV_CHAOS
    chaos_spec = args.chaos or _os.environ.get(ENV_CHAOS) or None
    if chaos_spec and not args.elastic_dir:
        raise SystemExit("--chaos needs --elastic-dir (faults are injected "
                         "into the ElasticTrainer recovery loop)")
    trainer = None
    if mesh_axes:
        # the reference's ParallelWrapperMain role (parallelism/main/
        # ParallelWrapperMain.java: CLI multi-device training): place the
        # model on a named mesh, train through the sharded step
        import jax

        from .parallel import ShardedTrainer, build_mesh

        total = 1
        for s in mesh_axes.values():
            total *= s
        if total > jax.device_count():
            raise SystemExit(f"--mesh {args.mesh!r} needs {total} device(s), "
                             f"found {jax.device_count()}")
        mesh = build_mesh(mesh_axes, devices=jax.devices()[:total])
        trainer = ShardedTrainer(net, mesh, pipeline_schedule=schedule,
                                 grad_compression=compress,
                                 nan_guard=args.nan_guard)
        print(f"mesh: {dict(mesh.shape)} over {mesh.devices.size} device(s)"
              + (f", pipeline schedule {schedule}" if schedule != "gpipe"
                 else "")
              + (f", grad compression {compress}" if compress else "")
              + (f", nan guard budget {args.nan_guard}" if args.nan_guard
                 else ""))
    elif args.nan_guard is not None:
        if not hasattr(net, "set_nan_guard"):
            raise SystemExit(f"--nan-guard is not supported for "
                             f"{type(net).__name__} yet")
        net.set_nan_guard(args.nan_guard)
        print(f"nan guard armed (budget {args.nan_guard})")
    prefetcher = None
    if args.prefetch:
        depth, dev_spec = _parse_prefetch(args.prefetch)
        if depth > 0:
            # device-resident input pipeline (docs/INPUT_PIPELINE.md):
            # batches cross host→device from a background thread, landing
            # pre-sharded on a mesh run (the trainer's batch placement
            # then passes them through untouched)
            from .datasets.device_prefetch import DevicePrefetchIterator

            if mesh_axes and dev_spec:
                raise SystemExit("--prefetch DEVICE does not combine with "
                                 "--mesh (batches land on the mesh's batch "
                                 "sharding)")
            sharding = trainer.batch_sharding if trainer is not None else None
            device = _resolve_device(dev_spec) if dev_spec else None
            prefetcher = it = DevicePrefetchIterator(
                it, depth=depth, sharding=sharding, device=device)
            where = ("mesh batch sharding" if sharding is not None else
                     str(device) if device is not None else "default device")
            print(f"prefetch: depth {depth} onto {where}")
    if args.elastic_dir:
        # checkpoint-restore recovery (the reference CheckpointListener +
        # Spark task-retry role; docs/FAULT_TOLERANCE.md) — with --chaos,
        # scripted faults are injected INSIDE the recovery loop, a
        # self-test that the stack rides out the scheduled failures
        from .parallel import ChaosInjector, ElasticTrainer

        class _Plain:
            def __init__(self, n):
                self.net = n

            def fit_batch(self, ds):
                return self.net.fit_batch(ds)

        inner = trainer if trainer is not None else _Plain(net)
        injector = None
        if chaos_spec:
            sched, seed, hang, slow = _parse_chaos(chaos_spec)
            injector = inner = ChaosInjector(inner, sched,
                                             hang_seconds=hang, seed=seed,
                                             slow_seconds=slow)
            print(f"chaos armed: {sched.pending()} fault(s) scheduled")
        # announced failures (docs/FAULT_TOLERANCE.md): SIGTERM/SIGUSR1 is
        # a preemption notice — grace-window emergency checkpoint at the
        # next step boundary, then a distinct PREEMPTED exit so the
        # launcher relaunches without burning the restart budget
        from .parallel.preemption import PreemptionHandler
        preemption = PreemptionHandler.install_from_env(grace_s=args.grace)
        trainer = ElasticTrainer(
            inner, args.elastic_dir, checkpoint_every=args.checkpoint_every,
            sync_every=min(10, args.checkpoint_every),
            step_timeout=args.step_timeout, backoff_base=0.5, jitter_seed=0,
            preemption=preemption)
        if injector is not None:
            injector.attach_checkpoints(trainer.ckpt)
        if heartbeat is not None:
            heartbeat.set_step_fn(lambda: trainer.global_step)
            heartbeat.set_ckpt_step_fn(lambda: trainer.last_checkpoint_step)
        # host (re)join: a relaunched worker resumes from the cluster's
        # newest checkpoint instead of step 0
        resumed = trainer.resume()
        if resumed:
            print(f"resumed from checkpoint @ step {resumed}")
    from .parallel.preemption import PreemptedError
    try:
        losses = (trainer.fit(it, epochs=args.epochs) if trainer
                  else net.fit(it, epochs=args.epochs))
    except PreemptedError as exc:
        print(f"preempted: {exc}")
        _flush_trace(trace_path)
        if heartbeat is not None:
            heartbeat.stop()
        return exc.exit_code
    if args.elastic_dir:
        et = trainer
        print(f"elastic: {et.total_restarts} recovery(ies), "
              f"{et.recovery_seconds:.1f}s in recovery, final checkpoint @ "
              f"step {et.global_step} in {args.elastic_dir}")
    print(f"trained {args.epochs} epoch(s), {len(losses)} iterations, "
          f"final loss {losses[-1]:.5f}")
    if prefetcher is not None:
        s = prefetcher.stall_stats()
        print(f"prefetch: stall fraction {s['stall_fraction']:.3f} "
              f"({s['stalls']} stall(s), avg {s['avg_stall_ms']:.1f}ms) over "
              f"{s['batches']} batches, depth {s['depth']}")
    if args.dashboard:
        from .ui import render_dashboard

        render_dashboard(storage, args.dashboard)
        print(f"dashboard: {args.dashboard}")
    if args.output:
        from .parallel.distributed import resolve_process_index
        out_path = args.output.replace("{process}",
                                       str(resolve_process_index()))
        net.save(out_path)
        print(f"saved: {out_path}")
    _flush_trace(trace_path)
    if heartbeat is not None:
        heartbeat.stop()
    return 0


def cmd_evaluate(args) -> int:
    net = _load_model(args.model)
    xs, ys = _load_data(args.data, train=False,
                        num_classes=_num_classes_of(net))
    ev = net.evaluate((xs, ys))
    print(ev.stats() if hasattr(ev, "stats") else
          f"accuracy: {ev.accuracy():.4f}")
    return 0


def cmd_predict(args) -> int:
    net = _load_model(args.model)
    data = np.load(args.input)
    x = data["x"] if "x" in data else data[data.files[0]]
    out = net.output(np.asarray(x, np.float32))
    out = out[0] if isinstance(out, list) else out
    np.savez(args.output, predictions=out)
    print(f"wrote {out.shape} predictions to {args.output}")
    return 0


def _parse_tenants(path):
    """tenants.json → serving.TenantTable (docs/SERVING.md "Multi-tenant
    serving").  The file is a JSON list of tenant rows (or an object
    with a "tenants" list), each row the TenantConfig dict shape:
    {"tenant": "acme", "model": null, "slo_ms": 50, "weight": 2.0,
    "quota_qps": 100, "quota_concurrent": 8, "admission": "shed"}.
    Every parse or validation failure is a one-line CLI error, not a
    traceback."""
    from .serving import TenantTable

    try:
        with open(path) as f:
            rows = json.load(f)
    except OSError as e:
        raise SystemExit(f"bad --tenants {path!r}: {e}")
    except json.JSONDecodeError as e:
        raise SystemExit(f"bad --tenants {path!r}: invalid JSON ({e})")
    if isinstance(rows, dict):
        rows = rows.get("tenants", rows)
    if not isinstance(rows, list) or not rows or not all(
            isinstance(r, dict) for r in rows):
        raise SystemExit(f"bad --tenants {path!r}: expected a non-empty "
                         "JSON list of tenant rows (or "
                         '{"tenants": [...]})')
    try:
        return TenantTable.from_specs(rows)
    except (TypeError, ValueError) as e:
        raise SystemExit(f"bad --tenants {path!r}: {e}")


def _parse_models(spec):
    """'NAME=PATH[,NAME=PATH...]' (or bare checkpoint paths — the name
    is the file stem) → [(name, path)] with clean CLI errors."""
    import os

    out, seen = [], set()
    for part in [p.strip() for p in spec.split(",") if p.strip()]:
        name, sep, path = part.partition("=")
        if not sep:
            name = os.path.splitext(os.path.basename(part))[0]
            path = part
        if not name or not path:
            raise SystemExit(f"bad --models {spec!r}: expected "
                             "NAME=PATH[,NAME=PATH...] or a comma-"
                             "separated list of checkpoint paths")
        if name in seen:
            raise SystemExit(f"bad --models {spec!r}: duplicate model "
                             f"name {name!r}")
        seen.add(name)
        out.append((name, path))
    if not out:
        raise SystemExit(f"bad --models {spec!r}: no models")
    return out


def _serve_queue_depth(engine) -> int:
    """Pending work still inside a serving engine (or fleet router) —
    the drain loop below waits for this to reach zero."""
    batcher = getattr(engine, "batcher", None)
    if batcher is not None:
        return batcher.qsize()
    return int(engine.metrics_snapshot().get("queue_depth", 0))


def cmd_serve(args) -> int:
    """Production serving (docs/SERVING.md): load a checkpoint into the
    versioned registry, AOT-warm every shape bucket, and serve — over
    HTTP (POST /predict + GET /metrics on the UI server), as a --fleet
    router fronting remote serve hosts, or as a --smoke self-test that
    pushes synthetic requests through the engine and prints the metrics
    snapshot.

    A SIGTERM/SIGUSR1 preemption notice (docs/FAULT_TOLERANCE.md env
    contract) triggers a graceful drain: admission stops (new requests
    shed with HTTP 429), in-flight requests finish within the grace
    budget, and the process exits with ``PREEMPTED_EXIT_CODE`` so the
    pod launcher relaunches it without burning restart budget."""
    import os
    import time

    from .parallel.distributed import ENV_SERVE_PORT, PREEMPTED_EXIT_CODE
    from .parallel.launcher import Heartbeat
    from .parallel.preemption import PreemptionHandler
    from .serving import Engine, FleetRouter, HttpHost, ModelRegistry
    from .serving.warmcache import enable_compile_cache

    trace_path = _setup_trace(args)
    cache_dir = enable_compile_cache(getattr(args, "compile_cache", None))
    if cache_dir:
        print(f"compile cache: {cache_dir}")
    if not args.fleet and not args.model and not getattr(
            args, "models", None):
        raise SystemExit("serve needs --model/--models "
                         "(or --fleet HOST:PORT,...)")
    tenants = (_parse_tenants(args.tenants)
               if getattr(args, "tenants", None) else None)
    net = None
    is_lm = False
    if args.fleet:
        if args.smoke:
            raise SystemExit("serve --smoke is incompatible with --fleet")
        if tenants is not None:
            raise SystemExit("--tenants configures a serve HOST's "
                             "admission — pass it to each `serve --model` "
                             "worker, not the --fleet router")
        engine = FleetRouter(
            max_retries=args.max_retries,
            request_timeout_s=args.forward_timeout,
            breaker_threshold=args.breaker_threshold)
        for ep in [e.strip() for e in args.fleet.split(",") if e.strip()]:
            url = ep if ep.startswith("http") else f"http://{ep}"
            engine.add_host(ep, engine=HttpHost(
                url, timeout_s=args.forward_timeout or 5.0))
        print(f"fleet router over {sorted(engine.hosts())}: "
              f"max_retries={args.max_retries}, "
              f"request_timeout={args.forward_timeout}")
    else:
        from .models.transformer import TransformerBlock
        model_pairs = (_parse_models(args.models)
                       if getattr(args, "models", None) else [])
        if args.model:
            model_pairs = [(args.name, args.model)] + model_pairs
        name, default_path = model_pairs[0]
        net = _load_model(default_path)
        is_lm = any(isinstance(l, TransformerBlock) for l in net.conf.layers)
        if is_lm:
            # a transformer LM has no float /predict surface (the predict
            # engine's warmup batches are float feature rows) — serve it
            # decode-only: POST /generate below, /predict answers 503
            if len(model_pairs) > 1:
                raise SystemExit("--models needs predict checkpoints "
                                 "(float feature inputs) — a transformer "
                                 "LM serves decode-only via --model")
            engine = None
        else:
            reg = ModelRegistry()
            version = reg.load(name, default_path, version=args.version)
            reg.set_alias(name, "prod", version)
            engine = Engine.from_registry(
                reg, name, "prod", max_batch=args.max_batch,
                slo_ms=args.slo_ms,
                replicas=args.replicas, max_queue=args.queue_cap,
                admission=args.admission,
                forward_timeout_s=args.forward_timeout,
                max_retries=args.max_retries,
                breaker_threshold=args.breaker_threshold,
                tenants=tenants)
            # an explicit --warm-bundle wins; otherwise the registry's
            # checkpoint provenance finds `<checkpoint>.warm` automatically
            engine.load(warm_bundle=getattr(args, "warm_bundle", None))
            # --models extras: registered + AOT-warmed alongside the
            # default, addressable via the request's "model" field
            for extra_name, extra_path in model_pairs[1:]:
                v = reg.load(extra_name, extra_path)
                reg.set_alias(extra_name, "prod", v)
                engine.add_model_from_registry(reg, extra_name, "prod")
            print(f"serving {name} v{version} (alias 'prod'): "
                  f"max_batch={args.max_batch}, slo={args.slo_ms}ms, "
                  f"replicas={len(engine._replicas)}, "
                  f"admission={args.admission}, "
                  f"warmed buckets {engine.batcher.buckets}")
            if len(model_pairs) > 1:
                print(f"models placed: {engine.placed_models()}")
    if tenants is not None:
        print(f"tenants: {sorted(tenants.tenants())} from {args.tenants}")
    if args.smoke:
        if engine is None:
            raise SystemExit("serve --smoke needs a predict checkpoint "
                             "(float feature inputs), not a transformer "
                             "LM — use POST /generate instead")
        shape = engine._example_shape
        rng = np.random.default_rng(0)
        futs = [engine.output_async(
            rng.normal(size=(1 + i % 4,) + shape).astype(np.float32))
            for i in range(args.smoke)]
        for f in futs:
            f.result(timeout=120)
        print(json.dumps(engine.metrics_snapshot()))
        engine.shutdown()
        _flush_trace(trace_path)
        return 0
    from .ui import UIServer

    # under the pod launcher each serving worker gets a stable port
    # assignment via the env contract; an explicit --port wins
    port = args.port
    if port == 9000 and os.environ.get(ENV_SERVE_PORT):
        port = int(os.environ[ENV_SERVE_PORT])
    server = UIServer(port=port, host=args.host)
    if engine is not None:
        server.attach_engine(engine)
    decode_eng = None
    wants_decode = (is_lm
                    or getattr(args, "prefix_cache", False)
                    or getattr(args, "speculate", None)
                    or getattr(args, "decode_role", "unified")
                    not in (None, "unified")
                    or getattr(args, "kv_dtype", None)
                    not in (None, "float32"))
    if wants_decode:
        # decode-speed flags attach a DecodeEngine for POST /generate
        # next to the predict engine (docs/SERVING.md "Decode-side
        # optimizations")
        if args.fleet:
            raise SystemExit("--prefix-cache/--speculate/--kv-dtype need "
                             "a local --model, not --fleet")
        from .models.transformer import (TransformerBlock,
                                         TransformerDecodeAdapter)
        from .serving import DecodeEngine
        if net is None:
            net = _load_model(args.model)
        if not any(isinstance(l, TransformerBlock)
                   for l in net.conf.layers):
            raise SystemExit("--prefix-cache/--speculate/--kv-dtype need "
                             "a transformer LM checkpoint")
        opts = _decode_opts(args)
        decode_eng = DecodeEngine(TransformerDecodeAdapter(net),
                                  tenants=tenants, **opts).load()
        server.attach_decode_engine(decode_eng)
        print(f"decode engine on POST /generate: "
              f"role={opts['role']}, "
              f"prefix_cache={opts['prefix_cache']}, "
              f"speculate_k={opts['speculate_k'] if opts['draft_model'] is not None else 0}, "
              f"kv_dtype={opts['kv_dtype'] or 'float32'}")
    server.start()
    heartbeat = Heartbeat.start_from_env()
    handler = PreemptionHandler.install_from_env()
    print(f"listening on http://{args.host}:{server.port} — "
          "POST /predict, GET /metrics, GET /healthz, GET /trace",
          flush=True)
    preempted = False
    try:
        while not handler.requested:
            time.sleep(0.2)
        preempted = True
        # graceful drain: shed new admissions, let in-flight requests
        # finish inside the grace window, then hand the port back
        for e in (engine, decode_eng):
            if e is not None:
                e.begin_drain()
        print(f"serve: preemption notice — draining "
              f"({handler.remaining_s:.1f}s grace)", flush=True)
        drain_of = engine if engine is not None else decode_eng
        while _serve_queue_depth(drain_of) > 0 and handler.remaining_s > 0.5:
            time.sleep(0.05)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        if engine is not None:
            engine.shutdown()
        if decode_eng is not None:
            decode_eng.shutdown()
        if heartbeat is not None:
            heartbeat.stop()
        handler.uninstall()
        _flush_trace(trace_path)
    return PREEMPTED_EXIT_CODE if preempted else 0


def _sample_probs(probs: np.ndarray, temperature: float, top_k: int,
                  top_p: float, rng: np.random.Generator) -> int:
    """Host-side sampling from a probability row (the char-RNN path —
    its output layer already applied softmax).  Same knob semantics as
    the decode engine: temperature<=0 greedy, top_k==0 / top_p>=1 off."""
    if temperature <= 0.0:
        return int(np.argmax(probs))
    p = np.asarray(probs, np.float64) ** (1.0 / max(temperature, 1e-6))
    if top_k and top_k < p.shape[0]:
        p[np.argsort(p)[:-top_k]] = 0.0
    if top_p < 1.0:
        order = np.argsort(p)[::-1]
        cum = np.cumsum(p[order]) / max(p.sum(), 1e-30)
        p[order[1:][cum[:-1] >= top_p]] = 0.0   # keep top-1 always
    p /= p.sum()
    return int(rng.choice(p.shape[0], p=p))


def _parse_speculate(spec):
    """'DRAFT_CKPT[,k]' → (path, k) with a clean CLI error — the
    --speculate argument of generate/serve."""
    if spec is None:
        return None, 4
    path, sep, ks = spec.rpartition(",")
    if sep and path:
        try:
            k = int(ks)
            if k < 1:
                raise ValueError
        except ValueError:
            raise SystemExit(f"bad --speculate {spec!r}: expected "
                             "DRAFT_CKPT[,k] with k >= 1")
        return path, k
    return spec, 4


def _decode_opts(args) -> dict:
    """DecodeEngine kwargs for the decode-speed flags shared by
    generate/serve: --prefix-cache, --speculate DRAFT_CKPT[,k],
    --kv-dtype int8 (docs/SERVING.md "Decode-side optimizations")."""
    from .models.transformer import TransformerDecodeAdapter

    draft_path, k = _parse_speculate(getattr(args, "speculate", None))
    draft = None
    if draft_path:
        draft = TransformerDecodeAdapter(_load_model(draft_path))
    kv = getattr(args, "kv_dtype", None)
    return {
        "prefix_cache": bool(getattr(args, "prefix_cache", False)),
        "draft_model": draft,
        "speculate_k": k,
        "kv_dtype": None if kv in (None, "float32") else kv,
        "role": getattr(args, "decode_role", None) or "unified",
    }


def cmd_generate(args) -> int:
    """Autoregressive text generation (docs/SERVING.md "Autoregressive
    decode").  Two model families, one CLI:

      transformer LM  — served through serving.DecodeEngine (paged
                        KV-cache, bucketed prefill, continuous
                        batching), models.TransformerDecodeAdapter
      recurrent nets  — the reference rnnTimeStep() streaming loop
                        (stateful hidden carry, one step per token)

    Text <-> token ids is byte-valued (ord/chr clamped to the model's
    vocab) — the char-LM convention of examples/10_textgen_decode.py.
    """
    net = _load_model(args.model)
    from .models.transformer import TransformerBlock

    is_transformer = any(isinstance(l, TransformerBlock)
                         for l in net.conf.layers)
    if is_transformer:
        from .models.transformer import TransformerDecodeAdapter
        from .serving import DecodeEngine

        adapter = TransformerDecodeAdapter(net)
        vocab = adapter.vocab_size
        pos_rows = int(adapter.params["pos"]["P"].shape[0])
        page = args.page_size
        while page > 1 and page > pos_rows // 2:
            page //= 2
        prompt_ids = [min(ord(c), vocab - 1) for c in args.prompt]
        if not prompt_ids:
            raise SystemExit("--prompt must be non-empty")
        eng = DecodeEngine(adapter, max_slots=1, page_size=page,
                           default_max_new=args.max_tokens,
                           **_decode_opts(args)).load()
        try:
            if len(prompt_ids) > eng.max_prompt:
                raise SystemExit(f"prompt longer than the warmed buckets "
                                 f"(max {eng.max_prompt} tokens)")
            res = eng.generate(prompt_ids, max_new_tokens=args.max_tokens,
                               temperature=args.temperature,
                               top_k=args.top_k, top_p=args.top_p,
                               seed=args.seed)
            text = "".join(chr(t) if t < 0x110000 else "?"
                           for t in res.tokens)
            print(f"[decode engine: {len(res.tokens)} tokens, "
                  f"finish={res.finish_reason}, ttft={res.ttft_ms}ms, "
                  f"tpot={res.tpot_ms}ms]", file=sys.stderr)
            print(args.prompt + text)
        finally:
            eng.shutdown()
        return 0

    # recurrent path: reference rnnTimeStep() streaming
    if (getattr(args, "prefix_cache", False)
            or getattr(args, "speculate", None)
            or getattr(args, "kv_dtype", None) not in (None, "float32")):
        raise SystemExit(
            "--prefix-cache/--speculate/--kv-dtype need a transformer LM "
            "checkpoint (they live in the paged decode engine)")
    out_layer = net.conf.layers[-1]
    vocab = int(getattr(out_layer, "n_out", 256) or 256)
    prompt_ids = [min(ord(c), vocab - 1) for c in args.prompt]
    if not prompt_ids:
        raise SystemExit("--prompt must be non-empty")
    rng = np.random.default_rng(args.seed)
    net.rnn_clear_previous_state()
    probs = net.rnn_time_step(np.asarray([prompt_ids], np.int32))
    dist = probs[0, -1] if probs.ndim == 3 else probs[0]
    toks = []
    for _ in range(args.max_tokens):
        tok = _sample_probs(dist, args.temperature, args.top_k, args.top_p,
                            rng)
        toks.append(tok)
        probs = net.rnn_time_step(np.asarray([tok], np.int32))
        dist = probs[0]
    net.rnn_clear_previous_state()
    print(f"[rnn_time_step: {len(toks)} tokens]", file=sys.stderr)
    print(args.prompt + "".join(chr(t) if t < 0x110000 else "?"
                                for t in toks))
    return 0


def _parse_chaos_worker(specs):
    """['1:proc_kill@10', ...] → {worker: chaos spec}, validating both the
    worker index syntax and the embedded chaos spec (clean CLI errors)."""
    out = {}
    for item in specs or []:
        worker_s, sep, spec = item.partition(":")
        try:
            worker = int(worker_s)
            if worker < 0 or not sep or not spec:
                raise ValueError
        except ValueError:
            raise SystemExit(f"bad --chaos-worker {item!r}: expected "
                             "WORKER:SPEC, e.g. '1:proc_kill@10'")
        if worker in out:
            raise SystemExit(f"bad --chaos-worker {item!r}: duplicate "
                             f"worker {worker}")
        _parse_chaos(spec)   # validate eagerly; workers re-parse from env
        out[worker] = spec
    return out


def cmd_launch(args) -> int:
    """Pod-scale launcher (docs/FAULT_TOLERANCE.md "Process-scale"): fork
    N worker processes running the command after ``--`` (or join an
    existing cluster with --join), monitor heartbeats, and relaunch
    workers that die or hang — host leave/join with membership epochs.
    """
    import os

    rest = list(args.worker_args or [])
    if rest and rest[0] == "--":
        rest = rest[1:]
    if not rest:
        raise SystemExit("launch needs a worker command after '--', e.g. "
                         "launch --nprocs 2 -- train --zoo lenet ...")
    if rest[0] not in ("train", "evaluate", "predict", "serve", "summary"):
        raise SystemExit(f"launch worker command must be a "
                         f"deeplearning4j_tpu subcommand, got {rest[0]!r}")
    # arm the shared compile cache BEFORE any worker exists: enable_
    # compile_cache exports DL4J_TPU_COMPILE_CACHE, which both forked
    # workers and the --join re-exec inherit
    from .serving.warmcache import enable_compile_cache
    cache_dir = enable_compile_cache(getattr(args, "compile_cache", None))
    if cache_dir:
        print(f"launch: compile cache {cache_dir}")
    if args.join:
        # join mode: THIS process becomes worker --process-id of an
        # existing cluster (one `launch --join` per host on a real pod)
        from .parallel.distributed import (
            ENV_CONNECT_TIMEOUT, ENV_COORDINATOR, ENV_NUM_PROCESSES,
            ENV_PROCESS_ID, ENV_RUN_DIR,
        )
        if args.process_id is None:
            raise SystemExit("launch --join needs --process-id")
        if args.coordinator:
            os.environ[ENV_COORDINATOR] = args.coordinator
        os.environ[ENV_PROCESS_ID] = str(args.process_id)
        os.environ[ENV_NUM_PROCESSES] = str(args.nprocs)
        os.environ[ENV_CONNECT_TIMEOUT] = str(args.connect_timeout)
        if args.run_dir:
            os.environ[ENV_RUN_DIR] = args.run_dir
        return main(rest)
    import sys as _sys

    from .parallel.launcher import PodLauncher

    run_dir = args.run_dir
    if not run_dir:
        import tempfile
        run_dir = tempfile.mkdtemp(prefix="dl4j_tpu_launch_")
    trace_dir = None
    if args.trace:
        # pod tracing: every worker incarnation writes its own trace
        # file under run_dir/trace, the launcher records its
        # spawn/leave/join/membership instants on its own track, and the
        # merge below stitches everything into ONE pod timeline at
        # args.trace (docs/OBSERVABILITY.md "Reading a pod timeline")
        from .obs import trace as obs_trace
        trace_dir = os.path.join(run_dir, "trace")
        obs_trace.enable_tracing(
            path=os.path.join(trace_dir, "launcher.trace.json"),
            process_id=-1, process_name="launcher")
    chaos = _parse_chaos_worker(args.chaos_worker)
    launcher = PodLauncher(
        [_sys.executable, "-m", "deeplearning4j_tpu"] + rest,
        num_workers=args.nprocs, run_dir=run_dir,
        devices_per_worker=args.devices_per_proc,
        chaos=chaos or None,
        bootstrap=args.bootstrap,
        heartbeat_timeout=args.heartbeat_timeout,
        max_restarts=args.max_restarts,
        deadline_s=args.deadline,
        connect_timeout_s=args.connect_timeout,
        megascale_slices=args.megascale_slices,
        trace_dir=trace_dir,
        grace_s=args.grace,
        straggler_factor=args.straggler_factor,
        straggler_beats=args.straggler_beats,
        straggler_policy=args.straggler_policy,
        serve=args.serve)
    print(f"launch: {args.nprocs} worker(s) x "
          f"{args.devices_per_proc or 'default'} device(s), "
          f"bootstrap={args.bootstrap}, run dir {run_dir}"
          + (f", chaos {chaos}" if chaos else ""))
    if args.serve:
        print("launch: fleet endpoints "
              + ",".join(launcher.serve_endpoints()))
    report = launcher.run()
    print(f"launch: completed={report['completed']} "
          f"restarts={report['restarts']} "
          f"planned_leaves={report['planned_leaves']} "
          f"stragglers={len(report['stragglers'])} "
          f"epoch={report['epoch']} "
          f"last_ckpt_step={report['last_checkpoint_step']} "
          f"leaked={report['leaked_killed']} "
          f"wall={report['wall_seconds']}s")
    for e in report["events"]:
        print(f"  [{e['t']:8.2f}s] {e['kind']}"
              + (f" worker {e['worker']}" if 'worker' in e else "")
              + (f" ({e['cause']}, rc={e.get('rc')})"
                 if e['kind'] in ('leave', 'unrecovered') else ""))
    if args.trace:
        merged = launcher.merge_trace(args.trace)
        if merged is None:
            print(f"trace: no worker traces found under {trace_dir}")
        else:
            print(f"trace: pod timeline ({merged['metadata']['events']} "
                  f"events) -> {args.trace}")
    if report["unrecovered"]:
        print(f"launch: UNRECOVERED workers {report['unrecovered']} — "
              f"logs under {run_dir}/logs")
        return 1
    return 0


def cmd_summary(args) -> int:
    net = _load_model(args.model)
    from .nn.conf.memory import memory_report

    print(f"model: {type(net).__name__}, {net.num_params():,} params")
    print(memory_report(net, minibatch=args.batch_size))
    return 0


def cmd_check(args) -> int:
    """graftcheck static analysis (docs/STATIC_ANALYSIS.md)."""
    from .analysis import main as analysis_main

    argv = list(args.paths)
    argv += ["--format", args.format]
    if args.baseline != "<default>":
        argv += ["--baseline", args.baseline]
    if args.baseline_update:
        argv += ["--baseline-update", "--justification", args.justification]
    if args.show_suppressed:
        argv += ["--show-suppressed"]
    return analysis_main(argv)


def cmd_flywheel(args) -> int:
    """Headless train→eval→canary→fleet-promote flywheel on a synthetic
    task (docs/LIFECYCLE.md): a PromotionPipeline drives --generations
    lifecycle rounds against an in-process registry + fleet, with
    optional chaos kinds fired on successive generations after the
    bootstrap.  One JSON line per generation; the journal makes a
    killed run resumable (re-run with the same --journal)."""
    import os
    import tempfile
    import threading
    import time

    from .datasets import DataSet
    from .datasets.iterators import ListDataSetIterator
    from .earlystopping import DataSetLossCalculator
    from .nn.conf.inputs import InputType
    from .nn.layers import Dense, OutputLayer
    from .nn.multilayer import MultiLayerNetwork, NeuralNetConfiguration
    from .nn.updaters import Sgd
    from .parallel import (ChaosInjector, ElasticTrainer, FaultKind,
                           FaultSchedule)
    from .serving import (Engine, EvalGate, FleetRouter, ModelRegistry,
                          PromotionPipeline)
    from .utils.serializer import load_model

    chaos_plan = [c.strip() for c in (args.chaos or "").split(",")
                  if c.strip()]
    known = {"device_loss", "nan", "regression", "host_kill", "crash"}
    bad = set(chaos_plan) - known
    if bad:
        print(f"unknown --chaos kind(s): {sorted(bad)} "
              f"(known: {sorted(known)})", file=sys.stderr)
        return 2
    # chaos kinds fire one per generation, starting at gen 2: the
    # bootstrap generation always runs clean (there is nothing to roll
    # back to before the first promote)
    chaos_at = {i + 2: kind for i, kind in enumerate(chaos_plan)}

    rng = np.random.default_rng(args.seed)
    teacher = rng.standard_normal((12, 3)).astype(np.float32)

    def data(n, seed):
        r = np.random.default_rng(seed)
        x = r.standard_normal((n, 12)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[np.argmax(x @ teacher, axis=1)]
        return DataSet(features=x, labels=y)

    def mlp(seed, lr=0.05):
        conf = (NeuralNetConfiguration.builder().seed(seed)
                .updater(Sgd(lr=lr))
                .layer(Dense(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(12)).build())
        net = MultiLayerNetwork(conf)
        net.init()
        return net

    train_ds, eval_ds = data(96, args.seed + 1), data(48, args.seed + 2)
    workdir = args.workdir or tempfile.mkdtemp(prefix="flywheel_")
    os.makedirs(workdir, exist_ok=True)
    journal = args.journal or os.path.join(workdir, "flywheel.jsonl")
    reg = ModelRegistry()
    router = FleetRouter(max_retries=3)
    killable = {"host": None}

    def train_fn(gen):
        kind = chaos_at.get(gen)
        if kind == "nan":
            net = mlp(args.seed + gen)
            import jax
            net.params = jax.tree_util.tree_map(
                lambda a: np.full(np.shape(a), np.nan, np.float32),
                net.params)
            return {"model": net, "run_id": f"flywheel-g{gen}"}
        labels = train_ds.labels
        if kind == "regression":
            net = mlp(args.seed + gen, lr=0.1)
            labels = np.roll(labels, 1, axis=1)   # confidently wrong
        elif gen == 1:
            net = mlp(args.seed, lr=0.08)
        else:
            net = load_model(reg.checkpoint_path("flywheel", args.alias))
        trainee = net
        if kind == "device_loss":
            trainee = ChaosInjector(net, FaultSchedule.scripted(
                {3: FaultKind.DEVICE_LOSS}))
        tr = ElasticTrainer(trainee,
                            checkpoint_dir=os.path.join(workdir,
                                                        f"gen{gen}"),
                            checkpoint_every=2, sync_every=1,
                            run_id=f"flywheel-g{gen}")
        shuffled = train_ds.features, labels
        idx = np.random.default_rng(args.seed + 10 * gen).permutation(
            shuffled[0].shape[0])
        batches = ListDataSetIterator(
            [DataSet(features=shuffled[0][idx][i:i + 24],
                     labels=shuffled[1][idx][i:i + 24])
             for i in range(0, shuffled[0].shape[0], 24)])
        tr.fit(batches, epochs=1 if kind == "regression" else args.epochs)
        return tr

    class _Crash(Exception):
        pass

    crash_armed = {g for g, k in chaos_at.items() if k == "crash"}

    def stage_hook(stage, gen):
        if stage == "CANARY" and gen in crash_armed:
            crash_armed.discard(gen)
            raise _Crash(f"controller crash injected at gen {gen}")

    thresholds = {}
    if args.max_divergence is not None:
        thresholds["max_divergence"] = args.max_divergence

    def make_pipe():
        return PromotionPipeline(
            reg, router, "flywheel", train_fn,
            EvalGate(DataSetLossCalculator(eval_ds),
                     threshold=args.eval_threshold),
            alias=args.alias, journal_path=journal,
            canary_frac=args.canary, canary_window=args.canary_window,
            canary_timeout_s=args.canary_timeout_s,
            canary_thresholds=thresholds, stage_hook=stage_hook)

    pipe = make_pipe()
    resumed = pipe.resume()
    if resumed["completed"] or resumed["partial"] is not None:
        print(f"resumed from journal: completed={resumed['completed']} "
              f"partial={resumed['partial']}", file=sys.stderr)

    stop = threading.Event()
    traffic = None
    dropped = [0]
    try:
        while len(pipe.completed) < args.generations:
            gen_no = max(pipe.completed, default=0) + 1
            if chaos_at.get(gen_no) == "host_kill" \
                    and killable["host"] is not None:
                killable["host"].kill_on_swap = True
            try:
                rec = pipe.run_generation()
            except _Crash as exc:
                print(f"# {exc} — resuming from the journal",
                      file=sys.stderr)
                pipe = make_pipe()
                pipe.resume()
                rec = pipe.run_generation()
            print(json.dumps(rec))
            if args.hosts > 0 and not router.hosts():
                # fleet birth after the bootstrap promote: every host
                # loads straight from the registry's warm bundle
                kw = dict(max_batch=8, slo_ms=30_000.0, replicas=1,
                          admission="block")
                h0 = Engine.from_registry(reg, "flywheel", args.alias,
                                          **kw)
                h0.load()
                router.add_host("h0", engine=h0)
                v, model = reg.resolve("flywheel", args.alias)
                for i in range(1, args.hosts):
                    eng = Engine(model, **kw)
                    eng.swap_model(model, tag=f"flywheel:v{v}")
                    eng.load()
                    host = _KillableEngine(eng)
                    killable["host"] = host
                    router.add_host(f"h{i}", engine=host)

                def loop():   # canary mirror windows need live traffic
                    probes = [rng.standard_normal((r, 12)).astype(
                        np.float32) for r in (1, 2, 4)]
                    i = 0
                    while not stop.is_set():
                        try:
                            router.output(probes[i % 3], slo_ms=30_000.0)
                        except Exception:
                            dropped[0] += 1   # reported in final stats;
                            # expected inside chaos windows (host_kill)
                        i += 1
                        time.sleep(0.002)
                traffic = threading.Thread(target=loop, daemon=True)
                traffic.start()
    finally:
        stop.set()
        if traffic is not None:
            traffic.join(timeout=10)
        router.shutdown(shutdown_hosts=True)
    print(json.dumps({"stats": pipe.stats(),
                      "alias": reg.resolve("flywheel", args.alias)[0],
                      "traffic_dropped": dropped[0],
                      "journal": journal}))
    return 0


class _KillableEngine:
    """cmd_flywheel's --chaos host_kill seam: dies the moment a rolling
    swap touches it (scripts/train_promote_soak.py carries the full
    version)."""

    def __init__(self, inner):
        self.inner = inner
        self.kill_on_swap = False
        self.killed = False

    def output_async(self, x, slo_ms=None):
        from .serving import ServingUnavailableError
        if self.killed:
            raise ServingUnavailableError("host killed (chaos)")
        return self.inner.output_async(x, slo_ms=slo_ms)

    def swap_model(self, model, tag=None, warm_bundle=None):
        if self.kill_on_swap or self.killed:
            self.killed = True
            raise RuntimeError("host killed mid-roll (chaos)")
        return self.inner.swap_model(model, tag, warm_bundle=warm_bundle)

    @property
    def current_tag(self):
        return self.inner.current_tag

    def metrics_snapshot(self):
        return self.inner.metrics_snapshot()

    def health_snapshot(self):
        if self.killed:
            return {"status": "unready", "ready": False}
        return self.inner.health_snapshot()

    def shutdown(self, timeout: float = 5.0):
        self.inner.shutdown(timeout=timeout)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="deeplearning4j_tpu",
                                description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="command", required=True)

    t = sub.add_parser("train", help="train a model")
    t.add_argument("--zoo", help="zoo model name (e.g. lenet)")
    t.add_argument("--zoo-args", help="JSON kwargs for the zoo constructor")
    t.add_argument("--config", help="MultiLayerConfiguration JSON file")
    t.add_argument("--data", required=True,
                   help="builtin name (mnist/cifar10/iris/emnist/svhn/uci) or .npz")
    t.add_argument("--epochs", type=int, default=1)
    t.add_argument("--batch-size", type=int, default=128)
    t.add_argument("--seed", type=int, default=12345)
    t.add_argument("--print-every", type=int, default=10)
    t.add_argument("--output", help="checkpoint zip to write")
    t.add_argument("--dashboard", help="HTML training report to write")
    t.add_argument("--mesh", help="train sharded over a named device mesh, "
                   "e.g. 'data=8' or 'data=4,model=2' (the reference's "
                   "ParallelWrapperMain role); an optional "
                   "'schedule=gpipe|1f1b' token picks the pipeline "
                   "microbatch order for pipe-axis nets, and "
                   "'compress=threshold|bitmap' enables the DCN-tier "
                   "compressed gradient exchange on dcn-axis meshes, "
                   "e.g. 'dcn=2,data=4,compress=threshold'")
    t.add_argument("--prefetch", metavar="DEPTH[,DEVICE]",
                   help="device-resident input pipeline "
                   "(docs/INPUT_PIPELINE.md): keep DEPTH batches already "
                   "transferred to device ahead of the step (async H2D from "
                   "a background thread; pre-sharded on --mesh runs); "
                   "'0' = synchronous feeding (bitwise-unchanged legacy "
                   "path); optional DEVICE pins placement, e.g. '2,tpu:0'")
    t.add_argument("--nan-guard", type=int, default=None, metavar="BUDGET",
                   help="arm the divergence guard: steps with non-finite "
                   "gradients apply no update; BUDGET consecutive bad steps "
                   "escalate (recoverable under --elastic-dir)")
    t.add_argument("--elastic-dir", metavar="DIR",
                   help="train under ElasticTrainer: rolling checkpoints in "
                   "DIR + automatic restore-and-continue on recoverable "
                   "failures (docs/FAULT_TOLERANCE.md)")
    t.add_argument("--checkpoint-every", type=int, default=100,
                   help="checkpoint interval in steps for --elastic-dir")
    t.add_argument("--step-timeout", type=float, default=None,
                   metavar="SECONDS",
                   help="step watchdog for --elastic-dir: a step exceeding "
                   "this wall clock is treated as hung and recovered")
    t.add_argument("--chaos", metavar="SPEC",
                   help="inject scripted faults (chaos drill; needs "
                   "--elastic-dir): 'kind@step[,kind@step...]"
                   "[,seed=S][,hang=SECONDS][,slow=SECONDS]', kinds: "
                   "device_loss/ckpt_write_crash/ckpt_truncate/"
                   "ckpt_bitflip/hung_step/nan_grads/proc_kill/proc_hang/"
                   "preempt_notice/coord_kill/slow_worker (the process "
                   "kinds take down THIS worker — only meaningful under "
                   "`launch`, which restarts it; preempt_notice is the "
                   "ANNOUNCED path: SIGTERM self, emergency checkpoint, "
                   "PREEMPTED exit)")
    t.add_argument("--trace", metavar="PATH",
                   help="record step/span tracing and write a Chrome-"
                   "trace JSON to PATH on exit (view in chrome://tracing "
                   "or ui.perfetto.dev; '{process}' expands to the worker "
                   "index; docs/OBSERVABILITY.md)")
    t.add_argument("--compile-cache", metavar="DIR",
                   help="persistent XLA compile cache (serving/warmcache.py): "
                   "compiled executables are stored in DIR and later "
                   "processes skip the compile (default: the "
                   "DL4J_TPU_COMPILE_CACHE env var; unset = off)")
    t.add_argument("--grace", type=float, default=None, metavar="SECONDS",
                   help="preemption grace budget for --elastic-dir runs: "
                   "on SIGTERM/SIGUSR1 (a preemption notice) the next "
                   "step boundary writes a deadline-bounded emergency "
                   "checkpoint (uncompressed fallback when deflate won't "
                   "fit the remaining budget) and exits with the "
                   "PREEMPTED code 75 (default: DL4J_TPU_GRACE_S env, "
                   "else 30)")
    t.set_defaults(fn=cmd_train)

    ln = sub.add_parser(
        "launch", help="multi-process pod launcher: fork N workers (or "
        "join a cluster) with heartbeat membership + host join/leave "
        "recovery (docs/FAULT_TOLERANCE.md)")
    ln.add_argument("--nprocs", type=int, default=2,
                    help="number of worker processes (cluster size)")
    ln.add_argument("--devices-per-proc", type=int, default=None,
                    metavar="K", help="per-process device visibility: each "
                    "worker sees K devices (CPU: K virtual devices via "
                    "XLA_FLAGS)")
    ln.add_argument("--bootstrap", choices=("replica", "distributed"),
                    default="replica",
                    help="'distributed' = workers form a jax.distributed "
                    "cluster (global mesh; needs backend support — see "
                    "probe_multiprocess_support); 'replica' = independent "
                    "replicas per worker, no cross-process collectives "
                    "(default; the single-box CPU mode)")
    ln.add_argument("--run-dir", help="shared run directory for heartbeats/"
                    "membership/logs (default: a fresh temp dir)")
    ln.add_argument("--heartbeat-timeout", type=float, default=5.0,
                    metavar="S", help="a worker whose heartbeat is older "
                    "than this is declared hung, killed, and relaunched")
    ln.add_argument("--max-restarts", type=int, default=2,
                    help="per-worker relaunch budget (host rejoin)")
    ln.add_argument("--deadline", type=float, default=600.0, metavar="S",
                    help="overall run deadline; survivors are reaped "
                    "(no orphan worker outlives the launcher)")
    ln.add_argument("--connect-timeout", type=float, default=60.0,
                    metavar="S", help="coordinator bootstrap budget; a dead "
                    "coordinator raises CoordinatorUnreachableError instead "
                    "of hanging")
    ln.add_argument("--megascale-slices", type=int, default=None,
                    metavar="N", help="export MEGASCALE_NUM_SLICES=N to "
                    "workers (feeds detect_num_slices → "
                    "ShardedTrainer.two_tier / build_two_tier_mesh); "
                    "distributed bootstrap defaults it to --nprocs")
    ln.add_argument("--grace", type=float, default=30.0, metavar="S",
                    help="preemption grace budget exported to workers "
                    "(DL4J_TPU_GRACE_S) AND the launcher's escalation "
                    "deadline: a notified worker still alive ~1.5x past "
                    "it is SIGKILLed; workers exiting with the PREEMPTED "
                    "code are relaunched WITHOUT consuming the restart "
                    "budget")
    ln.add_argument("--straggler-factor", type=float, default=2.0,
                    metavar="K", help="flag a worker whose per-step wall "
                    "time exceeds K x the median of its peers' (from "
                    "heartbeats; default 2.0)")
    ln.add_argument("--straggler-beats", type=int, default=3, metavar="M",
                    help="consecutive over-threshold heartbeats before a "
                    "worker is flagged a straggler (default 3)")
    ln.add_argument("--straggler-policy",
                    choices=("off", "flag", "relaunch"), default="flag",
                    help="what to do with a flagged straggler: 'flag' = "
                    "counter + trace instant + run-report event (default), "
                    "'relaunch' = kill and relaunch it (consumes restart "
                    "budget), 'off' = no detection")
    ln.add_argument("--chaos-worker", action="append", metavar="I:SPEC",
                    help="arm worker I with a --chaos spec (repeatable), "
                    "e.g. '1:proc_kill@10' — injected only into the FIRST "
                    "incarnation, so the relaunched worker survives")
    ln.add_argument("--trace", metavar="PATH",
                    help="arm span tracing in every worker (per-"
                    "incarnation files under RUN_DIR/trace) and merge "
                    "them — plus the launcher's own membership/leave/join "
                    "events — into ONE pod-timeline Chrome trace at PATH")
    ln.add_argument("--serve", action="store_true",
                    help="serving-fleet mode: assign each worker a stable "
                    "serve port (exported as DL4J_TPU_SERVE_PORT, stable "
                    "across relaunch) and print the fleet endpoints — pair "
                    "with a 'serve' worker command and a `serve --fleet` "
                    "router (docs/SERVING.md 'Fleet serving')")
    ln.add_argument("--compile-cache", metavar="DIR",
                    help="export DL4J_TPU_COMPILE_CACHE=DIR to every worker: "
                    "they share one persistent XLA compile cache, so a "
                    "relaunched worker (or the whole next pod run) reuses "
                    "executables instead of recompiling")
    ln.add_argument("--join", action="store_true",
                    help="join an existing cluster as one worker instead "
                    "of forking (one `launch --join` per host on a pod)")
    ln.add_argument("--process-id", type=int, default=None,
                    help="this host's index (with --join)")
    ln.add_argument("--coordinator", metavar="HOST:PORT",
                    help="coordinator address (with --join)")
    ln.add_argument("worker_args", nargs=argparse.REMAINDER,
                    help="-- followed by the worker subcommand, e.g. "
                    "-- train --zoo lenet --data mnist --elastic-dir ckpts")
    ln.set_defaults(fn=cmd_launch)

    e = sub.add_parser("evaluate", help="evaluate a saved model")
    e.add_argument("--model", required=True)
    e.add_argument("--data", required=True)
    e.set_defaults(fn=cmd_evaluate)

    r = sub.add_parser("predict", help="run inference")
    r.add_argument("--model", required=True)
    r.add_argument("--input", required=True, help=".npz with array 'x'")
    r.add_argument("--output", required=True, help=".npz to write")
    r.set_defaults(fn=cmd_predict)

    v = sub.add_parser("serve", help="serve a saved model (docs/SERVING.md)")
    v.add_argument("--model", default=None,
                   help="checkpoint zip to serve (required unless --fleet "
                   "or --models)")
    v.add_argument("--models", metavar="NAME=PATH,...",
                   help="boot a multi-model host: comma-separated "
                   "checkpoints (NAME=PATH, or bare paths — the name is "
                   "the file stem), all registered and AOT-warmed on one "
                   "engine; the first (or --model) is the default, the "
                   "rest are addressed by the request's 'model' field "
                   "(docs/SERVING.md 'Multi-tenant serving')")
    v.add_argument("--tenants", metavar="JSON",
                   help="per-tenant admission classes: a JSON list of "
                   "rows {tenant, model?, slo_ms?, weight?, quota_qps?, "
                   "quota_concurrent?, admission?} enforced by the "
                   "batcher's weighted-fair lanes — over-quota requests "
                   "shed typed, and the HTTP 429 carries the tenant "
                   "(docs/SERVING.md 'Multi-tenant serving')")
    v.add_argument("--fleet", metavar="HOST:PORT,...",
                   help="run a fleet router instead of a local engine: "
                   "front the comma-separated serve hosts with "
                   "least-loaded dispatch, session affinity, dead-host "
                   "failover, and rolling promote (docs/SERVING.md "
                   "'Fleet serving')")
    v.add_argument("--name", default="model",
                   help="registry name for the model (default: 'model')")
    v.add_argument("--version", type=int, default=None,
                   help="registry version number (default: auto-assign)")
    v.add_argument("--max-batch", type=int, default=32,
                   help="dynamic batcher fused-batch cap")
    v.add_argument("--slo-ms", type=float, default=50.0,
                   help="per-request deadline budget; queued requests past "
                   "it fail fast with DeadlineExceededError")
    v.add_argument("--replicas", type=int, default=-1,
                   help="engine replicas (-1 = one per local device)")
    v.add_argument("--admission", choices=("block", "shed"), default="shed",
                   help="overload policy: block callers or shed with "
                   "OverloadedError (HTTP 429)")
    v.add_argument("--queue-cap", type=int, default=256,
                   help="admission queue bound in requests")
    v.add_argument("--port", type=int, default=9000)
    v.add_argument("--host", default="127.0.0.1")
    v.add_argument("--forward-timeout", type=float, default=None,
                   metavar="SECONDS",
                   help="declare a replica HUNG (abandon + retry its batch "
                   "elsewhere + respawn it) when one forward exceeds this "
                   "(default: disabled)")
    v.add_argument("--max-retries", type=int, default=1,
                   help="per-request retry budget after a replica failure "
                   "(deadline-aware, different replica; default 1)")
    v.add_argument("--breaker-threshold", type=int, default=3,
                   help="consecutive replica failures that trip its circuit "
                   "breaker (dispatch routes around it; default 3)")
    v.add_argument("--compile-cache", metavar="DIR",
                   help="persistent XLA compile cache (serving/warmcache.py): "
                   "a restarted server reuses DIR's executables instead of "
                   "cold-compiling (default: DL4J_TPU_COMPILE_CACHE env)")
    v.add_argument("--warm-bundle", metavar="PATH",
                   help="warmup bundle of serialized AOT executables to "
                   "deserialize at load (default: <checkpoint>.warm next to "
                   "--model when present; docs/SERVING.md 'Cold start & "
                   "autoscaling')")
    v.add_argument("--smoke", type=int, default=0, metavar="N",
                   help="push N synthetic requests through the engine, "
                   "print the metrics snapshot, and exit (self-test)")
    v.add_argument("--trace", metavar="PATH",
                   help="record request/batch span tracing; the ring "
                   "buffer is served live on GET /trace and written to "
                   "PATH on shutdown (docs/OBSERVABILITY.md)")
    v.add_argument("--prefix-cache", action="store_true",
                   help="radix prefix cache over the paged KV pool: "
                   "shared-prompt requests attach matching pages "
                   "read-only and prefill only their suffix "
                   "(docs/SERVING.md 'Decode-side optimizations')")
    v.add_argument("--speculate", metavar="DRAFT_CKPT[,k]",
                   help="speculative decoding: DRAFT_CKPT proposes k "
                   "tokens per step (default k=4), the target "
                   "verifies in one dispatch — temp-0 output is "
                   "bit-identical to plain decode")
    v.add_argument("--kv-dtype", choices=("float32", "int8"),
                   default="float32",
                   help="KV page storage dtype: int8 stores "
                   "per-row-quantized pages + f32 scales (~4x "
                   "sessions at fixed HBM; changes bits — gated by "
                   "a top1-agree envelope, not the identity gates)")
    v.add_argument("--decode-role", choices=("unified", "prefill", "decode"),
                   default="unified",
                   help="disaggregated serving role for the decode "
                   "engine: 'prefill' hosts run prompt prefill and "
                   "export KV pages as handoffs, 'decode' hosts attach "
                   "handoffs and stream tokens; a FleetRouter routes "
                   "the two stages (docs/SERVING.md 'Disaggregated "
                   "and sharded decode')")
    v.set_defaults(fn=cmd_serve)

    g = sub.add_parser(
        "generate", help="autoregressive text generation (docs/SERVING.md "
        "\"Autoregressive decode\"): transformer LMs run through the "
        "paged-KV-cache decode engine, recurrent nets through "
        "rnnTimeStep streaming")
    g.add_argument("--model", required=True, help="checkpoint zip")
    g.add_argument("--prompt", required=True,
                   help="prompt text (byte-valued char vocab)")
    g.add_argument("--max-tokens", type=int, default=64,
                   help="tokens to generate (default 64)")
    g.add_argument("--temperature", type=float, default=0.0,
                   help="sampling temperature; 0 = greedy (default)")
    g.add_argument("--top-k", type=int, default=0,
                   help="keep only the k highest-probability tokens "
                   "(0 = off)")
    g.add_argument("--top-p", type=float, default=1.0,
                   help="nucleus sampling mass (1.0 = off)")
    g.add_argument("--seed", type=int, default=0,
                   help="sampling seed — same seed, same text")
    g.add_argument("--page-size", type=int, default=16,
                   help="KV-cache page size in tokens (transformer path; "
                   "auto-shrunk for short position tables)")
    g.add_argument("--prefix-cache", action="store_true",
                   help="radix prefix cache over the paged KV pool "
                   "(transformer path; docs/SERVING.md)")
    g.add_argument("--speculate", metavar="DRAFT_CKPT[,k]",
                   help="speculative decoding: DRAFT_CKPT proposes k "
                   "tokens per step (default k=4); temp-0 output is "
                   "bit-identical to plain decode")
    g.add_argument("--kv-dtype", choices=("float32", "int8"),
                   default="float32",
                   help="KV page storage dtype; int8 quantizes pages "
                   "per row (~4x sessions at fixed HBM)")
    g.set_defaults(fn=cmd_generate)

    s = sub.add_parser("summary", help="model + memory summary")
    s.add_argument("--model", required=True)
    s.add_argument("--batch-size", type=int, default=32)
    s.set_defaults(fn=cmd_summary)

    c = sub.add_parser(
        "check", help="graftcheck: repo-native static analysis — jit "
        "purity, determinism, thread safety, telemetry contracts "
        "(docs/STATIC_ANALYSIS.md)")
    c.add_argument("paths", nargs="*",
                   help="specific .py files (default: whole package)")
    c.add_argument("--format", choices=("text", "json"), default="text")
    c.add_argument("--baseline", default="<default>",
                   help="baseline json ('none' disables)")
    c.add_argument("--baseline-update", action="store_true",
                   help="accept current findings into the baseline "
                   "(REQUIRES --justification)")
    c.add_argument("--justification", default="",
                   help="why the baselined findings are accepted")
    c.add_argument("--show-suppressed", action="store_true")
    c.set_defaults(fn=cmd_check)

    fw = sub.add_parser(
        "flywheel", help="continuous train→eval→canary→fleet-promote "
        "lifecycle on a synthetic task (docs/LIFECYCLE.md): repeated "
        "PromotionPipeline generations with lineage-aware rollback, a "
        "crash-resumable journal, and optional per-generation chaos")
    fw.add_argument("--generations", type=int, default=3, metavar="K",
                    help="lifecycle generations to complete (default 3)")
    fw.add_argument("--eval-threshold", type=float, default=3.0,
                    help="eval-gate loss ceiling; non-finite scores "
                    "always fail (default 3.0)")
    fw.add_argument("--canary", type=float, default=1.0, metavar="FRAC",
                    help="fraction of live batches mirrored to the "
                    "canary (default 1.0)")
    fw.add_argument("--canary-window", type=int, default=4,
                    help="mirrored batches per canary decision "
                    "(default 4)")
    fw.add_argument("--canary-timeout-s", type=float, default=60.0,
                    help="canary window deadline; an unfilled window "
                    "is a rejection (default 60)")
    fw.add_argument("--max-divergence", type=float, default=None,
                    help="canary prediction-divergence ceiling "
                    "(mean abs diff vs the incumbent; default off)")
    fw.add_argument("--hosts", type=int, default=2,
                    help="fleet hosts; host 0 is the subscribed canary "
                    "engine, the rest roll via rolling_swap; 0 = no "
                    "fleet, alias-only promotion (default 2)")
    fw.add_argument("--chaos", default="", metavar="KIND[,KIND...]",
                    help="chaos kinds fired one per generation starting "
                    "at gen 2: device_loss (mid-train, recovered), nan "
                    "(eval gate catches), regression (canary rejects), "
                    "host_kill (mid-roll, lineage rollback), crash "
                    "(controller dies at CANARY, journal resume)")
    fw.add_argument("--workdir",
                    help="checkpoint/journal directory (default: fresh "
                    "temp dir)")
    fw.add_argument("--journal",
                    help="journal path override — reuse one to resume "
                    "a killed run (default: <workdir>/flywheel.jsonl)")
    fw.add_argument("--alias", default="prod")
    fw.add_argument("--epochs", type=int, default=3,
                    help="training epochs per generation (default 3)")
    fw.add_argument("--seed", type=int, default=12345)
    fw.set_defaults(fn=cmd_flywheel)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
