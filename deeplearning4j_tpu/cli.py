"""Command-line interface: train / evaluate / predict / summary.

Parity target: the reference ecosystem's CLI umbrella
(deeplearning4j-cli-parent — train/eval entry points over serialized
configs).  Usage:

    python -m deeplearning4j_tpu train --zoo lenet --data mnist \\
        --epochs 2 --batch-size 128 --output model.zip --dashboard out.html
    python -m deeplearning4j_tpu train --config conf.json --data data.npz ...
    python -m deeplearning4j_tpu evaluate --model model.zip --data mnist
    python -m deeplearning4j_tpu predict --model model.zip --input x.npz \\
        --output preds.npz
    python -m deeplearning4j_tpu summary --model model.zip

``--data`` accepts a built-in name (mnist / cifar10 / iris / emnist /
svhn / uci) or a .npz file with arrays ``x`` and ``y`` (one-hot or class
indices).  Configs are the framework's JSON (MultiLayerConfiguration
to_dict format).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Tuple

import numpy as np


def _num_classes_of(net) -> Optional[int]:
    """Model's output width, so index labels one-hot to the RIGHT width
    even when a split doesn't contain the highest class."""
    layers = getattr(net.conf, "layers", None)
    if layers:
        return getattr(layers[-1], "n_out", None) or None
    specs = getattr(net.conf, "vertices", None)
    if specs:
        by_name = {s.name: s for s in specs}
        out = by_name.get(net.conf.network_outputs[0])
        layer = getattr(getattr(out, "vertex", None), "layer", None)
        return getattr(layer, "n_out", None) or None
    return None


def _load_data(spec: str, train: bool = True,
               num_classes: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    from .datasets import fetchers

    builtin = {
        "mnist": lambda: fetchers.load_mnist(train=train),
        "cifar10": lambda: fetchers.load_cifar10(train=train),
        "iris": lambda: fetchers.load_iris(),
        "emnist": lambda: fetchers.load_emnist(train=train),
        "svhn": lambda: fetchers.load_svhn(train=train),
        "uci": lambda: fetchers.load_uci_synthetic_control(train=train),
    }
    if spec in builtin:
        xs, ys = builtin[spec]()
    else:
        data = np.load(spec)
        if "x" not in data or "y" not in data:
            raise SystemExit(f"{spec}: .npz must contain arrays 'x' and 'y'")
        xs, ys = data["x"], data["y"]
    if ys.ndim == 1:  # class indices → one-hot
        width = num_classes or int(ys.max()) + 1
        if int(ys.max()) >= width:
            raise SystemExit(f"label {int(ys.max())} out of range for "
                             f"{width} classes")
        ys = np.eye(width, dtype=np.float32)[ys.astype(np.int64)]
    return np.asarray(xs, np.float32), np.asarray(ys, np.float32)


def _build_model(args):
    if args.zoo:
        from .models import ZOO

        name = args.zoo.lower()
        if name not in ZOO:
            raise SystemExit(f"unknown zoo model '{args.zoo}' — one of "
                             f"{sorted(ZOO)}")
        kw = json.loads(args.zoo_args) if args.zoo_args else {}
        net = ZOO[name](**kw)
        if not getattr(net, "params", None):
            net.init()
        return net
    if args.config:
        from .nn.multilayer import MultiLayerConfiguration, MultiLayerNetwork

        with open(args.config) as f:
            conf = MultiLayerConfiguration.from_dict(json.load(f))
        net = MultiLayerNetwork(conf)
        net.init()
        return net
    raise SystemExit("pass --zoo NAME or --config conf.json")


def _load_model(path: str):
    from .utils.serializer import load_model

    return load_model(path)


def cmd_train(args) -> int:
    from .datasets import DataSet, ListDataSetIterator
    from .optimize import ScoreIterationListener

    net = _build_model(args)
    xs, ys = _load_data(args.data, train=True, num_classes=_num_classes_of(net))
    it = ListDataSetIterator(DataSet(xs, ys).shuffle(args.seed)
                             .batch_by(args.batch_size))
    listeners = [ScoreIterationListener(args.print_every)]
    storage = None
    if args.dashboard:
        from .ui import InMemoryStatsStorage, StatsListener

        storage = InMemoryStatsStorage()
        listeners.append(StatsListener(storage, session_id="cli_train"))
    net.set_listeners(*listeners)
    losses = net.fit(it, epochs=args.epochs)
    print(f"trained {args.epochs} epoch(s), {len(losses)} iterations, "
          f"final loss {losses[-1]:.5f}")
    if args.dashboard:
        from .ui import render_dashboard

        render_dashboard(storage, args.dashboard)
        print(f"dashboard: {args.dashboard}")
    if args.output:
        net.save(args.output)
        print(f"saved: {args.output}")
    return 0


def cmd_evaluate(args) -> int:
    net = _load_model(args.model)
    xs, ys = _load_data(args.data, train=False,
                        num_classes=_num_classes_of(net))
    ev = net.evaluate((xs, ys))
    print(ev.stats() if hasattr(ev, "stats") else
          f"accuracy: {ev.accuracy():.4f}")
    return 0


def cmd_predict(args) -> int:
    net = _load_model(args.model)
    data = np.load(args.input)
    x = data["x"] if "x" in data else data[data.files[0]]
    out = net.output(np.asarray(x, np.float32))
    out = out[0] if isinstance(out, list) else out
    np.savez(args.output, predictions=out)
    print(f"wrote {out.shape} predictions to {args.output}")
    return 0


def cmd_summary(args) -> int:
    net = _load_model(args.model)
    from .nn.conf.memory import memory_report

    print(f"model: {type(net).__name__}, {net.num_params():,} params")
    print(memory_report(net, minibatch=args.batch_size))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="deeplearning4j_tpu",
                                description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="command", required=True)

    t = sub.add_parser("train", help="train a model")
    t.add_argument("--zoo", help="zoo model name (e.g. lenet)")
    t.add_argument("--zoo-args", help="JSON kwargs for the zoo constructor")
    t.add_argument("--config", help="MultiLayerConfiguration JSON file")
    t.add_argument("--data", required=True,
                   help="builtin name (mnist/cifar10/iris/emnist/svhn/uci) or .npz")
    t.add_argument("--epochs", type=int, default=1)
    t.add_argument("--batch-size", type=int, default=128)
    t.add_argument("--seed", type=int, default=12345)
    t.add_argument("--print-every", type=int, default=10)
    t.add_argument("--output", help="checkpoint zip to write")
    t.add_argument("--dashboard", help="HTML training report to write")
    t.set_defaults(fn=cmd_train)

    e = sub.add_parser("evaluate", help="evaluate a saved model")
    e.add_argument("--model", required=True)
    e.add_argument("--data", required=True)
    e.set_defaults(fn=cmd_evaluate)

    r = sub.add_parser("predict", help="run inference")
    r.add_argument("--model", required=True)
    r.add_argument("--input", required=True, help=".npz with array 'x'")
    r.add_argument("--output", required=True, help=".npz to write")
    r.set_defaults(fn=cmd_predict)

    s = sub.add_parser("summary", help="model + memory summary")
    s.add_argument("--model", required=True)
    s.add_argument("--batch-size", type=int, default=32)
    s.set_defaults(fn=cmd_summary)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
