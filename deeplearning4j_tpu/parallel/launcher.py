"""Pod-scale elastic runtime: multi-process launcher + host join/leave.

The missing layer between "virtual devices in one process" and a real
pod: every piece of >8-device evidence in this repo used to live inside
one OS process, and ElasticTrainer only survived *in-process* restarts.
This module makes processes the failure unit (PAPERS.md: the
TPU-supercomputer retrospective frames preemption-tolerant pod training
as THE production problem):

- :class:`PodLauncher` — forks N worker processes (the CLI ``launch``
  subcommand's engine), sets per-process device visibility and the
  ``DL4J_TPU_*`` env contract, monitors liveness, and RELAUNCHES workers
  that die or hang — host leave → join, with a bounded restart budget
  and a leak check that no orphan worker survives a run.
- :class:`Membership` — a shared heartbeat ledger with a coordinator-side
  membership **epoch**: workers beat, the coordinator's ``refresh()``
  bumps the epoch whenever the alive-set changes.  File-based (every
  worker of a single-box launch — and every host of a pod with a shared
  filesystem — can reach it), with an injectable clock so join/leave
  transitions are testable against a fake clock.
- :class:`Heartbeat` — the worker-side daemon thread that beats.
- :class:`ProcessFailureDetector` — a FailureDetector whose ``check()``
  raises :class:`HostLostError` / :class:`MembershipChangedError` when
  the membership moved; wired into ``ElasticTrainer(membership_check=)``
  it turns a peer host's death into the SAME backoff → rebuild → restore
  recovery path as a device loss, with ``mesh.surviving_mesh`` rebuilding
  a (possibly smaller ``dcn``) mesh over the survivors.

Bootstrap modes: ``distributed`` (workers call
``distributed.initialize`` against a coordinator with a bounded connect
timeout — the real-pod path, requires a jaxlib whose backend supports
cross-process collectives, see ``probe_multiprocess_support``) and
``replica`` (no jax.distributed: each worker is an independent replica
over its own local devices — the single-box CPU path the multi-process
chaos soak rides).  ``auto`` picks distributed only when a coordinator
can work: on the CPU backend it falls back to replica.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..obs import trace as obs_trace
from ..obs.metrics import get_registry, merge_snapshots
from .distributed import (
    ENV_CHAOS, ENV_CONNECT_TIMEOUT, ENV_COORDINATOR, ENV_INCARNATION,
    ENV_NUM_PROCESSES, ENV_PROCESS_ID, ENV_RUN_DIR, ENV_TRACE_DIR,
    initialize, resolve_process_index,
)
from .elastic import FailureDetector, RecoverableInfraError

logger = logging.getLogger("deeplearning4j_tpu")


class HostLostError(RecoverableInfraError):
    """A previously-alive member's heartbeat expired (process died, host
    preempted, network partition).  Message carries UNAVAILABLE so
    marker-based detectors classify it too; ``lost`` lists the members."""

    def __init__(self, lost: Sequence[int], epoch: int):
        super().__init__(
            f"UNAVAILABLE: host(s) {sorted(lost)} left the membership "
            f"(heartbeat expired) at epoch {epoch} — rebuilding over the "
            "survivors")
        self.lost = sorted(lost)
        self.epoch = epoch


class MembershipChangedError(RecoverableInfraError):
    """The membership epoch moved under a live trainer (typically a host
    JOINING back) — the mesh should be re-provisioned over the new
    member set before the next step."""

    def __init__(self, joined: Sequence[int], epoch: int):
        super().__init__(
            f"ABORTED: membership changed at epoch {epoch} — host(s) "
            f"{sorted(joined)} joined; re-provisioning the mesh")
        self.joined = sorted(joined)
        self.epoch = epoch


def _atomic_write_json(path: str, payload: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


class Membership:
    """Shared heartbeat ledger + coordinator-side membership epoch.

    Workers call ``beat(process_id)``; the coordinator (launcher) calls
    ``refresh()``, which recomputes the alive-set from heartbeat ages and
    bumps the persisted epoch whenever it changes.  Heartbeat files and
    the epoch ledger are single files under ``directory`` written with
    atomic renames, so readers never see torn JSON.  ``clock`` is
    injectable (fake-clock transition tests); cross-process use needs a
    wall clock — the default ``time.time`` — because monotonic clocks
    don't compare across processes."""

    LEDGER = "membership.json"

    def __init__(self, directory: str, heartbeat_timeout: float = 5.0,
                 clock: Callable[[], float] = time.time):
        if heartbeat_timeout <= 0:
            raise ValueError(f"heartbeat_timeout must be > 0, got "
                             f"{heartbeat_timeout}")
        self.directory = directory
        self.heartbeat_timeout = heartbeat_timeout
        self.clock = clock
        os.makedirs(directory, exist_ok=True)

    # -- worker side -------------------------------------------------------

    def _hb_path(self, process_id: int) -> str:
        return os.path.join(self.directory, f"hb_{int(process_id)}.json")

    def beat(self, process_id: int, pid: Optional[int] = None,
             step: Optional[int] = None) -> None:
        _atomic_write_json(self._hb_path(process_id), {
            "process_id": int(process_id),
            "pid": int(pid if pid is not None else os.getpid()),
            "step": step, "t": self.clock()})

    def last_beat(self, process_id: int) -> Optional[dict]:
        try:
            with open(self._hb_path(process_id)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def remove(self, process_id: int) -> None:
        try:
            os.remove(self._hb_path(process_id))
        except OSError:
            pass

    # -- coordinator side --------------------------------------------------

    def _scan(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for fn in names:
            if not (fn.startswith("hb_") and fn.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.directory, fn)) as f:
                    rec = json.load(f)
                out[int(rec["process_id"])] = rec
            except (OSError, ValueError, KeyError):
                continue   # torn/foreign file — not a member
        return out

    def alive(self) -> List[int]:
        now = self.clock()
        return sorted(i for i, rec in self._scan().items()
                      if now - float(rec.get("t", 0)) <= self.heartbeat_timeout)

    def read(self) -> dict:
        """The persisted ledger: {"epoch": int, "members": [ids]} (epoch 0,
        no members before the first refresh)."""
        try:
            with open(os.path.join(self.directory, self.LEDGER)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"epoch": 0, "members": []}

    @property
    def epoch(self) -> int:
        return int(self.read()["epoch"])

    def members(self) -> List[int]:
        return list(self.read()["members"])

    def refresh(self) -> int:
        """Recompute the alive-set; if it differs from the ledger, bump
        the epoch and persist — ONE bump per transition batch, so two
        hosts expiring in the same scan cost one epoch, not two.  Only
        the coordinator calls this (single ledger writer)."""
        led = self.read()
        alive = self.alive()
        if alive != list(led["members"]):
            led = {"epoch": int(led["epoch"]) + 1, "members": alive,
                   "t": self.clock()}
            _atomic_write_json(os.path.join(self.directory, self.LEDGER), led)
            obs_trace.instant("membership/epoch", cat="launcher",
                              epoch=led["epoch"], members=list(alive))
            logger.info("membership epoch %d: members %s", led["epoch"],
                        alive)
        return int(led["epoch"])


class Heartbeat:
    """Worker-side liveness beacon: a daemon thread that beats the shared
    Membership every ``interval`` seconds (plus once immediately), with an
    optional ``step_fn`` so the ledger records training progress.  A
    SIGSTOPped / wedged worker stops beating — which is exactly the
    signal the launcher's hang detection keys on."""

    def __init__(self, membership: Membership, process_id: int,
                 interval: float = 0.2,
                 step_fn: Optional[Callable[[], int]] = None,
                 export_metrics: bool = True, metrics_every: int = 5):
        self.membership = membership
        self.process_id = int(process_id)
        self.interval = interval
        self.step_fn = step_fn
        # pod-level telemetry: every Nth beat also snapshots the global
        # MetricsRegistry into run_dir/obs/ — the launcher's
        # ``pod_metrics()`` aggregates these per-worker files into one
        # pod view (docs/OBSERVABILITY.md)
        self.export_metrics = export_metrics
        self.metrics_every = max(1, int(metrics_every))
        self._beats = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def metrics_path(self) -> str:
        return os.path.join(self.membership.directory, "obs",
                            f"metrics_w{self.process_id}.json")

    def export_metrics_now(self) -> None:
        try:
            snap = get_registry().snapshot()
            snap["process_id"] = self.process_id
            snap["t"] = self.membership.clock()
            os.makedirs(os.path.dirname(self.metrics_path()), exist_ok=True)
            _atomic_write_json(self.metrics_path(), snap)
        except (OSError, TypeError, ValueError) as exc:
            logger.debug("metrics export failed: %s", exc)

    def set_step_fn(self, step_fn: Callable[[], int]) -> None:
        self.step_fn = step_fn

    def _beat_once(self) -> None:
        step = None
        if self.step_fn is not None:
            try:
                step = int(self.step_fn())
            except Exception:
                step = None
        try:
            self.membership.beat(self.process_id, step=step)
        except OSError as exc:   # run dir vanished mid-shutdown — not fatal
            logger.debug("heartbeat write failed: %s", exc)
        self._beats += 1
        if self.export_metrics and self._beats % self.metrics_every == 1:
            self.export_metrics_now()

    def start(self) -> "Heartbeat":
        if self._thread is not None:
            return self
        self._beat_once()

        def loop():
            while not self._stop.wait(self.interval):
                self._beat_once()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"heartbeat-{self.process_id}")
        self._thread.start()
        return self

    def stop(self, deregister: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self.export_metrics:
            self.export_metrics_now()   # final counters beat the interval
        if deregister:
            self.membership.remove(self.process_id)

    @classmethod
    def start_from_env(cls, step_fn: Optional[Callable[[], int]] = None,
                       interval: float = 0.2) -> Optional["Heartbeat"]:
        """Start beating iff launched under the pod launcher (the
        ``DL4J_TPU_RUN_DIR`` env is the contract); None otherwise."""
        run_dir = os.environ.get(ENV_RUN_DIR)
        if not run_dir:
            return None
        return cls(Membership(run_dir), resolve_process_index(),
                   interval=interval, step_fn=step_fn).start()


class ProcessFailureDetector(FailureDetector):
    """Heartbeat-based process-liveness detection on top of the marker
    classifier: ``check()`` compares the current alive-set against the
    last one it saw and raises :class:`HostLostError` (leave) or
    :class:`MembershipChangedError` (join) — both recoverable by
    construction.  Wire it into ``ElasticTrainer(membership_check=
    detector.check, failure_detector=detector, rebuild_fn=...)`` and a
    peer's death flows through the standard backoff/restore recovery with
    a mesh rebuilt over the survivors (``mesh.surviving_mesh``)."""

    def __init__(self, membership: Membership,
                 recover_on_join: bool = True):
        self.membership = membership
        self.recover_on_join = recover_on_join
        self._known: Optional[frozenset] = None

    def check(self) -> None:
        alive = frozenset(self.membership.alive())
        if self._known is None:       # first observation is the baseline
            self._known = alive
            return
        lost, joined = self._known - alive, alive - self._known
        self._known = alive
        epoch = self.membership.epoch
        if lost:
            raise HostLostError(lost, epoch)
        if joined and self.recover_on_join:
            raise MembershipChangedError(joined, epoch)


def maybe_bootstrap_from_env(timeout_s: Optional[float] = None) -> bool:
    """Join the jax.distributed cluster iff the launcher exported a
    coordinator address (``DL4J_TPU_COORDINATOR``); workers in replica
    mode (no coordinator) return False and stay single-process.  The
    bounded-timeout ``initialize`` raises CoordinatorUnreachableError
    instead of hanging when the coordinator is gone."""
    addr = os.environ.get(ENV_COORDINATOR)
    if not addr:
        return False
    n = int(os.environ[ENV_NUM_PROCESSES])
    i = resolve_process_index()
    if timeout_s is None:
        timeout_s = float(os.environ.get(ENV_CONNECT_TIMEOUT, "60"))
    initialize(addr, n, i, timeout_s=timeout_s)
    return True


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _with_device_count(xla_flags: str, count: int) -> str:
    """XLA_FLAGS with exactly one host-platform device-count flag."""
    kept = [t for t in xla_flags.split()
            if "xla_force_host_platform_device_count" not in t]
    kept.append(f"--xla_force_host_platform_device_count={count}")
    return " ".join(kept)


class _WorkerHandle:
    def __init__(self, process_id: int):
        self.process_id = process_id
        self.proc: Optional[subprocess.Popen] = None
        self.state = "pending"       # running | completed | unrecovered
        self.incarnation = 0
        self.restarts = 0
        self.hang_killed = False
        self.spawned_pids: List[int] = []
        self.log_path: Optional[str] = None
        self._log_f = None


class PodLauncher:
    """Fork, monitor, and heal a fleet of worker processes (one per
    "host") — the engine behind the CLI ``launch`` subcommand and the
    multi-process chaos soak.

    Every worker runs ``worker_argv`` with the ``DL4J_TPU_*`` env
    contract (process id/count, run dir for heartbeats, optional
    coordinator address, optional chaos spec).  The monitor loop:

    - reaps exited workers — rc 0 is completion; anything else is a host
      LEAVE, and the worker is relaunched (host JOIN) while its restart
      budget lasts, with the chaos spec stripped (a scheduled
      ``proc_kill`` fires once per run, not once per incarnation);
    - declares a worker HUNG when its heartbeat goes stale while the
      process is still alive (SIGSTOP, wedged runtime), SIGKILLs it, and
      relaunches through the same leave/join path;
    - bumps the membership epoch on every transition via
      ``Membership.refresh()``;
    - on exit, kills anything still running and verifies no orphan
      worker process survives (the leak check the soak gates on).
    """

    def __init__(self, worker_argv: Sequence[str], num_workers: int,
                 run_dir: str,
                 devices_per_worker: Optional[int] = None,
                 base_env: Optional[Dict[str, str]] = None,
                 chaos: Optional[Dict[int, str]] = None,
                 bootstrap: str = "replica",
                 coordinator_port: Optional[int] = None,
                 heartbeat_timeout: float = 5.0,
                 max_restarts: int = 2,
                 poll_interval: float = 0.1,
                 deadline_s: float = 600.0,
                 connect_timeout_s: float = 60.0,
                 platform: Optional[str] = None,
                 megascale_slices: Optional[int] = None,
                 trace_dir: Optional[str] = None):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        if bootstrap not in ("replica", "distributed"):
            raise ValueError(f"bootstrap must be replica/distributed, got "
                             f"{bootstrap!r}")
        self.worker_argv = list(worker_argv)
        self.num_workers = num_workers
        self.run_dir = run_dir
        self.devices_per_worker = devices_per_worker
        self.base_env = dict(base_env if base_env is not None else os.environ)
        self.chaos = dict(chaos or {})
        bad = set(self.chaos) - set(range(num_workers))
        if bad:
            raise ValueError(f"chaos targets {sorted(bad)} out of range "
                             f"[0, {num_workers})")
        self.bootstrap = bootstrap
        self.coordinator_port = coordinator_port
        self.heartbeat_timeout = heartbeat_timeout
        self.max_restarts = max_restarts
        self.poll_interval = poll_interval
        self.deadline_s = deadline_s
        self.connect_timeout_s = connect_timeout_s
        self.platform = platform
        self.megascale_slices = megascale_slices
        # when set, workers write per-incarnation Chrome traces here (the
        # DL4J_TPU_TRACE_DIR contract) and merge_trace() stitches them —
        # plus the launcher's own membership/leave/join instants — into
        # one pod timeline
        self.trace_dir = trace_dir
        self.membership = Membership(run_dir, heartbeat_timeout)
        self.handles = [_WorkerHandle(i) for i in range(num_workers)]
        self.events: List[dict] = []
        self._t0: Optional[float] = None
        get_registry().register_collector("launcher", self.stats,
                                          unique=True)

    def stats(self) -> dict:
        """Membership/fleet counters (the registry collector view)."""
        by_kind: Dict[str, int] = {}
        for e in self.events:
            by_kind[e["kind"]] = by_kind.get(e["kind"], 0) + 1
        return {"workers": self.num_workers,
                "epoch": self.membership.epoch,
                "members": self.membership.members(),
                "restarts": sum(h.restarts for h in self.handles),
                "events": by_kind}

    # -- env / spawn -------------------------------------------------------

    def _event(self, kind: str, worker: Optional[int] = None, **extra):
        e = {"t": round(time.time() - (self._t0 or time.time()), 3),
             "kind": kind}
        if worker is not None:
            e["worker"] = worker
        e.update(extra)
        self.events.append(e)
        obs_trace.instant(f"launcher/{kind}", cat="launcher",
                          **{k: v for k, v in e.items()
                             if k not in ("t", "kind", "log_tail")})
        logger.info("launcher: %s", e)

    def _env_for(self, h: _WorkerHandle) -> Dict[str, str]:
        env = dict(self.base_env)
        env[ENV_PROCESS_ID] = str(h.process_id)
        env[ENV_NUM_PROCESSES] = str(self.num_workers)
        env[ENV_RUN_DIR] = self.run_dir
        env[ENV_INCARNATION] = str(h.incarnation)
        env[ENV_CONNECT_TIMEOUT] = str(self.connect_timeout_s)
        if self.devices_per_worker:
            env["XLA_FLAGS"] = _with_device_count(
                env.get("XLA_FLAGS", ""), self.devices_per_worker)
        if self.platform:
            env["JAX_PLATFORMS"] = self.platform
        if self.bootstrap == "distributed":
            if self.coordinator_port is None:
                self.coordinator_port = free_port()
            env[ENV_COORDINATOR] = f"127.0.0.1:{self.coordinator_port}"
            # feed slice detection (distributed.detect_num_slices →
            # build_two_tier_mesh / ShardedTrainer.two_tier): each worker
            # process is one "slice" unless the deployment already set
            # the multislice runtime's env or the caller overrode it
            if self.megascale_slices:
                env["MEGASCALE_NUM_SLICES"] = str(self.megascale_slices)
            else:
                env.setdefault("MEGASCALE_NUM_SLICES",
                               str(self.num_workers))
        else:
            env.pop(ENV_COORDINATOR, None)
            if self.megascale_slices:
                env["MEGASCALE_NUM_SLICES"] = str(self.megascale_slices)
        if self.trace_dir:
            env[ENV_TRACE_DIR] = self.trace_dir
        spec = self.chaos.get(h.process_id)
        if spec and h.incarnation == 0:
            env[ENV_CHAOS] = spec     # consumed once per RUN: a relaunched
        else:                         # worker must not re-kill itself at
            env.pop(ENV_CHAOS, None)  # the same scheduled step forever
        return env

    def _spawn(self, h: _WorkerHandle) -> None:
        self.membership.remove(h.process_id)   # a stale beat from the dead
        # incarnation must not trip hang detection before the new process
        # gets through its imports to the first beat
        logs = os.path.join(self.run_dir, "logs")
        os.makedirs(logs, exist_ok=True)
        h.log_path = os.path.join(
            logs, f"worker{h.process_id}.inc{h.incarnation}.log")
        h._log_f = open(h.log_path, "wb")
        h.proc = subprocess.Popen(self.worker_argv, env=self._env_for(h),
                                  stdout=h._log_f,
                                  stderr=subprocess.STDOUT)
        h.state = "running"
        h.hang_killed = False
        h.spawned_pids.append(h.proc.pid)
        self._event("spawn", h.process_id, pid=h.proc.pid,
                    incarnation=h.incarnation)

    def _close_log(self, h: _WorkerHandle) -> None:
        if h._log_f is not None:
            try:
                h._log_f.close()
            except OSError:
                pass
            h._log_f = None

    def _log_tail(self, h: _WorkerHandle, n: int = 1500) -> str:
        try:
            with open(h.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - n))
                return f.read().decode(errors="replace")
        except (OSError, TypeError):
            return ""

    # -- monitor -----------------------------------------------------------

    def _poll_once(self) -> None:
        now = time.time()
        for h in self.handles:
            if h.state != "running":
                continue
            rc = h.proc.poll()
            if rc is not None:
                self._close_log(h)
                if rc == 0 and not h.hang_killed:
                    h.state = "completed"
                    self.membership.remove(h.process_id)
                    self._event("complete", h.process_id,
                                incarnation=h.incarnation)
                    continue
                kind = "hang" if h.hang_killed else "crash"
                self._event("leave", h.process_id, cause=kind, rc=rc,
                            incarnation=h.incarnation)
                if h.restarts < self.max_restarts:
                    h.restarts += 1
                    h.incarnation += 1
                    self._spawn(h)
                    self._event("join", h.process_id,
                                incarnation=h.incarnation)
                else:
                    h.state = "unrecovered"
                    self._event("unrecovered", h.process_id, cause=kind,
                                rc=rc, log_tail=self._log_tail(h))
                continue
            # alive — hang detection: a beat from THIS incarnation (the hb
            # file is removed at spawn) that has gone stale means the
            # process is wedged or stopped; never-beaten workers get
            # startup grace (imports/compiles) and are bounded by the
            # overall deadline instead
            hb = self.membership.last_beat(h.process_id)
            if hb is not None and \
                    now - float(hb.get("t", now)) > self.heartbeat_timeout:
                h.hang_killed = True
                self._event("hang_detected", h.process_id,
                            stale_s=round(now - float(hb["t"]), 2))
                try:
                    h.proc.kill()    # SIGKILL terminates SIGSTOPped too
                except OSError:
                    pass

    def _running(self) -> bool:
        return any(h.state == "running" for h in self.handles)

    def _reap_all(self) -> int:
        """Kill anything still alive and count it; then verify every pid
        this launcher EVER spawned is gone — the no-orphans contract."""
        leaked = 0
        for h in self.handles:
            if h.proc is not None and h.proc.poll() is None:
                leaked += 1
                try:
                    h.proc.kill()
                    h.proc.wait(timeout=10)
                except (OSError, subprocess.TimeoutExpired):
                    pass
            self._close_log(h)
        for h in self.handles:
            for pid in h.spawned_pids:
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    continue           # gone, as it should be
                except PermissionError:
                    pass               # exists under another uid — not ours
                else:
                    # still alive (a double-fork would land here) — last
                    # resort, then recheck
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except OSError:
                        pass
                    leaked += 1
        return leaked

    # -- pod-level telemetry -----------------------------------------------

    def pod_metrics(self) -> dict:
        """Aggregate the per-worker registry snapshots (written by each
        worker's Heartbeat into run_dir/obs/) plus this launcher's own
        registry into ONE pod-level view: counters summed, histogram
        buckets added, gauges min/mean/max across workers — the
        pod-scale ``/metrics`` answer."""
        workers: Dict[str, dict] = {}
        obs_dir = os.path.join(self.run_dir, "obs")
        try:
            names = sorted(os.listdir(obs_dir))
        except OSError:
            names = []
        for fn in names:
            if not (fn.startswith("metrics_w") and fn.endswith(".json")):
                continue
            try:
                with open(os.path.join(obs_dir, fn)) as f:
                    workers[fn[len("metrics_"):-len(".json")]] = json.load(f)
            except (OSError, ValueError):
                continue   # torn write — the next beat replaces it
        return {"workers": workers,
                "launcher": get_registry().snapshot(),
                "aggregate": merge_snapshots(list(workers.values()))}

    def merge_trace(self, out_path: str) -> Optional[dict]:
        """Stitch every per-worker (and per-incarnation) trace file under
        ``trace_dir`` — plus the launcher's own events, flushed here —
        into one pod timeline at ``out_path``; None when tracing was not
        armed or no worker wrote a trace."""
        if not self.trace_dir:
            return None
        rec = obs_trace.get_recorder()
        if rec is not None:
            rec.save(os.path.join(self.trace_dir, "launcher.trace.json"))
        try:
            names = sorted(os.listdir(self.trace_dir))
        except OSError:
            return None
        paths = [os.path.join(self.trace_dir, fn) for fn in names
                 if fn.endswith(".trace.json")
                 and not fn.endswith("pod.trace.json")]
        if not paths:
            return None
        return obs_trace.merge_traces(paths, out_path)

    def run(self) -> dict:
        """Launch the fleet, heal it until every worker completes (or its
        budget/deadline runs out), and return the run report."""
        self._t0 = time.time()
        os.makedirs(self.run_dir, exist_ok=True)
        for h in self.handles:
            self._spawn(h)
        deadline_hit = False
        leaked = 0
        try:
            while self._running():
                time.sleep(self.poll_interval)
                self.membership.refresh()
                self._poll_once()
                if time.time() - self._t0 > self.deadline_s:
                    deadline_hit = True
                    for h in self.handles:
                        if h.state == "running":
                            h.state = "unrecovered"
                            self._event("unrecovered", h.process_id,
                                        cause="deadline",
                                        log_tail=self._log_tail(h))
                    break
            self.membership.refresh()
        finally:
            leaked = self._reap_all()
        completed = [h.process_id for h in self.handles
                     if h.state == "completed"]
        unrecovered = [h.process_id for h in self.handles
                       if h.state == "unrecovered"]
        report = {
            "workers": self.num_workers,
            "completed": completed,
            "unrecovered": unrecovered,
            "restarts": sum(h.restarts for h in self.handles),
            "leaves": [e for e in self.events if e["kind"] == "leave"],
            "joins": sum(1 for e in self.events if e["kind"] == "join"),
            "hang_detected": sum(1 for e in self.events
                                 if e["kind"] == "hang_detected"),
            "epoch": self.membership.epoch,
            "deadline_hit": deadline_hit,
            "leaked_killed": leaked,
            "wall_seconds": round(time.time() - self._t0, 2),
            "events": self.events,
        }
        report["ok"] = (not unrecovered and not deadline_hit
                        and leaked == 0)
        report["pod_metrics"] = self.pod_metrics()
        return report
