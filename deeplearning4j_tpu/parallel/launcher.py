"""Pod-scale elastic runtime: multi-process launcher + host join/leave.

The missing layer between "virtual devices in one process" and a real
pod: every piece of >8-device evidence in this repo used to live inside
one OS process, and ElasticTrainer only survived *in-process* restarts.
This module makes processes the failure unit (PAPERS.md: the
TPU-supercomputer retrospective frames preemption-tolerant pod training
as THE production problem):

- :class:`PodLauncher` — forks N worker processes (the CLI ``launch``
  subcommand's engine), sets per-process device visibility and the
  ``DL4J_TPU_*`` env contract, monitors liveness, and RELAUNCHES workers
  that die or hang — host leave → join, with a bounded restart budget
  and a leak check that no orphan worker survives a run.
- :class:`Membership` — a shared heartbeat ledger with a coordinator-side
  membership **epoch**: workers beat, the coordinator's ``refresh()``
  bumps the epoch whenever the alive-set changes.  File-based (every
  worker of a single-box launch — and every host of a pod with a shared
  filesystem — can reach it), with an injectable clock so join/leave
  transitions are testable against a fake clock.
- :class:`Heartbeat` — the worker-side daemon thread that beats.
- :class:`ProcessFailureDetector` — a FailureDetector whose ``check()``
  raises :class:`HostLostError` / :class:`MembershipChangedError` when
  the membership moved; wired into ``ElasticTrainer(membership_check=)``
  it turns a peer host's death into the SAME backoff → rebuild → restore
  recovery path as a device loss, with ``mesh.surviving_mesh`` rebuilding
  a (possibly smaller ``dcn``) mesh over the survivors.

Bootstrap modes: ``distributed`` (workers call
``distributed.initialize`` against a coordinator with a bounded connect
timeout — the real-pod path, requires a jaxlib whose backend supports
cross-process collectives, see ``probe_multiprocess_support``) and
``replica`` (no jax.distributed: each worker is an independent replica
over its own local devices — the single-box CPU path the multi-process
chaos soak rides).  ``auto`` picks distributed only when a coordinator
can work: on the CPU backend it falls back to replica.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..obs import trace as obs_trace
from ..obs.metrics import get_registry, merge_snapshots
from .distributed import (
    ENV_CHAOS, ENV_CONNECT_TIMEOUT, ENV_COORD_PORTS, ENV_COORDINATOR,
    ENV_GRACE_S, ENV_INCARNATION, ENV_NUM_PROCESSES, ENV_PROCESS_ID,
    ENV_RUN_DIR, ENV_SERVE_PORT, ENV_TRACE_DIR, PREEMPTED_EXIT_CODE,
    CoordinatorUnreachableError, initialize, resolve_process_index,
)
from .elastic import FailureDetector, RecoverableInfraError

logger = logging.getLogger("deeplearning4j_tpu")


class HostLostError(RecoverableInfraError):
    """A previously-alive member's heartbeat expired (process died, host
    preempted, network partition).  Message carries UNAVAILABLE so
    marker-based detectors classify it too; ``lost`` lists the members."""

    def __init__(self, lost: Sequence[int], epoch: int):
        super().__init__(
            f"UNAVAILABLE: host(s) {sorted(lost)} left the membership "
            f"(heartbeat expired) at epoch {epoch} — rebuilding over the "
            "survivors")
        self.lost = sorted(lost)
        self.epoch = epoch


class MembershipChangedError(RecoverableInfraError):
    """The membership epoch moved under a live trainer (typically a host
    JOINING back) — the mesh should be re-provisioned over the new
    member set before the next step."""

    def __init__(self, joined: Sequence[int], epoch: int):
        super().__init__(
            f"ABORTED: membership changed at epoch {epoch} — host(s) "
            f"{sorted(joined)} joined; re-provisioning the mesh")
        self.joined = sorted(joined)
        self.epoch = epoch


def _atomic_write_json(path: str, payload: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


class Membership:
    """Shared heartbeat ledger + coordinator-side membership epoch.

    Workers call ``beat(process_id)``; the coordinator (launcher) calls
    ``refresh()``, which recomputes the alive-set from heartbeat ages and
    bumps the persisted epoch whenever it changes.  Heartbeat files and
    the epoch ledger are single files under ``directory`` written with
    atomic renames, so readers never see torn JSON.  ``clock`` is
    injectable (fake-clock transition tests); cross-process use needs a
    wall clock — the default ``time.time`` — because monotonic clocks
    don't compare across processes."""

    LEDGER = "membership.json"

    def __init__(self, directory: str, heartbeat_timeout: float = 5.0,
                 clock: Callable[[], float] = time.time):
        if heartbeat_timeout <= 0:
            raise ValueError(f"heartbeat_timeout must be > 0, got "
                             f"{heartbeat_timeout}")
        self.directory = directory
        self.heartbeat_timeout = heartbeat_timeout
        self.clock = clock
        os.makedirs(directory, exist_ok=True)

    # -- worker side -------------------------------------------------------

    def _hb_path(self, process_id: int) -> str:
        return os.path.join(self.directory, f"hb_{int(process_id)}.json")

    def _leaving_path(self, process_id: int) -> str:
        return os.path.join(self.directory,
                            f"leaving_{int(process_id)}.json")

    def beat(self, process_id: int, pid: Optional[int] = None,
             step: Optional[int] = None,
             step_s: Optional[float] = None,
             ckpt_step: Optional[int] = None,
             addr: Optional[str] = None) -> None:
        """Liveness beat.  Beyond (pid, step, t): ``step_s`` is this
        worker's current per-step wall time (straggler detection keys on
        it), ``ckpt_step`` the newest checkpoint step known durable on
        disk (pod-liveness reporting), ``addr`` a coordinator-capable
        host address (coordinator election)."""
        _atomic_write_json(self._hb_path(process_id), {
            "process_id": int(process_id),
            "pid": int(pid if pid is not None else os.getpid()),
            "step": step, "step_s": step_s, "ckpt_step": ckpt_step,
            "addr": addr, "t": self.clock()})

    def last_beat(self, process_id: int) -> Optional[dict]:
        try:
            with open(self._hb_path(process_id)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return None
        return rec if isinstance(rec, dict) else None

    def remove(self, process_id: int) -> None:
        try:
            os.remove(self._hb_path(process_id))
        except OSError:
            pass

    # -- announced leaves (preemption notices) -----------------------------

    def mark_leaving(self, process_id: int,
                     grace_s: Optional[float] = None) -> None:
        """Record that this worker received a preemption notice and will
        exit within ``grace_s`` — survivors and the launcher observe a
        fast LEAVE instead of waiting out the heartbeat timeout
        (parallel/preemption.py)."""
        _atomic_write_json(self._leaving_path(process_id), {
            "process_id": int(process_id), "grace_s": grace_s,
            "t": self.clock()})

    def clear_leaving(self, process_id: int) -> None:
        try:
            os.remove(self._leaving_path(process_id))
        except OSError:
            pass

    def leaving(self) -> Dict[int, dict]:
        """{process_id: marker} of workers that announced a leave (and
        have not been respawned since — the launcher clears the marker
        at spawn).  Torn/foreign files are skipped, same contract as
        ``_scan``."""
        out: Dict[int, dict] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for fn in names:
            if not (fn.startswith("leaving_") and fn.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.directory, fn)) as f:
                    rec = json.load(f)
                if not isinstance(rec, dict):
                    continue
                out[int(rec["process_id"])] = rec
            except (OSError, ValueError, KeyError, TypeError):
                continue
        return out

    # -- coordinator side --------------------------------------------------

    def _scan(self) -> Dict[int, dict]:
        """Read every heartbeat file, hardened against torn state: a
        worker killed mid-``beat()`` (or a foreign/garbage file matching
        the glob) must read as a MISSED beat, never raise into the
        coordinator's monitor loop — so empty files, truncated JSON,
        non-dict payloads (``null``) and malformed ids are all skipped."""
        out: Dict[int, dict] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for fn in names:
            if not (fn.startswith("hb_") and fn.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.directory, fn)) as f:
                    rec = json.load(f)
                if not isinstance(rec, dict):
                    continue   # json "null"/list — torn or foreign
                out[int(rec["process_id"])] = rec
            except (OSError, ValueError, KeyError, TypeError):
                continue   # torn/foreign file — not a member
        return out

    @staticmethod
    def _num(value, default: float = 0.0) -> float:
        try:
            return float(value)
        except (TypeError, ValueError):
            return default

    def alive(self) -> List[int]:
        """Members with a fresh heartbeat, EXCLUDING those that announced
        a leave — a preemption notice is an immediate logical departure
        (the fast-LEAVE contract), even while the worker spends its grace
        budget writing the emergency checkpoint."""
        now = self.clock()
        leaving = self.leaving()
        return sorted(i for i, rec in self._scan().items()
                      if i not in leaving
                      and now - self._num(rec.get("t")) <=
                      self.heartbeat_timeout)

    def last_checkpoint_step(self) -> int:
        """Newest checkpoint step any member reported durable in its
        heartbeat (-1 when nobody reported one) — the launcher's
        "how much work would a loss cost right now" number."""
        steps = [int(self._num(rec.get("ckpt_step"), -1))
                 for rec in self._scan().values()
                 if rec.get("ckpt_step") is not None]
        return max(steps, default=-1)

    def read(self) -> dict:
        """The persisted ledger: {"epoch": int, "members": [ids]} (epoch 0,
        no members before the first refresh).  A truncated/garbage ledger
        file degrades to the empty default — the next ``refresh()``
        re-persists from the heartbeat scan — instead of raising."""
        default = {"epoch": 0, "members": []}
        try:
            with open(os.path.join(self.directory, self.LEDGER)) as f:
                led = json.load(f)
        except (OSError, ValueError):
            return default
        if (not isinstance(led, dict)
                or not isinstance(led.get("members"), list)):
            return default
        try:
            led["epoch"] = int(led["epoch"])
        except (KeyError, TypeError, ValueError):
            return default
        return led

    @property
    def epoch(self) -> int:
        return int(self.read()["epoch"])

    def members(self) -> List[int]:
        return list(self.read()["members"])

    def refresh(self) -> int:
        """Recompute the alive-set; if it differs from the ledger, bump
        the epoch and persist — ONE bump per transition batch, so two
        hosts expiring in the same scan cost one epoch, not two.  Only
        the coordinator calls this (single ledger writer)."""
        led = self.read()
        alive = self.alive()
        if alive != list(led["members"]):
            led = {"epoch": int(led["epoch"]) + 1, "members": alive,
                   "t": self.clock()}
            _atomic_write_json(os.path.join(self.directory, self.LEDGER), led)
            obs_trace.instant("membership/epoch", cat="launcher",
                              epoch=led["epoch"], members=list(alive))
            logger.info("membership epoch %d: members %s", led["epoch"],
                        alive)
        return int(led["epoch"])


class Heartbeat:
    """Worker-side liveness beacon: a daemon thread that beats the shared
    Membership every ``interval`` seconds (plus once immediately), with an
    optional ``step_fn`` so the ledger records training progress.  A
    SIGSTOPped / wedged worker stops beating — which is exactly the
    signal the launcher's hang detection keys on."""

    def __init__(self, membership: Membership, process_id: int,
                 interval: float = 0.2,
                 step_fn: Optional[Callable[[], int]] = None,
                 ckpt_step_fn: Optional[Callable[[], int]] = None,
                 export_metrics: bool = True, metrics_every: int = 5):
        self.membership = membership
        self.process_id = int(process_id)
        self.interval = interval
        self.step_fn = step_fn
        # pod-liveness extras: the newest DURABLE checkpoint step (e.g.
        # ``lambda: elastic_trainer.last_checkpoint_step``) rides the
        # beat, and per-step wall time is DERIVED from step_fn deltas —
        # the launcher's straggler detection needs no trainer wiring
        self.ckpt_step_fn = ckpt_step_fn
        self._last_step: Optional[int] = None
        self._last_step_t: Optional[float] = None
        self._step_s: Optional[float] = None
        self._step_samples = 0
        # pod-level telemetry: every Nth beat also snapshots the global
        # MetricsRegistry into run_dir/obs/ — the launcher's
        # ``pod_metrics()`` aggregates these per-worker files into one
        # pod view (docs/OBSERVABILITY.md)
        self.export_metrics = export_metrics
        self.metrics_every = max(1, int(metrics_every))
        self._beats = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def metrics_path(self) -> str:
        return os.path.join(self.membership.directory, "obs",
                            f"metrics_w{self.process_id}.json")

    def export_metrics_now(self) -> None:
        try:
            snap = get_registry().snapshot()
            snap["process_id"] = self.process_id
            snap["t"] = self.membership.clock()
            os.makedirs(os.path.dirname(self.metrics_path()), exist_ok=True)
            _atomic_write_json(self.metrics_path(), snap)
        except (OSError, TypeError, ValueError) as exc:
            logger.debug("metrics export failed: %s", exc)

    def set_step_fn(self, step_fn: Callable[[], int]) -> None:
        self.step_fn = step_fn

    def set_ckpt_step_fn(self, ckpt_step_fn: Callable[[], int]) -> None:
        self.ckpt_step_fn = ckpt_step_fn

    def _observe_step(self, step: Optional[int]) -> Optional[float]:
        """Derive per-step wall time from step_fn deltas.  The FIRST
        delta is discarded — it includes jit compilation (the same
        compile-grace reasoning as the elastic step watchdog), and a
        compile-polluted sample would make every cold-starting worker
        look like a straggler."""
        if step is None:
            return self._step_s
        now = self.membership.clock()
        if self._last_step is not None and step > self._last_step:
            sample = (now - self._last_step_t) / (step - self._last_step)
            self._step_samples += 1
            if self._step_samples >= 2:
                self._step_s = sample
        if self._last_step is None or step != self._last_step:
            self._last_step, self._last_step_t = step, now
        return self._step_s

    def _beat_once(self) -> None:
        step = ckpt_step = None
        if self.step_fn is not None:
            try:
                step = int(self.step_fn())
            except Exception:
                step = None
        if self.ckpt_step_fn is not None:
            try:
                ckpt_step = int(self.ckpt_step_fn())
            except Exception:
                ckpt_step = None
        try:
            self.membership.beat(self.process_id, step=step,
                                 step_s=self._observe_step(step),
                                 ckpt_step=ckpt_step)
        except OSError as exc:   # run dir vanished mid-shutdown — not fatal
            logger.debug("heartbeat write failed: %s", exc)
        self._beats += 1
        if self.export_metrics and self._beats % self.metrics_every == 1:
            self.export_metrics_now()

    def start(self) -> "Heartbeat":
        if self._thread is not None:
            return self
        self._beat_once()

        def loop():
            while not self._stop.wait(self.interval):
                self._beat_once()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"heartbeat-{self.process_id}")
        self._thread.start()
        return self

    def stop(self, deregister: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self.export_metrics:
            self.export_metrics_now()   # final counters beat the interval
        if deregister:
            self.membership.remove(self.process_id)

    @classmethod
    def start_from_env(cls, step_fn: Optional[Callable[[], int]] = None,
                       interval: float = 0.2,
                       ckpt_step_fn: Optional[Callable[[], int]] = None,
                       ) -> Optional["Heartbeat"]:
        """Start beating iff launched under the pod launcher (the
        ``DL4J_TPU_RUN_DIR`` env is the contract); None otherwise."""
        run_dir = os.environ.get(ENV_RUN_DIR)
        if not run_dir:
            return None
        return cls(Membership(run_dir), resolve_process_index(),
                   interval=interval, step_fn=step_fn,
                   ckpt_step_fn=ckpt_step_fn).start()


class ProcessFailureDetector(FailureDetector):
    """Heartbeat-based process-liveness detection on top of the marker
    classifier: ``check()`` compares the current alive-set against the
    last one it saw and raises :class:`HostLostError` (leave) or
    :class:`MembershipChangedError` (join) — both recoverable by
    construction.  Wire it into ``ElasticTrainer(membership_check=
    detector.check, failure_detector=detector, rebuild_fn=...)`` and a
    peer's death flows through the standard backoff/restore recovery with
    a mesh rebuilt over the survivors (``mesh.surviving_mesh``)."""

    def __init__(self, membership: Membership,
                 recover_on_join: bool = True):
        self.membership = membership
        self.recover_on_join = recover_on_join
        self._known: Optional[frozenset] = None

    def check(self) -> None:
        alive = frozenset(self.membership.alive())
        if self._known is None:       # first observation is the baseline
            self._known = alive
            return
        lost, joined = self._known - alive, alive - self._known
        self._known = alive
        epoch = self.membership.epoch
        if lost:
            raise HostLostError(lost, epoch)
        if joined and self.recover_on_join:
            raise MembershipChangedError(joined, epoch)


def elect_coordinator(membership: Membership, ports) -> tuple:
    """→ (leader_id, 'host:port'): the survivor with the LOWEST alive id
    from the heartbeat ledger, at its coordinator-capable port.  ``ports``
    maps process id → port (dict or sequence — the launcher exports it as
    the comma-separated ``DL4J_TPU_COORD_PORTS`` env).  The host comes
    from the leader's own heartbeat ``addr`` field when it advertised one
    (multi-box pods), else 127.0.0.1 (the single-box launcher).  Raises
    CoordinatorUnreachableError when nobody is alive to elect — there is
    no cluster left to rejoin."""
    alive = membership.alive()
    if not alive:
        raise CoordinatorUnreachableError(
            "coordinator election found no alive member in the ledger at "
            f"{membership.directory} — nothing to fail over to")
    leader = min(alive)
    try:
        port = int(ports[leader])
    except (KeyError, IndexError, TypeError, ValueError):
        raise CoordinatorUnreachableError(
            f"no coordinator port known for elected leader {leader} "
            f"(ports: {ports!r})")
    beat = membership.last_beat(leader) or {}
    host = beat.get("addr") or "127.0.0.1"
    return leader, f"{host}:{port}"


def maybe_bootstrap_from_env(timeout_s: Optional[float] = None,
                             _initialize=None) -> bool:
    """Join the jax.distributed cluster iff the launcher exported a
    coordinator address (``DL4J_TPU_COORDINATOR``); workers in replica
    mode (no coordinator) return False and stay single-process.  The
    bounded-timeout ``initialize`` raises CoordinatorUnreachableError
    instead of hanging when the coordinator is gone.

    Coordinator restart: when the configured coordinator is unreachable
    AND the launcher exported per-process coordinator ports
    (``DL4J_TPU_COORD_PORTS``) plus a run dir, the worker does NOT die —
    it elects the survivor with the lowest alive id from the membership
    ledger (``elect_coordinator``) and re-initializes against that
    address.  ``_initialize`` is injectable for tests."""
    init = _initialize or initialize
    addr = os.environ.get(ENV_COORDINATOR)
    if not addr:
        return False
    n = int(os.environ[ENV_NUM_PROCESSES])
    i = resolve_process_index()
    if timeout_s is None:
        timeout_s = float(os.environ.get(ENV_CONNECT_TIMEOUT, "60"))
    try:
        init(addr, n, i, timeout_s=timeout_s)
        return True
    except CoordinatorUnreachableError:
        run_dir = os.environ.get(ENV_RUN_DIR)
        ports_env = os.environ.get(ENV_COORD_PORTS)
        if not run_dir or not ports_env:
            raise   # no failover contract — the old terminal behavior
        ports = [int(p) for p in ports_env.split(",") if p.strip()]
        leader, new_addr = elect_coordinator(Membership(run_dir), ports)
        if new_addr == addr:
            raise   # election picked the address that just failed
        obs_trace.instant("launcher/coordinator_failover", cat="launcher",
                          leader=leader, addr=new_addr, process=i)
        logger.warning("coordinator %s unreachable — failing over to "
                       "elected survivor %d at %s", addr, leader, new_addr)
        init(new_addr, n, i, timeout_s=timeout_s)
        return True


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _with_device_count(xla_flags: str, count: int) -> str:
    """XLA_FLAGS with exactly one host-platform device-count flag."""
    kept = [t for t in xla_flags.split()
            if "xla_force_host_platform_device_count" not in t]
    kept.append(f"--xla_force_host_platform_device_count={count}")
    return " ".join(kept)


class _WorkerHandle:
    def __init__(self, process_id: int):
        self.process_id = process_id
        self.proc: Optional[subprocess.Popen] = None
        self.state = "pending"       # running | completed | unrecovered
        self.incarnation = 0
        self.restarts = 0            # budget-consuming relaunches only
        self.planned_leaves = 0      # PREEMPTED exits (budget untouched)
        self.hang_killed = False
        self.notice_t: Optional[float] = None   # wall clock of the notice
        self.grace_escalated = False
        self.straggler_streak = 0
        self.straggler_flagged = False
        self.straggler_killed = False
        self._last_hb_seen: Optional[float] = None
        self.spawned_pids: List[int] = []
        self.log_path: Optional[str] = None
        self._log_f = None


class PodLauncher:
    """Fork, monitor, and heal a fleet of worker processes (one per
    "host") — the engine behind the CLI ``launch`` subcommand and the
    multi-process chaos soak.

    Every worker runs ``worker_argv`` with the ``DL4J_TPU_*`` env
    contract (process id/count, run dir for heartbeats, optional
    coordinator address, optional chaos spec).  The monitor loop:

    - reaps exited workers — rc 0 is completion; anything else is a host
      LEAVE, and the worker is relaunched (host JOIN) while its restart
      budget lasts, with the chaos spec stripped (a scheduled
      ``proc_kill`` fires once per run, not once per incarnation);
    - declares a worker HUNG when its heartbeat goes stale while the
      process is still alive (SIGSTOP, wedged runtime), SIGKILLs it, and
      relaunches through the same leave/join path;
    - bumps the membership epoch on every transition via
      ``Membership.refresh()``;
    - on exit, kills anything still running and verifies no orphan
      worker process survives (the leak check the soak gates on).
    """

    def __init__(self, worker_argv: Sequence[str], num_workers: int,
                 run_dir: str,
                 devices_per_worker: Optional[int] = None,
                 base_env: Optional[Dict[str, str]] = None,
                 chaos: Optional[Dict[int, str]] = None,
                 bootstrap: str = "replica",
                 coordinator_port: Optional[int] = None,
                 heartbeat_timeout: float = 5.0,
                 max_restarts: int = 2,
                 poll_interval: float = 0.1,
                 deadline_s: float = 600.0,
                 connect_timeout_s: float = 60.0,
                 platform: Optional[str] = None,
                 megascale_slices: Optional[int] = None,
                 trace_dir: Optional[str] = None,
                 grace_s: float = 30.0,
                 max_planned_leaves: int = 8,
                 straggler_factor: float = 2.0,
                 straggler_beats: int = 3,
                 straggler_policy: str = "flag",
                 serve: bool = False,
                 clock: Callable[[], float] = time.time):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        if bootstrap not in ("replica", "distributed"):
            raise ValueError(f"bootstrap must be replica/distributed, got "
                             f"{bootstrap!r}")
        self.worker_argv = list(worker_argv)
        self.num_workers = num_workers
        self.run_dir = run_dir
        self.devices_per_worker = devices_per_worker
        self.base_env = dict(base_env if base_env is not None else os.environ)
        self.chaos = dict(chaos or {})
        bad = set(self.chaos) - set(range(num_workers))
        if bad:
            raise ValueError(f"chaos targets {sorted(bad)} out of range "
                             f"[0, {num_workers})")
        self.bootstrap = bootstrap
        self.coordinator_port = coordinator_port
        self.heartbeat_timeout = heartbeat_timeout
        self.max_restarts = max_restarts
        self.poll_interval = poll_interval
        self.deadline_s = deadline_s
        self.connect_timeout_s = connect_timeout_s
        self.platform = platform
        self.megascale_slices = megascale_slices
        # when set, workers write per-incarnation Chrome traces here (the
        # DL4J_TPU_TRACE_DIR contract) and merge_trace() stitches them —
        # plus the launcher's own membership/leave/join instants — into
        # one pod timeline
        self.trace_dir = trace_dir
        # announced failures (docs/FAULT_TOLERANCE.md "Announced
        # failures"): grace_s is the emergency-checkpoint budget exported
        # to workers AND the launcher-side escalation deadline — a
        # notified worker still alive past ~1.5x the budget is SIGKILLed
        # (it is wedged, and the scheduler is about to do the same).
        # max_planned_leaves bounds PREEMPTED-exit relaunches separately
        # from the restart budget (a worker that always exits 75 must not
        # relaunch forever).
        if grace_s <= 0:
            raise ValueError(f"grace_s must be > 0, got {grace_s}")
        self.grace_s = grace_s
        self.max_planned_leaves = max_planned_leaves
        # straggler policy: a worker whose per-step wall time (from its
        # heartbeat) exceeds straggler_factor x the median of its PEERS'
        # step times for straggler_beats consecutive fresh beats is
        # flagged ("flag", the default: counter + trace instant + event)
        # or killed-and-relaunched ("relaunch", consuming restart budget);
        # "off" disables the scan
        if straggler_policy not in ("off", "flag", "relaunch"):
            raise ValueError(f"straggler_policy must be off/flag/relaunch, "
                             f"got {straggler_policy!r}")
        self.straggler_factor = straggler_factor
        self.straggler_beats = max(1, int(straggler_beats))
        self.straggler_policy = straggler_policy
        # serving worker role (``launch --serve``): every worker gets a
        # preassigned HTTP port exported as DL4J_TPU_SERVE_PORT — a
        # serve-role worker binds its UIServer there, and a fleet router
        # (serving/fleet.py) reaches the whole pod via serve_endpoints();
        # ports are STABLE across relaunches so a recovered host rejoins
        # the fleet at the same address
        self.serve_ports: Optional[List[int]] = (
            [free_port() for _ in range(num_workers)] if serve else None)
        # one injectable wall clock shared with the membership ledger:
        # launcher event times, notice deadlines and heartbeat staleness
        # all read the SAME clock, and fake-clock tests can drive it
        self.clock = clock
        self.membership = Membership(run_dir, heartbeat_timeout,
                                     clock=clock)
        self.handles = [_WorkerHandle(i) for i in range(num_workers)]
        self.events: List[dict] = []
        self._t0: Optional[float] = None
        self._shutting_down = False
        self._shutdown_forwarded = False
        self._prev_sigterm = None
        self.coord_ports: Optional[List[int]] = None
        reg = get_registry()
        self._m_preempt_notices = reg.counter("launcher_preempt_notices_total")
        self._m_planned_leaves = reg.counter("launcher_planned_leaves_total")
        self._m_stragglers = reg.counter("launcher_stragglers_total")
        self._m_grace_escalations = reg.counter(
            "launcher_grace_escalations_total")
        reg.register_collector("launcher", self.stats, unique=True)

    def stats(self) -> dict:
        """Membership/fleet counters (the registry collector view — this
        is what ``/metrics`` shows under ``registry.collected.launcher``):
        the pod-liveness answer an operator needs at a glance — epoch,
        who is alive, who announced a leave, and the newest checkpoint
        step known durable (how much work a loss would cost)."""
        by_kind: Dict[str, int] = {}
        for e in self.events:
            by_kind[e["kind"]] = by_kind.get(e["kind"], 0) + 1
        return {"workers": self.num_workers,
                "epoch": self.membership.epoch,
                "members": self.membership.members(),
                "alive": self.membership.alive(),
                "leaving": sorted(self.membership.leaving()),
                "last_checkpoint_step":
                    self.membership.last_checkpoint_step(),
                "restarts": sum(h.restarts for h in self.handles),
                "planned_leaves": sum(h.planned_leaves
                                      for h in self.handles),
                "stragglers_flagged": sum(1 for h in self.handles
                                          if h.straggler_flagged),
                "events": by_kind}

    def serve_endpoints(self) -> List[str]:
        """``host:port`` per worker when launched with ``serve=True``
        (``launch --serve``) — feed these to ``serve --fleet`` or
        ``FleetRouter`` over ``HttpHost``s."""
        if self.serve_ports is None:
            raise RuntimeError("launcher was not started with serve=True")
        return [f"127.0.0.1:{p}" for p in self.serve_ports]

    # -- env / spawn -------------------------------------------------------

    def _event(self, kind: str, worker: Optional[int] = None, **extra):
        e = {"t": round(self.clock() - (self._t0 or self.clock()), 3),
             "kind": kind}
        if worker is not None:
            e["worker"] = worker
        e.update(extra)
        self.events.append(e)
        obs_trace.instant(f"launcher/{kind}", cat="launcher",
                          **{k: v for k, v in e.items()
                             if k not in ("t", "kind", "log_tail")})
        logger.info("launcher: %s", e)

    def _env_for(self, h: _WorkerHandle) -> Dict[str, str]:
        env = dict(self.base_env)
        env[ENV_PROCESS_ID] = str(h.process_id)
        env[ENV_NUM_PROCESSES] = str(self.num_workers)
        env[ENV_RUN_DIR] = self.run_dir
        env[ENV_INCARNATION] = str(h.incarnation)
        env[ENV_CONNECT_TIMEOUT] = str(self.connect_timeout_s)
        if self.devices_per_worker:
            env["XLA_FLAGS"] = _with_device_count(
                env.get("XLA_FLAGS", ""), self.devices_per_worker)
        if self.platform:
            env["JAX_PLATFORMS"] = self.platform
        env[ENV_GRACE_S] = str(self.grace_s)
        if self.bootstrap == "distributed":
            if self.coordinator_port is None:
                self.coordinator_port = free_port()
            env[ENV_COORDINATOR] = f"127.0.0.1:{self.coordinator_port}"
            # restartable coordinator: every worker gets a preassigned
            # coordinator-capable port, so a worker that finds the
            # configured coordinator dead can elect the survivor with the
            # lowest alive id and re-initialize there (elect_coordinator
            # + maybe_bootstrap_from_env failover)
            if self.coord_ports is None:
                self.coord_ports = [self.coordinator_port] + [
                    free_port() for _ in range(self.num_workers - 1)]
            env[ENV_COORD_PORTS] = ",".join(str(p)
                                            for p in self.coord_ports)
            # feed slice detection (distributed.detect_num_slices →
            # build_two_tier_mesh / ShardedTrainer.two_tier): each worker
            # process is one "slice" unless the deployment already set
            # the multislice runtime's env or the caller overrode it
            if self.megascale_slices:
                env["MEGASCALE_NUM_SLICES"] = str(self.megascale_slices)
            else:
                env.setdefault("MEGASCALE_NUM_SLICES",
                               str(self.num_workers))
        else:
            env.pop(ENV_COORDINATOR, None)
            if self.megascale_slices:
                env["MEGASCALE_NUM_SLICES"] = str(self.megascale_slices)
        if self.trace_dir:
            env[ENV_TRACE_DIR] = self.trace_dir
        if self.serve_ports is not None:
            env[ENV_SERVE_PORT] = str(self.serve_ports[h.process_id])
        spec = self.chaos.get(h.process_id)
        if spec and h.incarnation == 0:
            env[ENV_CHAOS] = spec     # consumed once per RUN: a relaunched
        else:                         # worker must not re-kill itself at
            env.pop(ENV_CHAOS, None)  # the same scheduled step forever
        return env

    def _spawn(self, h: _WorkerHandle) -> None:
        self.membership.remove(h.process_id)   # a stale beat from the dead
        # incarnation must not trip hang detection before the new process
        # gets through its imports to the first beat
        self.membership.clear_leaving(h.process_id)   # the new incarnation
        # is joining, not leaving — a stale marker would exclude it from
        # alive() forever
        h.notice_t = None
        h.grace_escalated = False
        h.straggler_streak = 0
        h.straggler_flagged = False
        h.straggler_killed = False
        h._last_hb_seen = None
        logs = os.path.join(self.run_dir, "logs")
        os.makedirs(logs, exist_ok=True)
        h.log_path = os.path.join(
            logs, f"worker{h.process_id}.inc{h.incarnation}.log")
        h._log_f = open(h.log_path, "wb")
        h.proc = subprocess.Popen(self.worker_argv, env=self._env_for(h),
                                  stdout=h._log_f,
                                  stderr=subprocess.STDOUT)
        h.state = "running"
        h.hang_killed = False
        h.spawned_pids.append(h.proc.pid)
        self._event("spawn", h.process_id, pid=h.proc.pid,
                    incarnation=h.incarnation)

    def _close_log(self, h: _WorkerHandle) -> None:
        if h._log_f is not None:
            try:
                h._log_f.close()
            except OSError:
                pass
            h._log_f = None

    def _log_tail(self, h: _WorkerHandle, n: int = 1500) -> str:
        try:
            with open(h.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - n))
                return f.read().decode(errors="replace")
        except (OSError, TypeError):
            return ""

    # -- monitor -----------------------------------------------------------

    def _poll_once(self) -> None:
        now = self.clock()
        leaving = self.membership.leaving()
        for h in self.handles:
            if h.state != "running":
                continue
            rc = h.proc.poll()
            if rc is not None:
                self._close_log(h)
                if rc == 0 and not h.hang_killed:
                    h.state = "completed"
                    self.membership.remove(h.process_id)
                    self._event("complete", h.process_id,
                                incarnation=h.incarnation)
                    continue
                if (rc == PREEMPTED_EXIT_CODE and not h.hang_killed
                        and not h.grace_escalated):
                    # PLANNED leave: the worker processed its notice,
                    # wrote the emergency checkpoint, and exited on
                    # purpose — relaunch WITHOUT consuming the restart
                    # budget (preemption is the platform's fault, not the
                    # worker's)
                    h.planned_leaves += 1
                    self._m_planned_leaves.inc()
                    self._event("leave", h.process_id, cause="preempted",
                                rc=rc, incarnation=h.incarnation,
                                planned=True)
                    if self._shutting_down:
                        h.state = "completed"
                        self.membership.remove(h.process_id)
                    elif h.planned_leaves <= self.max_planned_leaves:
                        h.incarnation += 1
                        self._spawn(h)
                        self._event("join", h.process_id,
                                    incarnation=h.incarnation)
                    else:
                        h.state = "unrecovered"
                        self._event("unrecovered", h.process_id,
                                    cause="preempt_loop", rc=rc,
                                    log_tail=self._log_tail(h))
                    continue
                if self._shutting_down:
                    # pod shutdown in progress: exits are expected; a
                    # worker without a preemption handler dies on the
                    # forwarded SIGTERM itself (rc -15) — that is still a
                    # clean shutdown, not a crash to relaunch
                    h.state = "completed"
                    self.membership.remove(h.process_id)
                    self._event("leave", h.process_id, cause="shutdown",
                                rc=rc, incarnation=h.incarnation)
                    continue
                if h.grace_escalated:
                    kind = "grace_expired"
                elif h.straggler_killed:
                    kind = "straggler"
                elif h.hang_killed:
                    kind = "hang"
                else:
                    kind = "crash"
                self._event("leave", h.process_id, cause=kind, rc=rc,
                            incarnation=h.incarnation)
                if h.restarts < self.max_restarts:
                    h.restarts += 1
                    h.incarnation += 1
                    self._spawn(h)
                    self._event("join", h.process_id,
                                incarnation=h.incarnation)
                else:
                    h.state = "unrecovered"
                    self._event("unrecovered", h.process_id, cause=kind,
                                rc=rc, log_tail=self._log_tail(h))
                continue
            # alive — observe a self-announced leave (the worker's
            # preemption handler wrote the ledger marker, e.g. the
            # scheduler SIGTERMed it directly): start the escalation
            # clock from the marker's own timestamp
            if h.notice_t is None and h.process_id in leaving:
                h.notice_t = Membership._num(
                    leaving[h.process_id].get("t"), now)
                self._m_preempt_notices.inc()
                self._event("preempt_notice", h.process_id,
                            source="worker", incarnation=h.incarnation)
            # grace escalation: a notified worker still alive well past
            # the budget is wedged — SIGKILL it (the scheduler is about
            # to anyway) and recover through the normal leave path
            if (h.notice_t is not None and not h.grace_escalated
                    and now - h.notice_t >
                    self.grace_s + max(1.0, 0.5 * self.grace_s)):
                h.grace_escalated = True
                self._m_grace_escalations.inc()
                self._event("grace_expired", h.process_id,
                            overdue_s=round(now - h.notice_t, 2))
                try:
                    h.proc.kill()
                except OSError:
                    pass
                continue
            # hang detection: a beat from THIS incarnation (the hb
            # file is removed at spawn) that has gone stale means the
            # process is wedged or stopped; never-beaten workers get
            # startup grace (imports/compiles) and are bounded by the
            # overall deadline instead
            hb = self.membership.last_beat(h.process_id)
            if hb is not None and \
                    now - Membership._num(hb.get("t"), now) > \
                    self.heartbeat_timeout:
                h.hang_killed = True
                self._event("hang_detected", h.process_id,
                            stale_s=round(now - float(hb["t"]), 2))
                try:
                    h.proc.kill()    # SIGKILL terminates SIGSTOPped too
                except OSError:
                    pass
        self._check_stragglers()

    def _check_stragglers(self) -> None:
        """Flag (or relaunch) workers whose per-step wall time — derived
        by their Heartbeat and carried in the beat — exceeds
        ``straggler_factor`` x the median of their PEERS' step times for
        ``straggler_beats`` consecutive FRESH beats.  Peer median (not
        pod median including self) so a single slow worker among few
        can't drag the threshold up to meet itself; requires >= 2 running
        workers with steady-state samples.  One flag per incarnation."""
        if self.straggler_policy == "off" or self.num_workers < 2:
            return
        beats: Dict[int, dict] = {}
        for h in self.handles:
            if h.state != "running":
                continue
            hb = self.membership.last_beat(h.process_id)
            if hb is not None:
                beats[h.process_id] = hb
        for h in self.handles:
            hb = beats.get(h.process_id)
            if hb is None or h.state != "running":
                continue
            t = Membership._num(hb.get("t"))
            if h._last_hb_seen is not None and t <= h._last_hb_seen:
                continue          # same beat — don't recount the streak
            h._last_hb_seen = t
            step_s = hb.get("step_s")
            if not isinstance(step_s, (int, float)) or step_s <= 0:
                continue
            peers = [b.get("step_s") for i, b in beats.items()
                     if i != h.process_id
                     and isinstance(b.get("step_s"), (int, float))
                     and b.get("step_s") > 0]
            if not peers:
                continue
            peers.sort()
            median = peers[len(peers) // 2] if len(peers) % 2 else \
                0.5 * (peers[len(peers) // 2 - 1] + peers[len(peers) // 2])
            if median > 0 and step_s > self.straggler_factor * median:
                h.straggler_streak += 1
            else:
                h.straggler_streak = 0
                continue
            if (h.straggler_streak >= self.straggler_beats
                    and not h.straggler_flagged):
                h.straggler_flagged = True
                self._m_stragglers.inc()
                self._event("straggler", h.process_id,
                            step_s=round(float(step_s), 4),
                            peer_median_s=round(float(median), 4),
                            streak=h.straggler_streak,
                            policy=self.straggler_policy)
                if self.straggler_policy == "relaunch":
                    h.straggler_killed = True
                    try:
                        h.proc.kill()
                    except OSError:
                        pass

    def _running(self) -> bool:
        return any(h.state == "running" for h in self.handles)

    # -- announced preemption ----------------------------------------------

    def preempt_worker(self, process_id: int) -> bool:
        """Deliver a preemption notice (SIGTERM) to one running worker —
        the launcher-side half of the announced-failure path: the worker's
        PreemptionHandler writes its emergency checkpoint and exits
        PREEMPTED within the grace budget, or the monitor escalates to
        SIGKILL past it.  → True when the signal was sent."""
        h = self.handles[process_id]
        if h.state != "running" or h.proc is None:
            return False
        try:
            h.proc.send_signal(signal.SIGTERM)
        except OSError:
            return False
        if h.notice_t is None:
            h.notice_t = self.clock()
            self._m_preempt_notices.inc()
            self._event("preempt_notice", process_id, source="launcher",
                        incarnation=h.incarnation)
        return True

    def preempt_all(self) -> int:
        """Forward a preemption notice to every running worker (the
        launcher's own SIGTERM handler calls this: pod-level preemption
        notices cascade down as worker notices).  → count notified."""
        return sum(1 for h in self.handles
                   if self.preempt_worker(h.process_id))

    def _on_sigterm(self, signum, frame) -> None:
        # the launcher itself was told to go away: cascade the notice and
        # stop healing — workers get their grace window, nobody relaunches
        self._shutting_down = True

    def _install_sigterm(self) -> None:
        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM,
                                               self._on_sigterm)
        except ValueError:   # not the main thread (tests drive run() from
            self._prev_sigterm = None        # a helper thread) — skip

    def _restore_sigterm(self) -> None:
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None

    def shutdown_gracefully(self) -> None:
        """Programmatic equivalent of SIGTERMing the launcher: notify
        every worker and let the monitor loop drain them within grace."""
        self._shutting_down = True

    def _reap_all(self) -> int:
        """Kill anything still alive and count it; then verify every pid
        this launcher EVER spawned is gone — the no-orphans contract."""
        leaked = 0
        for h in self.handles:
            if h.proc is not None and h.proc.poll() is None:
                leaked += 1
                try:
                    h.proc.kill()
                    h.proc.wait(timeout=10)
                except (OSError, subprocess.TimeoutExpired):
                    pass
            self._close_log(h)
        for h in self.handles:
            for pid in h.spawned_pids:
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    continue           # gone, as it should be
                except PermissionError:
                    pass               # exists under another uid — not ours
                else:
                    # still alive (a double-fork would land here) — last
                    # resort, then recheck
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except OSError:
                        pass
                    leaked += 1
        return leaked

    # -- pod-level telemetry -----------------------------------------------

    def pod_metrics(self) -> dict:
        """Aggregate the per-worker registry snapshots (written by each
        worker's Heartbeat into run_dir/obs/) plus this launcher's own
        registry into ONE pod-level view: counters summed, histogram
        buckets added, gauges min/mean/max across workers — the
        pod-scale ``/metrics`` answer."""
        workers: Dict[str, dict] = {}
        obs_dir = os.path.join(self.run_dir, "obs")
        try:
            names = sorted(os.listdir(obs_dir))
        except OSError:
            names = []
        for fn in names:
            if not (fn.startswith("metrics_w") and fn.endswith(".json")):
                continue
            try:
                with open(os.path.join(obs_dir, fn)) as f:
                    workers[fn[len("metrics_"):-len(".json")]] = json.load(f)
            except (OSError, ValueError):
                continue   # torn write — the next beat replaces it
        return {"workers": workers,
                "launcher": get_registry().snapshot(),
                "aggregate": merge_snapshots(list(workers.values()))}

    def merge_trace(self, out_path: str) -> Optional[dict]:
        """Stitch every per-worker (and per-incarnation) trace file under
        ``trace_dir`` — plus the launcher's own events, flushed here —
        into one pod timeline at ``out_path``; None when tracing was not
        armed or no worker wrote a trace."""
        if not self.trace_dir:
            return None
        rec = obs_trace.get_recorder()
        if rec is not None:
            rec.save(os.path.join(self.trace_dir, "launcher.trace.json"))
        try:
            names = sorted(os.listdir(self.trace_dir))
        except OSError:
            return None
        paths = [os.path.join(self.trace_dir, fn) for fn in names
                 if fn.endswith(".trace.json")
                 and not fn.endswith("pod.trace.json")]
        if not paths:
            return None
        return obs_trace.merge_traces(paths, out_path)

    def run(self) -> dict:
        """Launch the fleet, heal it until every worker completes (or its
        budget/deadline runs out), and return the run report."""
        self._t0 = self.clock()
        os.makedirs(self.run_dir, exist_ok=True)
        self._install_sigterm()
        for h in self.handles:
            self._spawn(h)
        deadline_hit = False
        leaked = 0
        try:
            while self._running():
                time.sleep(self.poll_interval)
                if self._shutting_down and not self._shutdown_forwarded:
                    self._shutdown_forwarded = True
                    self._event("shutdown",
                                notified=self.preempt_all())
                self.membership.refresh()
                self._poll_once()
                if self.clock() - self._t0 > self.deadline_s:
                    deadline_hit = True
                    for h in self.handles:
                        if h.state == "running":
                            h.state = "unrecovered"
                            self._event("unrecovered", h.process_id,
                                        cause="deadline",
                                        log_tail=self._log_tail(h))
                    break
            self.membership.refresh()
        finally:
            leaked = self._reap_all()
            self._restore_sigterm()
        completed = [h.process_id for h in self.handles
                     if h.state == "completed"]
        unrecovered = [h.process_id for h in self.handles
                       if h.state == "unrecovered"]
        report = {
            "workers": self.num_workers,
            "completed": completed,
            "unrecovered": unrecovered,
            "restarts": sum(h.restarts for h in self.handles),
            "budget_used": {h.process_id: h.restarts
                            for h in self.handles},
            "planned_leaves": sum(h.planned_leaves for h in self.handles),
            "preempt_notices": sum(1 for e in self.events
                                   if e["kind"] == "preempt_notice"),
            "grace_escalations": sum(1 for e in self.events
                                     if e["kind"] == "grace_expired"),
            "stragglers": [e for e in self.events
                           if e["kind"] == "straggler"],
            "leaves": [e for e in self.events if e["kind"] == "leave"],
            "joins": sum(1 for e in self.events if e["kind"] == "join"),
            "hang_detected": sum(1 for e in self.events
                                 if e["kind"] == "hang_detected"),
            "epoch": self.membership.epoch,
            "alive": self.membership.alive(),
            "leaving": sorted(self.membership.leaving()),
            "last_checkpoint_step": self.membership.last_checkpoint_step(),
            "deadline_hit": deadline_hit,
            "leaked_killed": leaked,
            "wall_seconds": round(self.clock() - self._t0, 2),
            "events": self.events,
        }
        report["ok"] = (not unrecovered and not deadline_hit
                        and leaked == 0)
        report["pod_metrics"] = self.pod_metrics()
        return report
