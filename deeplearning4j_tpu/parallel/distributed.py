"""Multi-host initialization — the jax.distributed launcher story.

Parity target: the reference's cluster entry points (dl4j-spark
SharedTrainingMaster / ParameterAveragingTrainingMaster setup,
VoidConfiguration ports/controller address).  On TPU pods the equivalent
ceremony is tiny: every host runs the SAME program, calls
``initialize()`` (auto-detecting the coordinator on Cloud TPU, explicit
coordinator address elsewhere), and then ``build_mesh`` sees the GLOBAL
device set — the existing ShardedTrainer/pipeline/ring code is multi-host
already because GSPMD collectives span hosts transparently (ICI within a
slice, DCN across slices).

There is no Spark-style driver: data loading is per-host (each host feeds
its local shard of the global batch via ``process_index``), which is the
reference's SharedTraining data-locality model without the Aeron plumbing.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax

logger = logging.getLogger("deeplearning4j_tpu")


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join (or form) the multi-host runtime.

    On Cloud TPU pods all arguments auto-detect (metadata server); on other
    clusters pass ``coordinator_address='host:port'``, ``num_processes``
    and this host's ``process_id`` — the direct analog of the reference's
    VoidConfiguration controller address + shard index."""
    if num_processes is not None and process_id is not None:
        if not (0 <= process_id < num_processes):
            raise ValueError(f"process_id {process_id} out of range "
                             f"[0, {num_processes})")
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    logger.info("distributed initialized: process %d/%d, %d local / %d "
                "global devices", jax.process_index(), jax.process_count(),
                jax.local_device_count(), jax.device_count())


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def local_batch_slice(global_batch: int) -> slice:
    """This host's slice of a globally-indexed batch: hosts feed disjoint
    shards of the global batch (per-host data loading, reference
    SharedTraining locality model)."""
    n = jax.process_count()
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"{n} processes")
    per = global_batch // n
    i = jax.process_index()
    return slice(i * per, (i + 1) * per)


def detect_num_slices() -> int:
    """Number of DCN-connected TPU slices this job spans (1 when the whole
    job is ICI-connected).

    Reads ``MEGASCALE_NUM_SLICES`` — the Cloud TPU multislice runtime's
    env contract (every worker of a multislice job gets it) — and falls
    back to distinct ``device.slice_index`` values when the backend
    exposes them.  Use it to size the ``dcn`` axis:

        mesh = build_two_tier_mesh(detect_num_slices())
        trainer = ShardedTrainer(net, mesh, grad_compression="threshold")

    Multi-HOST but single-slice jobs correctly report 1: cross-host
    traffic within a slice is still ICI, where the dense exchange is the
    right call (see parallel/__init__ docstring)."""
    import os
    env = os.environ.get("MEGASCALE_NUM_SLICES")
    if env:
        return max(1, int(env))
    slice_ids = {getattr(d, "slice_index", 0) for d in jax.devices()}
    return max(1, len(slice_ids))


def is_coordinator() -> bool:
    """True on process 0 — gate checkpoint writes / logging / UI servers
    the way the reference gates them on the Spark driver."""
    return jax.process_index() == 0
