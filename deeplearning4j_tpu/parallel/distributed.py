"""Multi-host initialization — the jax.distributed launcher story.

Parity target: the reference's cluster entry points (dl4j-spark
SharedTrainingMaster / ParameterAveragingTrainingMaster setup,
VoidConfiguration ports/controller address).  On TPU pods the equivalent
ceremony is tiny: every host runs the SAME program, calls
``initialize()`` (auto-detecting the coordinator on Cloud TPU, explicit
coordinator address elsewhere), and then ``build_mesh`` sees the GLOBAL
device set — the existing ShardedTrainer/pipeline/ring code is multi-host
already because GSPMD collectives span hosts transparently (ICI within a
slice, DCN across slices).

There is no Spark-style driver: data loading is per-host (each host feeds
its local shard of the global batch via ``process_index``), which is the
reference's SharedTraining data-locality model without the Aeron plumbing.
"""

from __future__ import annotations

import logging
import os
import socket
import time
from typing import Optional, Tuple

import jax

logger = logging.getLogger("deeplearning4j_tpu")

#: env contract between the ``launch`` subcommand and its workers — the
#: launcher sets these; ``resolve_process_index`` / ``CheckpointManager``
#: / ``Heartbeat.start_from_env`` read them without needing jax.distributed
ENV_PROCESS_ID = "DL4J_TPU_PROCESS_ID"
ENV_NUM_PROCESSES = "DL4J_TPU_NUM_PROCESSES"
ENV_RUN_DIR = "DL4J_TPU_RUN_DIR"
ENV_COORDINATOR = "DL4J_TPU_COORDINATOR"
ENV_CHAOS = "DL4J_TPU_CHAOS"
ENV_INCARNATION = "DL4J_TPU_INCARNATION"
ENV_CONNECT_TIMEOUT = "DL4J_TPU_CONNECT_TIMEOUT"
#: directory each worker writes its Chrome trace file into (set by
#: ``launch --trace``; workers name files worker{i}.inc{j}.trace.json and
#: the launcher merges them into one pod timeline — obs/trace.py)
ENV_TRACE_DIR = "DL4J_TPU_TRACE_DIR"
#: preemption grace budget in seconds: how long a worker has between a
#: preemption notice (SIGTERM/SIGUSR1) and the host going away — the
#: emergency-checkpoint deadline (parallel/preemption.py)
ENV_GRACE_S = "DL4J_TPU_GRACE_S"
#: comma-separated coordinator-capable port per process id, so a worker
#: that finds the coordinator dead can re-``initialize`` against the
#: survivor with the lowest alive id from the membership ledger instead
#: of dying on CoordinatorUnreachableError (launcher.elect_coordinator)
ENV_COORD_PORTS = "DL4J_TPU_COORD_PORTS"
#: HTTP serving port assigned to this worker by the launcher when it was
#: started with ``--serve`` — ``cmd_serve`` binds its UIServer here (and
#: a fleet router finds every host at the launcher's serve_endpoints())
ENV_SERVE_PORT = "DL4J_TPU_SERVE_PORT"

#: distinct exit code for a PLANNED leave: the worker received a
#: preemption notice, wrote its emergency checkpoint, and exited on
#: purpose — the launcher relaunches it WITHOUT consuming the per-worker
#: restart budget (75 = BSD EX_TEMPFAIL: "temporary failure, retry").
PREEMPTED_EXIT_CODE = 75


class CoordinatorUnreachableError(ConnectionError):
    """``initialize()`` could not reach the coordinator within its bounded
    connect budget — the address is wrong, the coordinator process died,
    or the network path is down.  Raised INSTEAD of the indefinite hang
    jax's barrier would otherwise sit in, so launchers/restart loops can
    back off and retry (or re-elect) deterministically."""


def validate_coordinator_address(address: str) -> Tuple[str, int]:
    """'host:port' → (host, port), with every malformed shape rejected up
    front as ValueError (the failure would otherwise surface minutes later
    as an opaque RPC timeout inside the barrier)."""
    if not isinstance(address, str) or ":" not in address:
        raise ValueError(f"coordinator_address must be 'host:port', got "
                         f"{address!r}")
    host, _, port_s = address.rpartition(":")
    host = host.strip("[]")  # [v6::addr]:port
    if not host:
        raise ValueError(f"coordinator_address {address!r} has no host")
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(f"coordinator_address {address!r} has a non-integer "
                         f"port {port_s!r}")
    if not (0 < port < 65536):
        raise ValueError(f"coordinator_address {address!r} port out of range "
                         f"(1-65535)")
    return host, port


def _probe_coordinator(host: str, port: int, timeout_s: float) -> None:
    """Bounded TCP connect-with-retry to the coordinator before handing
    control to jax's barrier.  jax.distributed's own connect loop blocks
    with a very coarse deadline (and some jaxlib builds hang outright on a
    dead coordinator); a plain socket probe gives a crisp, configurable
    failure in seconds."""
    deadline = time.monotonic() + timeout_s
    delay = 0.1
    last_err: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            with socket.create_connection(
                    (host, port),
                    timeout=max(0.1, min(2.0, deadline - time.monotonic()))):
                return
        except OSError as exc:
            last_err = exc
            time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
            delay = min(1.0, delay * 2)
    raise CoordinatorUnreachableError(
        f"coordinator {host}:{port} unreachable after {timeout_s:.1f}s "
        f"of connect retries (last error: {last_err})")


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               timeout_s: Optional[float] = None,
               local_device_ids=None) -> None:
    """Join (or form) the multi-host runtime.

    On Cloud TPU pods all arguments auto-detect (metadata server); on other
    clusters pass ``coordinator_address='host:port'``, ``num_processes``
    and this host's ``process_id`` — the direct analog of the reference's
    VoidConfiguration controller address + shard index.

    ``timeout_s`` bounds the coordinator bootstrap: non-coordinator
    processes first TCP-probe the address with retries, and the barrier
    itself runs under jax's ``initialization_timeout`` — a dead or wrong
    coordinator raises :class:`CoordinatorUnreachableError` within the
    budget instead of hanging the worker forever (default 60s)."""
    if num_processes is not None and process_id is not None:
        if not (0 <= process_id < num_processes):
            raise ValueError(f"process_id {process_id} out of range "
                             f"[0, {num_processes})")
    host = port = None
    if coordinator_address is not None:
        host, port = validate_coordinator_address(coordinator_address)
    timeout_s = 60.0 if timeout_s is None else float(timeout_s)
    if timeout_s <= 0:
        raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
    if host is not None and process_id not in (None, 0):
        # process 0 HOSTS the coordinator service — only joiners probe
        _probe_coordinator(host, port, timeout_s)
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
            initialization_timeout=max(1, int(timeout_s)))
    except CoordinatorUnreachableError:
        raise
    except Exception as exc:
        text = f"{type(exc).__name__}: {exc}"
        if any(m in text for m in ("DEADLINE_EXCEEDED", "UNAVAILABLE",
                                   "Connection", "connect", "timed out",
                                   "Barrier timed out")):
            raise CoordinatorUnreachableError(
                f"coordinator bootstrap at {coordinator_address} failed "
                f"within {timeout_s:.1f}s: {text}") from exc
        raise
    logger.info("distributed initialized: process %d/%d, %d local / %d "
                "global devices", jax.process_index(), jax.process_count(),
                jax.local_device_count(), jax.device_count())


def reinitialize(coordinator_address: str,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None,
                 timeout_s: Optional[float] = None) -> None:
    """Tear down this process's distributed runtime and rejoin — the
    coordinator-restart path: after the coordinator process is relaunched
    (same address) or a survivor was elected to host a new one
    (``launcher.elect_coordinator``), workers call this instead of
    treating :class:`CoordinatorUnreachableError` as terminal.  The
    shutdown is best-effort (a worker whose runtime already collapsed
    with the coordinator just re-initializes)."""
    try:
        jax.distributed.shutdown()
    except Exception as exc:   # not initialized / already torn down
        logger.debug("distributed shutdown before rejoin: %s", exc)
    initialize(coordinator_address, num_processes, process_id,
               timeout_s=timeout_s)


def resolve_process_index(explicit: Optional[int] = None) -> int:
    """This host's process index WITHOUT requiring jax.distributed: an
    explicit value wins, then the launcher's ``DL4J_TPU_PROCESS_ID`` env
    (set for every forked worker), then ``jax.process_index()`` (1-process
    default 0).  Lets host-role decisions (who writes checkpoints, who
    serves the UI) work identically under the launcher's replica mode,
    real jax.distributed pods, and plain single-process runs."""
    if explicit is not None:
        return int(explicit)
    env = os.environ.get(ENV_PROCESS_ID)
    if env is not None:
        try:
            return int(env)
        except ValueError:
            raise ValueError(f"{ENV_PROCESS_ID}={env!r} is not an integer")
    try:
        return jax.process_index()
    except Exception:   # backend not initializable here — single process
        return 0


_MP_SUPPORT: Optional[Tuple[bool, str]] = None


def probe_multiprocess_support(timeout_s: float = 120.0) -> Tuple[bool, str]:
    """(supported, reason): can THIS jaxlib run cross-process collectives?

    Spawns two 1-device subprocesses that form a jax.distributed cluster
    on localhost and psum across the process boundary.  Some jaxlib CPU
    clients lack multiprocess execution entirely ("...aren't implemented
    on the CPU backend") — an environment capability, not a framework
    property, so tests probe it ONCE (cached) and skip only the cases
    that genuinely need cross-process collectives; launcher/membership
    logic runs everywhere."""
    global _MP_SUPPORT
    if _MP_SUPPORT is not None:
        return _MP_SUPPORT
    import subprocess
    import sys
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = (
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "os.environ.pop('XLA_FLAGS', None)\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        f"jax.distributed.initialize('127.0.0.1:{port}', 2, "
        "int(sys.argv[1]), initialization_timeout=60)\n"
        "import jax.numpy as jnp\n"
        "out = jax.pmap(lambda x: jax.lax.psum(x, 'i'), axis_name='i')(\n"
        "    jnp.ones((jax.local_device_count(),)))\n"
        "assert float(out[0]) == jax.device_count(), out\n")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen([sys.executable, "-c", script, str(i)],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE) for i in range(2)]
    ok, reason = True, ""
    for p in procs:
        try:
            _, err = p.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            ok, reason = False, "multiprocess probe timed out"
            break
        if p.returncode != 0:
            ok = False
            if b"aren't implemented on the CPU backend" in err:
                reason = "jaxlib CPU backend lacks multiprocess execution"
            else:
                reason = (f"probe worker rc={p.returncode}: "
                          f"{err.decode(errors='replace')[-400:]}")
            break
    _MP_SUPPORT = (ok, reason)
    logger.info("multiprocess backend probe: supported=%s %s", ok, reason)
    return _MP_SUPPORT


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def local_batch_slice(global_batch: int) -> slice:
    """This host's slice of a globally-indexed batch: hosts feed disjoint
    shards of the global batch (per-host data loading, reference
    SharedTraining locality model)."""
    n = jax.process_count()
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"{n} processes")
    per = global_batch // n
    i = jax.process_index()
    return slice(i * per, (i + 1) * per)


def detect_num_slices() -> int:
    """Number of DCN-connected TPU slices this job spans (1 when the whole
    job is ICI-connected).

    Reads ``MEGASCALE_NUM_SLICES`` — the Cloud TPU multislice runtime's
    env contract (every worker of a multislice job gets it) — and falls
    back to distinct ``device.slice_index`` values when the backend
    exposes them.  Use it to size the ``dcn`` axis:

        mesh = build_two_tier_mesh(detect_num_slices())
        trainer = ShardedTrainer(net, mesh, grad_compression="threshold")

    Multi-HOST but single-slice jobs correctly report 1: cross-host
    traffic within a slice is still ICI, where the dense exchange is the
    right call (see parallel/__init__ docstring)."""
    import os
    env = os.environ.get("MEGASCALE_NUM_SLICES")
    if env:
        return max(1, int(env))
    slice_ids = {getattr(d, "slice_index", 0) for d in jax.devices()}
    return max(1, len(slice_ids))


def is_coordinator() -> bool:
    """True on process 0 — gate checkpoint writes / logging / UI servers
    the way the reference gates them on the Spark driver."""
    return jax.process_index() == 0
