"""ShardedTrainer — DP/TP training over a mesh.

The reference's ParallelWrapper (parallelism/ParallelWrapper.java:58: clone
model per device, round-robin DataSets into per-worker queues, average
params every ``averagingFrequency`` iterations via Nd4j.averageAndPropagate
:326) collapses into: put params on the mesh with TP shardings, put the
batch on the data axis, call the SAME jit step the single-device path uses.
GSPMD partitions the program; gradient allreduce appears as a fused psum
over ICI (the Aeron/NCCL role).  Per-step summation ≡ averagingFrequency=1,
mathematically stronger than the reference's periodic averaging.

Multi-host: call jax.distributed.initialize() first (the Spark master's
process-placement role is played by the launcher — GKE/Ray/mpirun), then
build the mesh over jax.devices() spanning all hosts.

Two-tier gradient exchange: when the mesh carries a ``dcn`` axis (slices
joined by data-center network rather than ICI), ``grad_compression=``
swaps the cross-slice tier of the gradient allreduce for the reference's
compressed protocol — EncodingHandler thresholdEncode/bitmapEncode with a
per-slice error-feedback residual (ops/compression.py).  The step becomes
an explicit shard_map: per-device grads → dense psum over the ICI
``data`` axis (tier 1, unchanged math) → bucketed encode + all_gather of
the ENCODED buffers over ``dcn`` + decode-sum (tier 2) → optimizer
update.  ``grad_compression=None`` keeps the original GSPMD path
bit-for-bit.
"""

from __future__ import annotations

from typing import List, Optional, TYPE_CHECKING

if TYPE_CHECKING:
    from ..optimize.score import LazyScore

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..datasets.dataset import DataSet
from ..obs import trace as obs_trace
from ..utils.jax_compat import set_mesh, shard_map
from ..datasets.iterators import DataSetIterator
from .mesh import (
    DATA_AXIS, DCN_AXIS, MODEL_AXIS, build_mesh, build_two_tier_mesh,
    infer_param_shardings, put_global, replicated,
)


class ShardedTrainer:
    """Wraps a MultiLayerNetwork or ComputationGraph for mesh training.

    >>> mesh = build_mesh({"data": 4, "model": 2})
    >>> trainer = ShardedTrainer(net, mesh)
    >>> trainer.fit(iterator, epochs=2)

    The wrapped net keeps working as usual afterwards; its params simply
    live sharded on the mesh.
    """

    @classmethod
    def two_tier(cls, net, n_slices: Optional[int] = None,
                 axes: Optional[dict] = None, **kwargs) -> "ShardedTrainer":
        """The pod-launch ceremony in one line: a trainer over
        ``build_two_tier_mesh`` sized by the multislice runtime.

        ``n_slices`` defaults to ``distributed.detect_num_slices()`` —
        the MEGASCALE env contract every worker of a Cloud TPU multislice
        job carries (the ``launch`` subcommand propagates it to forked
        workers in distributed mode) — so the same program runs 1-slice
        and N-slice unchanged:

            distributed.initialize(...)            # or `launch --join`
            trainer = ShardedTrainer.two_tier(
                net, grad_compression="threshold")

        All ShardedTrainer kwargs pass through (pair with
        ``grad_compression=`` to compress the cross-slice tier)."""
        if n_slices is None:
            from .distributed import detect_num_slices
            n_slices = detect_num_slices()
        return cls(net, build_two_tier_mesh(n_slices, axes), **kwargs)

    def __init__(self, net, mesh: Optional[Mesh] = None,
                 data_axis: str = DATA_AXIS, model_axis: str = MODEL_AXIS,
                 pipeline_schedule: str = "gpipe",
                 grad_compression: Optional[str] = None,
                 dcn_axis: str = DCN_AXIS,
                 compression_threshold: Optional[float] = None,
                 compression_bucket_mb: float = 4.0,
                 nan_guard: Optional[int] = None):
        from .pipeline import SCHEDULES
        from ..ops import compression as _compression
        if pipeline_schedule not in SCHEDULES:
            raise ValueError(f"pipeline_schedule must be one of {SCHEDULES}, "
                             f"got {pipeline_schedule!r}")
        if grad_compression is not None \
                and grad_compression not in _compression.METHODS:
            raise ValueError(
                f"grad_compression must be one of {_compression.METHODS} or "
                f"None, got {grad_compression!r}")
        self.net = net
        self.mesh = mesh if mesh is not None else build_mesh()
        self.data_axis = data_axis
        self.model_axis = model_axis
        self.dcn_axis = dcn_axis
        # DCN-tier compressed exchange (reference EncodingHandler behind
        # SharedTrainingMaster): None = dense GSPMD psum everywhere (the
        # original path, bit-identical); "threshold"/"bitmap" = dense psum
        # over the ICI data axis + compressed exchange over the dcn axis
        # with per-slice error-feedback residuals
        self.grad_compression = grad_compression
        self.compression_threshold = compression_threshold
        self.compression_bucket_bytes = max(4, int(compression_bucket_mb
                                                   * (1 << 20)))
        self._compressed_step = None
        if grad_compression is not None:
            if dcn_axis not in self.mesh.shape:
                raise ValueError(
                    f"grad_compression={grad_compression!r} needs a "
                    f"{dcn_axis!r} mesh axis (build_two_tier_mesh) — got "
                    f"axes {dict(self.mesh.shape)}")
            for ax, size in self.mesh.shape.items():
                if ax not in (dcn_axis, data_axis) and size > 1:
                    raise ValueError(
                        f"grad_compression composes with dcn×data parallelism "
                        f"only (axis {ax!r} has size {size}); drop the axis "
                        "or run grad_compression=None")
        # divergence guard (opt-in; None = the exact pre-guard programs):
        # dense path rides the net's own guarded step; the compressed path
        # builds its guard into the two-tier shard_map step so a skipped
        # step ALSO skips residual accumulation — otherwise the error-
        # feedback state would absorb the poisoned gradient and replay it
        # on the next (healthy) step
        self.nan_guard = nan_guard
        self._bad_steps = 0
        if nan_guard is not None:
            if grad_compression is None:
                if not hasattr(net, "set_nan_guard"):
                    raise NotImplementedError(
                        f"nan_guard is not supported for "
                        f"{type(net).__name__} yet (needs set_nan_guard)")
                net.set_nan_guard(nan_guard)
        # microbatch order for nets that pipeline over a `pipe` axis
        # (parallel/pipeline.py): forwarded to the wrapped net when it
        # carries a schedule knob (ShardedTransformerLM); layer-stack nets
        # without a pipe dimension ignore it
        self.pipeline_schedule = pipeline_schedule
        if hasattr(net, "schedule"):
            net.schedule = pipeline_schedule
        # any dcn axis present ⇒ the batch spans both DP tiers, so dense
        # (GSPMD) and compressed runs shard identically and differ only in
        # how the gradient crosses the slow tier
        if dcn_axis in self.mesh.shape:
            self.batch_sharding = NamedSharding(
                self.mesh, P((dcn_axis, data_axis)))
        else:
            self.batch_sharding = NamedSharding(self.mesh, P(data_axis))
        self._place_model()

    # -- placement ---------------------------------------------------------

    def _place_model(self) -> None:
        """Move params/opt-state onto the mesh (TP rules), replicate state."""
        net = self.net
        self.param_shardings = infer_param_shardings(net.params, self.mesh, self.model_axis)
        net.params = jax.tree_util.tree_map(put_global, net.params,
                                            self.param_shardings)
        # opt state mirrors param shapes (Adam m/v etc.) → same shardings
        net.opt_state = self._put_like_params(net.opt_state)
        rep = replicated(self.mesh)
        net.state = jax.tree_util.tree_map(lambda a: put_global(a, rep),
                                           net.state)
        # ephemeral device scalars (rng key, device iteration counter) may
        # be committed to a PREVIOUS mesh (elastic resize) — pull to host
        # and let the next step recommit them under this mesh
        if getattr(net, "_rng", None) is not None:
            net._rng = jnp.asarray(np.asarray(net._rng))
        if getattr(net, "_it_dev", None) is not None:
            net._it_dev = None
        if self.grad_compression is not None:
            self._place_residual()

    def _place_residual(self) -> None:
        """Error-feedback residual: one params-shaped f32 tree PER SLICE
        (leading axis = dcn size, sharded on the dcn axis, replicated
        within the slice).  Adopts a residual already on the net — a
        checkpoint restore (utils/serializer.py format v3) or an elastic
        re-place — when its slice count still matches; otherwise starts
        from zeros (mathematically safe: error feedback only defers
        compression error, dropping it costs one step's deferral)."""
        net = self.net
        n_dcn = self.mesh.shape[self.dcn_axis]
        spec = NamedSharding(self.mesh, P(self.dcn_axis))
        existing = getattr(net, "grad_residual", None)
        leaves = jax.tree_util.tree_leaves(existing)
        if leaves and all(l.shape[0] == n_dcn for l in leaves):
            net.grad_residual = jax.tree_util.tree_map(
                lambda a: put_global(np.asarray(a, np.float32), spec),
                existing)
        else:
            net.grad_residual = jax.tree_util.tree_map(
                lambda p: put_global(
                    np.zeros((n_dcn,) + tuple(p.shape), np.float32), spec),
                net.params)

    def _put_like_params(self, opt_state):
        """Shard optimizer state structurally: per layer, each state subtree
        whose pytree structure matches the layer's params (Adam m/v,
        Nesterovs momentum, ...) gets the params' shardings leaf-for-leaf;
        anything else (scalars, mismatched trees) is replicated.  Structural
        mapping — never keyed by leaf shape — so per-layer sharding
        overrides can't silently leak across same-shaped layers."""
        rep = replicated(self.mesh)

        def place_layer(os_layer, p_layer, s_layer):
            if not os_layer:
                return os_layer
            p_struct = jax.tree_util.tree_structure(p_layer)

            def place_sub(sub):
                if jax.tree_util.tree_structure(sub) == p_struct:
                    return jax.tree_util.tree_map(put_global, sub, s_layer)
                return jax.tree_util.tree_map(
                    lambda a: put_global(a, rep), sub)

            return {k: place_sub(v) for k, v in os_layer.items()}

        params, shardings = self.net.params, self.param_shardings
        if isinstance(opt_state, list):
            return [place_layer(os, p, s)
                    for os, p, s in zip(opt_state, params, shardings)]
        return {k: place_layer(v, params[k], shardings[k])
                for k, v in opt_state.items()}

    # -- batch placement ---------------------------------------------------

    @staticmethod
    def _to_host_array(a):
        """Zero-copy host view: a numpy array passes through IDENTICALLY
        (``np.asarray`` on an ndarray subclass or list would materialize a
        fresh buffer — a redundant host copy of the whole batch, paid
        every step before the real H2D transfer)."""
        return a if type(a) is np.ndarray else np.asarray(a)

    def _shard_batch_arr(self, a):
        if a is None:
            return None
        if isinstance(a, jax.Array):
            # already on device: re-place only if the sharding differs —
            # never round-trip through host (a 224² imagenet batch is ~77MB;
            # re-uploading it every step would dominate the step time).
            # DevicePrefetchIterator batches placed with this trainer's
            # ``batch_sharding`` hit the pass-through.
            if a.sharding.is_equivalent_to(self.batch_sharding, a.ndim):
                return a
            return jax.device_put(a, self.batch_sharding)
        arr = self._to_host_array(a)
        dp = self.mesh.shape.get(self.data_axis, 1) \
            * self.mesh.shape.get(self.dcn_axis, 1)
        if arr.shape[0] % dp != 0:
            raise ValueError(
                f"global batch {arr.shape[0]} not divisible by data axis {dp} "
                "(pad or drop the remainder — XLA needs static shapes)")
        return put_global(arr, self.batch_sharding)

    def shard_dataset(self, ds: DataSet) -> DataSet:
        """Pre-place a batch on the mesh (public so callers that reuse a
        batch — benchmarks, eval loops — pay the host→device transfer
        once, not per step)."""
        return DataSet(
            self._shard_batch_arr(ds.features),
            None if ds.labels is None else jax.tree_util.tree_map(self._shard_batch_arr, ds.labels),
            self._shard_batch_arr(ds.features_mask),
            self._shard_batch_arr(ds.labels_mask),
        )


    # -- compressed two-tier step ------------------------------------------

    def _make_compressed_step(self):
        """Build the explicit two-tier train step (shard_map over dcn×data).

        The dense path lets GSPMD insert ONE psum spanning every DP axis;
        here the collective is split by tier: per-device grads are psum'd
        densely over the ICI ``data`` axis (tier 1 — same math XLA would
        emit), then each slice adds its error-feedback residual, encodes
        per bucket, and all_gathers only the ENCODED buffers over ``dcn``
        (tier 2).  Buckets are independent collectives, so XLA's
        latency-hiding scheduler overlaps bucket k's exchange with bucket
        k+1's encode/decode and the update math.  The decoded mean feeds
        the net's own ``_apply_updates`` — updater math, normalization
        and constraints are untouched."""
        from ..ops import compression as C

        net, mesh = self.net, self.mesh
        dcn, data = self.dcn_axis, self.data_axis
        n_data = mesh.shape.get(data, 1)
        method, thr = self.grad_compression, self.compression_threshold
        bucketer = C.GradBucketer(net.params, self.compression_bucket_bytes)
        is_graph = isinstance(net.params, dict)
        guard = self.nan_guard is not None

        def device_step(params, state, opt_state, it, x, y, rng, m, lm,
                        residual):
            # decorrelate per-device stochasticity (dropout/noise) the way
            # independent workers would; deterministic nets are unaffected
            di = jax.lax.axis_index(dcn) * n_data + jax.lax.axis_index(data)
            key = jax.random.fold_in(rng, di)

            def loss_fn(p):
                if is_graph:
                    return net._loss(p, state, x, y, train=True, rng=key,
                                     masks=m, label_masks=lm)
                return net._loss(p, state, x, y, train=True, rng=key,
                                 mask=m, label_mask=lm)

            (loss, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            # tier 1: dense ICI allreduce — free at ICI bandwidth
            grads = jax.lax.pmean(grads, data)
            if guard:
                # divergence guard: decided BEFORE the compressed exchange
                # and agreed GLOBALLY (pmin over both DP tiers) — one
                # slice skipping while another applies would fork the
                # replicated params across slices
                ok = jnp.isfinite(loss)
                for g in jax.tree_util.tree_leaves(grads):
                    ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g)))
                ok = jax.lax.pmin(ok.astype(jnp.int32), (data, dcn)) > 0
            # tier 2: bucketed compressed DCN exchange with error feedback.
            # acc = slice gradient + what previous steps failed to send;
            # the un-transmitted part of acc becomes the next residual —
            # compression error is deferred, never dropped (the
            # reference's residual accumulator, the property the
            # convergence-parity tests pin).
            res = jax.tree_util.tree_map(lambda a: a[0], residual)
            out_g, out_r = [], []
            for gb, rb in zip(bucketer.flatten(grads), bucketer.flatten(res)):
                acc = gb + rb
                mean_dec, local_dec = C.compressed_pmean(
                    acc, dcn, method, threshold=thr)
                out_g.append(mean_dec)
                out_r.append(acc - local_dec)
            grads = bucketer.unflatten(out_g)
            new_res = bucketer.unflatten(out_r, cast=False)
            new_params, new_opt = net._apply_updates(
                grads, params, opt_state, it.astype(jnp.float32))
            # keep replicated things replicated: batch-dependent state (BN
            # running stats) is averaged across every DP shard; loss is
            # reported as the global-batch mean
            new_state = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, (data, dcn))
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.inexact) else a,
                new_state)
            loss = jax.lax.pmean(jax.lax.pmean(loss, data), dcn)
            if guard:
                # skip the WHOLE step on a non-finite gradient: params,
                # opt state, bn state, AND the error-feedback residual
                # stay bit-identical (the residual otherwise absorbs the
                # poisoned acc and re-injects it next step)
                sel = lambda n, o: jax.tree_util.tree_map(  # noqa: E731
                    lambda a, b: jnp.where(ok, a, b), n, o)
                new_params = sel(new_params, params)
                new_state = sel(new_state, state)
                new_opt = sel(new_opt, opt_state)
                new_res = sel(new_res, res)
                new_res = jax.tree_util.tree_map(lambda a: a[None], new_res)
                return (new_params, new_state, new_opt, new_res, loss,
                        ok.astype(jnp.int32))
            new_res = jax.tree_util.tree_map(lambda a: a[None], new_res)
            return new_params, new_state, new_opt, new_res, loss

        pb = P((dcn, data))
        out_specs = (P(), P(), P(), P(dcn), P()) + ((P(),) if guard else ())
        stepped = shard_map(
            device_step, mesh=mesh,
            in_specs=(P(), P(), P(), P(), pb, pb, P(), pb, pb, P(dcn)),
            out_specs=out_specs, check_vma=False)
        return jax.jit(stepped, donate_argnums=(0, 1, 2, 9))

    def _fit_batch_compressed(self, ds: DataSet):
        from ..optimize.score import LazyScore
        net = self.net
        if getattr(net.conf, "backprop_type", "standard") == "tbptt":
            raise NotImplementedError(
                "grad_compression does not compose with TBPTT yet — the "
                "chunk scan applies updates inside the step; run "
                "grad_compression=None")
        with set_mesh(self.mesh):
            ds = self.shard_dataset(ds)
            if self._compressed_step is None:
                self._compressed_step = self._make_compressed_step()
            net._rng, sub = jax.random.split(net._rng)
            x, y = ds.features, ds.labels
            m, lm = ds.features_mask, ds.labels_mask
            if isinstance(net.params, dict):  # ComputationGraph calling
                x = {net.conf.network_inputs[0]: x}
                y = {net.conf.network_outputs[0]: y}
                m = {net.conf.network_inputs[0]: m}
                lm = {net.conf.network_outputs[0]: lm}
            # one span for the fused step: the two-tier grad exchange
            # (dense ICI psum + compressed DCN) runs INSIDE this program,
            # so the host-side span is the whole dispatch — use the XLA
            # profiler (ui/profiler.py) for the on-device breakdown
            with obs_trace.span("train/step", cat="train",
                                iteration=net.iteration + 1,
                                path="compressed_exchange"):
                with obs_trace.span("train/dispatch", cat="train"):
                    outs = self._compressed_step(
                        net.params, net.state, net.opt_state,
                        net._iter_scalar(1), x, y, sub, m, lm,
                        net.grad_residual)
            (net.params, net.state, net.opt_state, net.grad_residual,
             loss) = outs[:5]
            net.iteration += 1
            if self.nan_guard is not None:
                self._note_guarded_step(bool(outs[5]))
            score = LazyScore(loss)
            for lst in net.listeners:
                lst.iteration_done(net, net.iteration, score)
            return score

    def _note_guarded_step(self, ok: bool) -> None:
        """Budget accounting for the compressed path's guard (the dense
        path uses the net's own counter — same semantics)."""
        from ..nn.multilayer import DivergenceError
        import logging

        if ok:
            self._bad_steps = 0
            return
        self._bad_steps += 1
        logging.getLogger("deeplearning4j_tpu").warning(
            "non-finite gradients at iteration %d (compressed exchange) — "
            "update + residual accumulation skipped (%d/%d bad steps)",
            self.net.iteration, self._bad_steps, self.nan_guard)
        if self._bad_steps > self.nan_guard:
            # self-resetting on escalation (same semantics as the net's
            # guard): the catcher restores a checkpoint and the fresh run
            # gets a fresh budget
            bad, self._bad_steps = self._bad_steps, 0
            raise DivergenceError(bad, self.nan_guard)

    # -- training ----------------------------------------------------------

    def fit_batch(self, ds: DataSet) -> float:
        """One global step: batch split over the DP axes; grads psum'd by
        GSPMD (dense) or exchanged per tier when ``grad_compression`` is
        set (dense ICI psum + compressed DCN exchange)."""
        if self.grad_compression is not None:
            return self._fit_batch_compressed(ds)
        with set_mesh(self.mesh):
            return self.net.fit_batch(self.shard_dataset(ds))

    def fit_batches(self, batches) -> List["LazyScore"]:
        """k steps in ONE dispatch (the container's scanned multi-step),
        each batch data-sharded on the mesh.  Returns [k] LazyScores
        (device-resident; float() forces the readback — the fit_batch
        contract).  Compressed runs fall back to per-batch steps: the
        residual threads THROUGH the exchange, so steps cannot be fused
        into one scan without replaying the whole tier-2 pipeline there."""
        if self.grad_compression is not None:
            return [self._fit_batch_compressed(ds) for ds in batches]
        with set_mesh(self.mesh):
            return self.net.fit_batches(
                [self.shard_dataset(ds) for ds in batches])

    def fit(self, data, epochs: int = 1) -> List[float]:
        losses = []
        it = self.net._as_iterator(data)
        synced = 0
        for _ in range(epochs):
            for ds in it:
                losses.append(self.fit_batch(ds))
            # the container's own epoch epilogue — mesh mode must not
            # diverge from plain training (scores, counter, epoch_done)
            synced = self.net._end_epoch(losses, synced)
        return losses

    def output(self, x, **kw):
        with set_mesh(self.mesh):
            return self.net.output(self._shard_batch_arr(x), **kw)
