"""ShardedTrainer — DP/TP training over a mesh.

The reference's ParallelWrapper (parallelism/ParallelWrapper.java:58: clone
model per device, round-robin DataSets into per-worker queues, average
params every ``averagingFrequency`` iterations via Nd4j.averageAndPropagate
:326) collapses into: put params on the mesh with TP shardings, put the
batch on the data axis, call the SAME jit step the single-device path uses.
GSPMD partitions the program; gradient allreduce appears as a fused psum
over ICI (the Aeron/NCCL role).  Per-step summation ≡ averagingFrequency=1,
mathematically stronger than the reference's periodic averaging.

Multi-host: call jax.distributed.initialize() first (the Spark master's
process-placement role is played by the launcher — GKE/Ray/mpirun), then
build the mesh over jax.devices() spanning all hosts.
"""

from __future__ import annotations

from typing import List, Optional, TYPE_CHECKING

if TYPE_CHECKING:
    from ..optimize.score import LazyScore

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..datasets.dataset import DataSet
from ..utils.jax_compat import set_mesh
from ..datasets.iterators import DataSetIterator
from .mesh import (
    DATA_AXIS, MODEL_AXIS, build_mesh, infer_param_shardings, put_global,
    replicated,
)


class ShardedTrainer:
    """Wraps a MultiLayerNetwork or ComputationGraph for mesh training.

    >>> mesh = build_mesh({"data": 4, "model": 2})
    >>> trainer = ShardedTrainer(net, mesh)
    >>> trainer.fit(iterator, epochs=2)

    The wrapped net keeps working as usual afterwards; its params simply
    live sharded on the mesh.
    """

    def __init__(self, net, mesh: Optional[Mesh] = None,
                 data_axis: str = DATA_AXIS, model_axis: str = MODEL_AXIS,
                 pipeline_schedule: str = "gpipe"):
        from .pipeline import SCHEDULES
        if pipeline_schedule not in SCHEDULES:
            raise ValueError(f"pipeline_schedule must be one of {SCHEDULES}, "
                             f"got {pipeline_schedule!r}")
        self.net = net
        self.mesh = mesh if mesh is not None else build_mesh()
        self.data_axis = data_axis
        self.model_axis = model_axis
        # microbatch order for nets that pipeline over a `pipe` axis
        # (parallel/pipeline.py): forwarded to the wrapped net when it
        # carries a schedule knob (ShardedTransformerLM); layer-stack nets
        # without a pipe dimension ignore it
        self.pipeline_schedule = pipeline_schedule
        if hasattr(net, "schedule"):
            net.schedule = pipeline_schedule
        self.batch_sharding = NamedSharding(self.mesh, P(data_axis))
        self._place_model()

    # -- placement ---------------------------------------------------------

    def _place_model(self) -> None:
        """Move params/opt-state onto the mesh (TP rules), replicate state."""
        net = self.net
        self.param_shardings = infer_param_shardings(net.params, self.mesh, self.model_axis)
        net.params = jax.tree_util.tree_map(put_global, net.params,
                                            self.param_shardings)
        # opt state mirrors param shapes (Adam m/v etc.) → same shardings
        net.opt_state = self._put_like_params(net.opt_state)
        rep = replicated(self.mesh)
        net.state = jax.tree_util.tree_map(lambda a: put_global(a, rep),
                                           net.state)
        # ephemeral device scalars (rng key, device iteration counter) may
        # be committed to a PREVIOUS mesh (elastic resize) — pull to host
        # and let the next step recommit them under this mesh
        if getattr(net, "_rng", None) is not None:
            net._rng = jnp.asarray(np.asarray(net._rng))
        if getattr(net, "_it_dev", None) is not None:
            net._it_dev = None

    def _put_like_params(self, opt_state):
        """Shard optimizer state structurally: per layer, each state subtree
        whose pytree structure matches the layer's params (Adam m/v,
        Nesterovs momentum, ...) gets the params' shardings leaf-for-leaf;
        anything else (scalars, mismatched trees) is replicated.  Structural
        mapping — never keyed by leaf shape — so per-layer sharding
        overrides can't silently leak across same-shaped layers."""
        rep = replicated(self.mesh)

        def place_layer(os_layer, p_layer, s_layer):
            if not os_layer:
                return os_layer
            p_struct = jax.tree_util.tree_structure(p_layer)

            def place_sub(sub):
                if jax.tree_util.tree_structure(sub) == p_struct:
                    return jax.tree_util.tree_map(put_global, sub, s_layer)
                return jax.tree_util.tree_map(
                    lambda a: put_global(a, rep), sub)

            return {k: place_sub(v) for k, v in os_layer.items()}

        params, shardings = self.net.params, self.param_shardings
        if isinstance(opt_state, list):
            return [place_layer(os, p, s)
                    for os, p, s in zip(opt_state, params, shardings)]
        return {k: place_layer(v, params[k], shardings[k])
                for k, v in opt_state.items()}

    # -- batch placement ---------------------------------------------------

    def _shard_batch_arr(self, a):
        if a is None:
            return None
        if isinstance(a, jax.Array):
            # already on device: re-place only if the sharding differs —
            # never round-trip through host (a 224² imagenet batch is ~77MB;
            # re-uploading it every step would dominate the step time)
            if a.sharding.is_equivalent_to(self.batch_sharding, a.ndim):
                return a
            return jax.device_put(a, self.batch_sharding)
        arr = np.asarray(a)
        dp = self.mesh.shape.get(self.data_axis, 1)
        if arr.shape[0] % dp != 0:
            raise ValueError(
                f"global batch {arr.shape[0]} not divisible by data axis {dp} "
                "(pad or drop the remainder — XLA needs static shapes)")
        return put_global(arr, self.batch_sharding)

    def shard_dataset(self, ds: DataSet) -> DataSet:
        """Pre-place a batch on the mesh (public so callers that reuse a
        batch — benchmarks, eval loops — pay the host→device transfer
        once, not per step)."""
        return DataSet(
            self._shard_batch_arr(ds.features),
            None if ds.labels is None else jax.tree_util.tree_map(self._shard_batch_arr, ds.labels),
            self._shard_batch_arr(ds.features_mask),
            self._shard_batch_arr(ds.labels_mask),
        )


    # -- training ----------------------------------------------------------

    def fit_batch(self, ds: DataSet) -> float:
        """One global step: batch split over data axis, grads psum'd by GSPMD."""
        with set_mesh(self.mesh):
            return self.net.fit_batch(self.shard_dataset(ds))

    def fit_batches(self, batches) -> List["LazyScore"]:
        """k steps in ONE dispatch (the container's scanned multi-step),
        each batch data-sharded on the mesh.  Returns [k] LazyScores
        (device-resident; float() forces the readback — the fit_batch
        contract)."""
        with set_mesh(self.mesh):
            return self.net.fit_batches(
                [self.shard_dataset(ds) for ds in batches])

    def fit(self, data, epochs: int = 1) -> List[float]:
        losses = []
        it = self.net._as_iterator(data)
        synced = 0
        for _ in range(epochs):
            for ds in it:
                losses.append(self.fit_batch(ds))
            # the container's own epoch epilogue — mesh mode must not
            # diverge from plain training (scores, counter, epoch_done)
            synced = self.net._end_epoch(losses, synced)
        return losses

    def output(self, x, **kw):
        with set_mesh(self.mesh):
            return self.net.output(self._shard_batch_arr(x), **kw)
