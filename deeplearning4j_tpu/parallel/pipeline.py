"""Pipeline parallelism — GPipe-style microbatch schedule over the ``pipe``
mesh axis.

No reference analog (SURVEY.md §2.3: PP is ABSENT in DL4J; a first-class
TPU deliverable).  Design: a stack of homogeneous blocks (transformer /
LSTM layers) has its parameters stacked on a leading stage axis that is
sharded over ``pipe`` — each device holds ``n_stages // pipe`` block
params.  The microbatch schedule is a single ``lax.scan`` inside
``shard_map``: at step s, the device holding stage p processes microbatch
``s - p`` and hands its activation to stage p+1 via ``lax.ppermute`` —
compute and ICI transfer overlap, and the whole pipeline (fwd+bwd through
autodiff) stays inside ONE jitted XLA program.

The bubble is the standard GPipe (P-1)/(M+P-1) fraction; raise
``n_microbatches`` to amortize.
"""

from __future__ import annotations

import functools
import logging
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import vary_over

logger = logging.getLogger("deeplearning4j_tpu")

Array = jax.Array


def stack_stage_params(param_list):
    """Stack per-block param pytrees [p0, p1, ...] into one pytree with a
    leading stage axis (all blocks must be homogeneous)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *param_list)


def stage_sharding(mesh: Mesh, stacked_params, axis: str = "pipe"):
    """NamedShardings putting the leading stage axis on ``axis``."""
    def spec(a):
        return NamedSharding(mesh, P(axis, *([None] * (a.ndim - 1))))
    return jax.tree_util.tree_map(spec, stacked_params)


def pipeline_apply(block_fn: Callable[[Any, Array], Array],
                   stacked_params, x: Array, mesh: Mesh, *,
                   axis: str = "pipe", n_microbatches: int = 4,
                   data_axis: str | None = "data",
                   param_specs=None, x_spec=None) -> Array:
    """Run ``x`` through the pipelined block stack; returns same-shape y.

    ``block_fn(params_i, h) -> h`` is one block (activation shapes must be
    preserved — the homogeneous-pipeline contract).  ``stacked_params`` has
    leading axis n_stages (divisible by the pipe axis size), sharded via
    ``stage_sharding``.  ``x`` is [B, ...]; B must divide by
    n_microbatches.  Composes with other mesh axes: batch stays sharded on
    ``data_axis``, and block_fn may itself use collectives (e.g. ring
    attention on ``seq``, TP psums on ``model``).

    ``param_specs``: optional PartitionSpec pytree for the stacked params
    (leading dim on ``axis``) to tensor-parallel individual weights on top
    of the stage sharding.  ``x_spec``: optional PartitionSpec for the
    activations (e.g. ``P('data', 'seq', None)`` for sequence-sharded LM
    inputs); microbatching always splits dim 0.
    """
    n_pipe = mesh.shape[axis]
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stages % n_pipe:
        raise ValueError(f"{n_stages} stages not divisible by pipe={n_pipe}")

    if x_spec is not None:
        batch_spec = x_spec
    elif data_axis and mesh.shape.get(data_axis, 1) > 1:
        batch_spec = P(data_axis)
    else:
        batch_spec = P()

    # microbatches split the PER-DEVICE batch; shrink to the largest feasible
    # count (a perf knob, not a semantics change — parity tests cover this)
    dim0 = batch_spec[0] if len(batch_spec) else None
    dim0 = dim0 if isinstance(dim0, tuple) else (dim0,) if dim0 else ()
    dp = 1
    for a in dim0:
        dp *= mesh.shape.get(a, 1)
    b_local = x.shape[0] // dp
    if x.shape[0] % dp:
        raise ValueError(f"batch {x.shape[0]} not divisible by {dim0} ({dp})")
    requested_microbatches = n_microbatches
    while b_local % n_microbatches:
        n_microbatches -= 1
    if n_microbatches != requested_microbatches:
        # GPipe bubble fraction is (stages-1)/(m+stages-1): shrinking m
        # degrades pipelining — at m=1 every stage but one idles.  Never
        # do this silently (a prime b_local collapses all the way to 1).
        logger.warning(
            "n_microbatches=%d does not divide local batch %d — degraded to "
            "%d%s; pad the batch or pick a divisor to keep the pipeline full",
            requested_microbatches, b_local, n_microbatches,
            " (NO pipelining: full GPipe bubble)" if n_microbatches == 1 else "")
    param_spec = param_specs if param_specs is not None else \
        jax.tree_util.tree_map(
            lambda a: P(axis, *([None] * (a.ndim - 1))), stacked_params)

    def run(params_local, xs):  # per-device: params [n_stages/n_pipe, ...]
        my = jax.lax.axis_index(axis)
        m = n_microbatches
        mb = xs.shape[0] // m
        micro = xs.reshape((m, mb) + xs.shape[1:])

        def apply_local(h):
            def f(h, p):
                return block_fn(p, h), None
            h, _ = jax.lax.scan(f, h, params_local)
            return h

        perm_fwd = [(i, i + 1) for i in range(n_pipe - 1)]
        n_steps = m + n_pipe - 1
        # zero-init buffers must carry the same varying-axes type as the
        # loop body's outputs (shard_map vma typing): they vary over pipe
        # AND over any axis the batch is sharded on
        out0 = vary_over(jnp.zeros_like(micro), mesh.axis_names)
        buf0 = vary_over(jnp.zeros((mb,) + xs.shape[1:], xs.dtype),
                         mesh.axis_names)

        def step(carry, s):
            buf, out = carry
            # stage 0 injects microbatch s (clamped; inactive steps compute
            # on stale data and their results are never written back)
            inj = micro[jnp.clip(s, 0, m - 1)]
            h_in = jnp.where(my == 0, inj, buf)
            h_out = apply_local(h_in)
            # last stage banks microbatch s - (n_pipe - 1) when in range
            widx = s - (n_pipe - 1)
            write = jnp.logical_and(my == n_pipe - 1,
                                    jnp.logical_and(widx >= 0, widx < m))
            out = jax.lax.cond(
                write,
                lambda o: jax.lax.dynamic_update_index_in_dim(o, h_out, jnp.clip(widx, 0, m - 1), 0),
                lambda o: o, out)
            # hand activation to the next stage
            buf = jax.lax.ppermute(h_out, axis, perm_fwd)
            return (buf, out), None

        (_, out), _ = jax.lax.scan(step, (buf0, out0), jnp.arange(n_steps))
        # result lives on the last stage; broadcast over the pipe axis
        out = jax.lax.psum(
            jnp.where(my == n_pipe - 1, out, jnp.zeros_like(out)), axis)
        # activations may be typed varying over axes block_fn reduced over
        # (e.g. TP psums on "model" leave replicated-but-varying values);
        # pmean over axes absent from the output spec clears the variance
        spec_axes = set()
        for entry in batch_spec:
            if isinstance(entry, (tuple, list)):
                spec_axes.update(entry)
            elif entry is not None:
                spec_axes.add(entry)
        extra = tuple(n for n in jax.typeof(out).vma
                      if n != axis and n not in spec_axes)
        if extra:
            out = jax.lax.pmean(out, extra)
        return out.reshape(xs.shape)

    fn = jax.shard_map(run, mesh=mesh,
                       in_specs=(param_spec, batch_spec),
                       out_specs=batch_spec)
    return fn(stacked_params, x)
