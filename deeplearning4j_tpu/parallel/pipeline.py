"""Pipeline parallelism — microbatch schedules over the ``pipe`` mesh axis.

No reference analog (SURVEY.md §2.3: PP is ABSENT in DL4J; a first-class
TPU deliverable).  Design: a stack of homogeneous blocks (transformer /
LSTM layers) has its parameters stacked on a leading stage axis that is
sharded over ``pipe`` — each device holds ``n_stages // pipe`` block
params.  Two microbatch schedules:

``schedule="gpipe"`` (default) — all forwards, then all backwards.  A
single ``lax.scan`` inside ``shard_map``: at step s, the device holding
stage p processes microbatch ``s - p`` and hands its activation to stage
p+1 via ``lax.ppermute``; autodiff transposes the scan into the mirrored
backward.  Bubble (S-1)/(M+S-1); peak activation memory grows with M —
the scan checkpoints every step's block residuals, (M+S-1) sets per
device.

``schedule="1f1b"`` (opt-in) — interleaved one-forward-one-backward.
The forward value pass is the SAME program as gpipe (losses are
bit-identical); the backward is a hand-scheduled combined pass: warm-up
forwards, steady-state alternating one recomputed forward with one
backward, cool-down backwards.  A stage stashes only microbatch
*stage inputs*, at most ``min(M, 2S-1)+1`` live at once, and block
internals exist only transiently inside the one microbatch being
differentiated — so peak activation memory is bounded by the pipeline
DEPTH, not the microbatch count, and M can grow to amortize the bubble
without growing memory.  The price is recompute: 3 forward passes per
microbatch (value, wavefront, vjp linearization) vs gpipe's 1.  Pick
1f1b when activations at the gpipe M you need don't fit; pick gpipe when
they do (docs/PARALLELISM.md has the decision table and the derivations;
``pipeline_schedule_stats`` is the analytic model).

Both schedules compose with the other mesh axes: batch stays sharded on
``data``/``seq``, and block_fn may use collectives (ring attention on
``seq``, TP psums on ``model``).  The 1f1b backward takes ``jax.vjp`` OF
the shard_map'd stage step — never inside it — so the shard_map
transpose machinery inserts the data/seq/model grad collectives on every
jax version the framework supports (utils/jax_compat.py).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.jax_compat import shard_map, vma_of
from .mesh import vary_over

logger = logging.getLogger("deeplearning4j_tpu")

Array = jax.Array

SCHEDULES = ("gpipe", "1f1b")


def stack_stage_params(param_list):
    """Stack per-block param pytrees [p0, p1, ...] into one pytree with a
    leading stage axis (all blocks must be homogeneous)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *param_list)


def stage_sharding(mesh: Mesh, stacked_params, axis: str = "pipe"):
    """NamedShardings putting the leading stage axis on ``axis``."""
    def spec(a):
        return NamedSharding(mesh, P(axis, *([None] * (a.ndim - 1))))
    return jax.tree_util.tree_map(spec, stacked_params)


def pipeline_schedule_stats(schedule: str, n_microbatches: int,
                            n_stages: int, *, layers_per_stage: int = 1,
                            residual_factor: float = 1.0,
                            stage_input_bytes: int = 0) -> Dict[str, Any]:
    """Analytic bubble / peak-activation accounting for one schedule.

    Conventions (all derivations in docs/PARALLELISM.md):
      - ``bubble_fraction``: idle (garbage-compute) slots over total slots
        of the schedule grid the implementation actually executes.  gpipe
        runs two mirrored (M+S-1)-step scans → (S-1)/(M+S-1).  1f1b runs
        a value pass (M+S-1 slots) plus a combined pass of M+2(S-1) steps
        with a forward and a backward slot each → (5S-5)/(3M+5S-5).  At
        EQUAL M the 1f1b grid idles more (longer drain + recompute); its
        lever is ``peak_activation_units``, which is depth-bounded, so M
        can be raised at fixed memory — compare against
        ``gpipe_microbatches_at_same_memory`` for the like-for-like
        bubble.
      - ``peak_live_stage_inputs``: stage-input-sized activation buffers
        live per device at the worst moment.  gpipe's backward needs every
        scan step's saved state: M+S-1.  1f1b stashes at most
        min(M, 2S-1) stage inputs (+1 in transit).
      - ``peak_activation_units``: peak activation memory in stage-input
        units, including per-layer block residuals
        (``layers_per_stage * residual_factor`` per checkpointed
        microbatch).  gpipe checkpoints block internals for every step;
        1f1b only for the single microbatch inside the current vjp.
        Multiply by ``stage_input_bytes`` for bytes
        (``peak_activation_bytes``, 0 when no byte size is given).

    ``residual_factor``: saved residuals per layer per microbatch,
    measured in stage-input units (≈1-2 for a dense block; ≈10 + 2·d_ff/d
    for a transformer block — q/k/v/att/gelu/FFN intermediates).
    """
    if schedule not in SCHEDULES:
        raise ValueError(f"schedule must be one of {SCHEDULES}, got {schedule!r}")
    m, s = n_microbatches, n_stages
    if m < 1 or s < 1:
        raise ValueError(f"need n_microbatches>=1, n_stages>=1; got {m}, {s}")
    lr = layers_per_stage * residual_factor
    if schedule == "gpipe":
        out = {
            "schedule": "gpipe",
            "n_steps": 2 * (m + s - 1),
            "bubble_fraction": (s - 1) / (m + s - 1),
            "peak_live_stage_inputs": m + s - 1,
            "peak_activation_units": (m + s - 1) * max(lr, 1.0),
            "forward_passes_per_microbatch": 1,
        }
    else:
        live = min(m, 2 * s - 1) + 1
        out = {
            "schedule": "1f1b",
            "n_steps": (m + s - 1) + (m + 2 * (s - 1)),
            "bubble_fraction": (5 * s - 5) / (3 * m + 5 * s - 5),
            "peak_live_stage_inputs": live,
            "peak_activation_units": live + max(lr, 1.0),
            "forward_passes_per_microbatch": 3,
        }
        # the largest M a gpipe schedule could run inside THIS memory
        # footprint — the honest basis for a bubble comparison
        g_equiv = int(out["peak_activation_units"] // max(lr, 1.0)) - s + 1
        out["gpipe_microbatches_at_same_memory"] = max(g_equiv, 1)
    if stage_input_bytes:
        out["peak_activation_bytes"] = int(
            out["peak_activation_units"] * stage_input_bytes)
    return out


def _resolve_specs(mesh, stacked_params, x, axis, data_axis, x_spec,
                   param_specs, n_microbatches):
    """Shared spec/microbatch resolution for both schedules."""
    if x_spec is not None:
        batch_spec = x_spec
    elif data_axis and mesh.shape.get(data_axis, 1) > 1:
        batch_spec = P(data_axis)
    else:
        batch_spec = P()

    # microbatches split the PER-DEVICE batch; shrink to the largest feasible
    # count (a perf knob, not a semantics change — parity tests cover this)
    dim0 = batch_spec[0] if len(batch_spec) else None
    dim0 = dim0 if isinstance(dim0, tuple) else (dim0,) if dim0 else ()
    dp = 1
    for a in dim0:
        dp *= mesh.shape.get(a, 1)
    b_local = x.shape[0] // dp
    if x.shape[0] % dp:
        raise ValueError(f"batch {x.shape[0]} not divisible by {dim0} ({dp})")
    requested = n_microbatches
    while b_local % n_microbatches:
        n_microbatches -= 1
    if n_microbatches != requested:
        # GPipe bubble fraction is (stages-1)/(m+stages-1): shrinking m
        # degrades pipelining — at m=1 every stage but one idles.  Never
        # do this silently (a prime b_local collapses all the way to 1).
        # graftcheck: disable=GC102 (shape-static degradation warning: firing ONCE at trace time is exactly the intended behavior)
        logger.warning(
            "n_microbatches=%d does not divide local batch %d — degraded to "
            "%d%s; pad the batch or pick a divisor to keep the pipeline full",
            requested, b_local, n_microbatches,
            " (NO pipelining: full GPipe bubble)" if n_microbatches == 1 else "")
    param_spec = param_specs if param_specs is not None else \
        jax.tree_util.tree_map(
            lambda a: P(axis, *([None] * (a.ndim - 1))), stacked_params)
    return batch_spec, param_spec, n_microbatches


def _spec_axes(batch_spec):
    axes = set()
    for entry in batch_spec:
        if isinstance(entry, (tuple, list)):
            axes.update(entry)
        elif entry is not None:
            axes.add(entry)
    return axes


def _clear_extra_vma(out, batch_spec, axis):
    """Activations may be typed varying over axes block_fn reduced over
    (e.g. TP psums on "model" leave replicated-but-varying values);
    pmean over axes absent from the output spec clears the variance
    (no-op on jax without vma typing — the values are replicated)."""
    extra = tuple(n for n in vma_of(out)
                  if n != axis and n not in _spec_axes(batch_spec))
    if extra:
        out = jax.lax.pmean(out, extra)
    return out


def _gpipe_fn(block_fn, mesh, axis, n_pipe, m, batch_spec, param_spec):
    """The shard_map'd all-forward pipeline (the gpipe schedule's forward
    AND the 1f1b schedule's value pass — bit-identical by construction)."""
    def run(params_local, xs):  # per-device: params [n_stages/n_pipe, ...]
        my = jax.lax.axis_index(axis)
        mb = xs.shape[0] // m
        micro = xs.reshape((m, mb) + xs.shape[1:])

        def apply_local(h):
            def f(h, p):
                return block_fn(p, h), None
            h, _ = jax.lax.scan(f, h, params_local)
            return h

        perm_fwd = [(i, i + 1) for i in range(n_pipe - 1)]
        n_steps = m + n_pipe - 1
        # zero-init buffers must carry the same varying-axes type as the
        # loop body's outputs (shard_map vma typing): they vary over pipe
        # AND over any axis the batch is sharded on
        out0 = vary_over(jnp.zeros_like(micro), mesh.axis_names)
        buf0 = vary_over(jnp.zeros((mb,) + xs.shape[1:], xs.dtype),
                         mesh.axis_names)

        def step(carry, s):
            buf, out = carry
            # stage 0 injects microbatch s (clamped; inactive steps compute
            # on stale data and their results are never written back)
            inj = micro[jnp.clip(s, 0, m - 1)]
            h_in = jnp.where(my == 0, inj, buf)
            h_out = apply_local(h_in)
            # last stage banks microbatch s - (n_pipe - 1) when in range
            widx = s - (n_pipe - 1)
            write = jnp.logical_and(my == n_pipe - 1,
                                    jnp.logical_and(widx >= 0, widx < m))
            out = jax.lax.cond(
                write,
                lambda o: jax.lax.dynamic_update_index_in_dim(o, h_out, jnp.clip(widx, 0, m - 1), 0),
                lambda o: o, out)
            # hand activation to the next stage
            buf = jax.lax.ppermute(h_out, axis, perm_fwd)
            return (buf, out), None

        (_, out), _ = jax.lax.scan(step, (buf0, out0), jnp.arange(n_steps))
        # result lives on the last stage; broadcast over the pipe axis
        out = jax.lax.psum(
            jnp.where(my == n_pipe - 1, out, jnp.zeros_like(out)), axis)
        out = _clear_extra_vma(out, batch_spec, axis)
        return out.reshape(xs.shape)

    return shard_map(run, mesh=mesh, in_specs=(param_spec, batch_spec),
                     out_specs=batch_spec)


def _stage_step_fn(block_fn, mesh, axis, batch_spec, param_spec):
    """One pipeline tick as a shard_map'd function at GLOBAL level: every
    pipe device applies its local layer stack to its slot of the
    [n_pipe, microbatch, ...] activation stack.  The 1f1b backward takes
    ``jax.vjp`` of THIS function, so grad collectives (data/seq psums for
    params, TP transposes inside block_fn) are inserted by the shard_map
    transpose — correct on every supported jax."""
    hspec = P(axis, *tuple(batch_spec))

    def tick(params_local, h_stk):   # h_stk [1, mb_local, ...] per device
        h = h_stk[0]

        def f(h, p):
            return block_fn(p, h), None
        h, _ = jax.lax.scan(f, h, params_local)
        h = _clear_extra_vma(h, batch_spec, axis)
        return h[None]

    return shard_map(tick, mesh=mesh, in_specs=(param_spec, hspec),
                     out_specs=hspec)


def _pipeline_1f1b(block_fn, stacked_params, x, mesh, axis, n_pipe, m,
                   batch_spec, param_spec):
    """Interleaved 1F1B: gpipe-identical value pass + a hand-scheduled
    combined backward (custom_vjp).

    Backward schedule, per pipe stage p of S at combined-pass step s
    (each step has one forward and one backward slot):
      forward slot:  recompute microbatch  f = s - p            (warm-up)
      backward slot: differentiate         b = s - 2(S-1) + p   (cool-down)
    Steady state alternates the two; stage inputs are stashed in a
    ``min(M, 2S-1)``-deep ring buffer between their forward and backward.
    """
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    l_local = n_stages // n_pipe
    value_fn = _gpipe_fn(block_fn, mesh, axis, n_pipe, m, batch_spec,
                         param_spec)
    tick_fn = _stage_step_fn(block_fn, mesh, axis, batch_spec, param_spec)
    S = n_pipe
    K = min(m, 2 * S - 1)

    def bwd_pass(params, xx, gy):
        mbs = xx.shape[0] // m
        micro = xx.reshape((m, mbs) + xx.shape[1:])
        gmicro = gy.reshape((m, mbs) + xx.shape[1:])
        stages = jnp.arange(S)
        n_steps = m + 2 * (S - 1)
        stk_shape = (S, mbs) + xx.shape[1:]

        hs = NamedSharding(mesh, P(axis, *tuple(batch_spec)))
        ss = NamedSharding(mesh, P(axis, None, *tuple(batch_spec)))
        fstk0 = jax.lax.with_sharding_constraint(
            jnp.zeros(stk_shape, xx.dtype), hs)
        gstk0 = jax.lax.with_sharding_constraint(
            jnp.zeros(stk_shape, gy.dtype), hs)
        # the 1f1b memory contract: the ONLY cross-step activation state is
        # this K-deep per-stage stash of stage inputs (+ the two in-transit
        # stacks) — block internals never outlive one vjp
        sstk0 = jax.lax.with_sharding_constraint(
            jnp.zeros((S, K, mbs) + xx.shape[1:], xx.dtype), ss)
        dx0 = jnp.zeros((m, mbs) + xx.shape[1:], xx.dtype)
        dp0 = jax.tree_util.tree_map(jnp.zeros_like, params)

        def put(col, i, v):
            return jax.lax.dynamic_update_index_in_dim(col, v, i, 0)

        def take(col, i):
            return jax.lax.dynamic_index_in_dim(col, i, 0, keepdims=False)

        def step(carry, s):
            sstk, fstk, gstk, dx, dp = carry
            # ---- forward slot: recompute the wavefront ----
            f_idx = s - stages                                      # [S]
            f_ok = jnp.logical_and(f_idx >= 0, f_idx < m)
            h_in = put(fstk, 0, micro[jnp.clip(s, 0, m - 1)])
            slot_w = jnp.where(f_ok, f_idx % K, 0)
            stored = jax.vmap(put)(sstk, slot_w, h_in)
            keep = f_ok.reshape((S,) + (1,) * (sstk.ndim - 1))
            sstk = jnp.where(keep, stored, sstk)
            fstk = jnp.roll(tick_fn(params, h_in), 1, axis=0)
            # ---- backward slot: vjp of the stage tick ----
            b_idx = s - 2 * (S - 1) + stages                        # [S]
            b_ok = jnp.logical_and(b_idx >= 0, b_idx < m)
            g_in = put(gstk, S - 1, gmicro[jnp.clip(s - (S - 1), 0, m - 1)])
            h_sav = jax.vmap(take)(sstk, jnp.where(b_ok, b_idx % K, 0))
            _, vjp_fn = jax.vjp(tick_fn, params, h_sav)
            dp_s, dh = vjp_fn(g_in)
            layer_ok = jnp.repeat(b_ok, l_local)                    # [n_stages]

            def acc(a, g):
                mask = layer_ok.reshape((n_stages,) + (1,) * (g.ndim - 1))
                return a + jnp.where(mask, g, jnp.zeros_like(g))

            dp = jax.tree_util.tree_map(acc, dp, dp_s)
            dx = jnp.where(
                b_ok[0],
                put(dx, jnp.clip(b_idx[0], 0, m - 1), dh[0]), dx)
            gstk = jnp.roll(dh, -1, axis=0)
            return (sstk, fstk, gstk, dx, dp), None

        (_, _, _, dx, dp), _ = jax.lax.scan(
            step, (sstk0, fstk0, gstk0, dx0, dp0), jnp.arange(n_steps))
        return dp, dx.reshape(xx.shape)

    @jax.custom_vjp
    def pp(params, xx):
        return value_fn(params, xx)

    def pp_fwd(params, xx):
        return value_fn(params, xx), (params, xx)

    def pp_bwd(res, gy):
        params, xx = res
        return bwd_pass(params, xx, gy)

    pp.defvjp(pp_fwd, pp_bwd)
    return pp(stacked_params, x)


def pipeline_apply(block_fn: Callable[[Any, Array], Array],
                   stacked_params, x: Array, mesh: Mesh, *,
                   axis: str = "pipe", n_microbatches: int = 4,
                   data_axis: str | None = "data",
                   schedule: str = "gpipe",
                   param_specs=None, x_spec=None) -> Array:
    """Run ``x`` through the pipelined block stack; returns same-shape y.

    ``block_fn(params_i, h) -> h`` is one block (activation shapes must be
    preserved — the homogeneous-pipeline contract).  ``stacked_params`` has
    leading axis n_stages (divisible by the pipe axis size), sharded via
    ``stage_sharding``.  ``x`` is [B, ...]; B must divide by
    n_microbatches.  Composes with other mesh axes: batch stays sharded on
    ``data_axis``, and block_fn may itself use collectives (e.g. ring
    attention on ``seq``, TP psums on ``model``).

    ``schedule``: ``"gpipe"`` or ``"1f1b"`` (module docstring has the
    trade-off; forward values and first-step losses are bit-identical
    between the two — only the backward's order and memory differ).
    ``param_specs``: optional PartitionSpec pytree for the stacked params
    (leading dim on ``axis``) to tensor-parallel individual weights on top
    of the stage sharding.  ``x_spec``: optional PartitionSpec for the
    activations (e.g. ``P('data', 'seq', None)`` for sequence-sharded LM
    inputs); microbatching always splits dim 0.
    """
    if schedule not in SCHEDULES:
        raise ValueError(f"schedule must be one of {SCHEDULES}, got {schedule!r}")
    n_pipe = mesh.shape[axis]
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stages % n_pipe:
        raise ValueError(f"{n_stages} stages not divisible by pipe={n_pipe}")

    batch_spec, param_spec, m = _resolve_specs(
        mesh, stacked_params, x, axis, data_axis, x_spec, param_specs,
        n_microbatches)

    if schedule == "1f1b":
        return _pipeline_1f1b(block_fn, stacked_params, x, mesh, axis,
                              n_pipe, m, batch_spec, param_spec)
    fn = _gpipe_fn(block_fn, mesh, axis, n_pipe, m, batch_spec, param_spec)
    return fn(stacked_params, x)
