"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

No reference analog (DL4J 0.9.2 handles sequence scale with TBPTT +
masking only — SURVEY.md §5 "Long-context"); designed TPU-first per SURVEY
§7-M5: the sequence axis is sharded across devices, each device keeps its
local Q block resident, and K/V blocks rotate around the ICI ring via
``jax.lax.ppermute`` while the blockwise streaming-softmax accumulator
(ops.attention.blockwise_update — the same update rule the pallas flash
kernel uses on-chip) folds in one block per hop.  Communication overlaps
compute; peak memory is O(T/n) per device.

Use inside ``jax.shard_map`` with q/k/v sharded on the sequence axis, or
through ``ring_self_attention`` which sets that up from a Mesh.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import _NEG_INF, blockwise_update, causal_bias
from .mesh import vary_over
from ..utils.jax_compat import axis_size, shard_map, vma_of

Array = jax.Array


def ring_attention(q: Array, k: Array, v: Array, axis_name: str,
                   *, causal: bool = False,
                   scale: Optional[float] = None) -> Array:
    """Blockwise attention with K/V rotating around the ``axis_name`` ring.

    Call INSIDE shard_map/pjit with q/k/v [B,H,T_local,D] sharded on the
    sequence axis.  Each of the n hops computes the local Q against the
    visiting K/V block with an online-softmax accumulator, then ppermutes
    the block to the next device.  Causal masking uses global block offsets
    derived from ``lax.axis_index``.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, t, d = q.shape

    # flatten batch×heads so the accumulator matches blockwise_update's [T,D]
    qf = q.reshape(b * h, t, d)

    def local_block(carry, step):
        acc, m, l, kk, vv = carry
        src = (my - step) % n          # global block index currently held
        bias = causal_bias(t, t, my * t, src * t) if causal else None

        kf = kk.reshape(b * h, t, d)
        vf = vv.reshape(b * h, t, d)
        upd = jax.vmap(
            functools.partial(blockwise_update, scale=scale, bias=bias))
        acc, m, l = upd(acc, m, l, qf, kf, vf)

        # rotate K/V to the next device (last hop's permute is still issued
        # to keep the loop shape static; XLA overlaps it with the epilogue)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        return (acc, m, l, kk, vv), None

    # mark the zero-init accumulators as device-varying over every axis the
    # inputs vary on (shard_map's vma typing: the scan carry must match the
    # loop body's type) — q may additionally vary over data/model/pipe when
    # ring attention runs inside a larger manual region
    vary = tuple(set(vma_of(q)) | {axis_name})
    acc0 = vary_over(jnp.zeros((b * h, t, d), jnp.float32), vary)
    m0 = vary_over(jnp.full((b * h, t, 1), _NEG_INF, jnp.float32), vary)
    l0 = vary_over(jnp.zeros((b * h, t, 1), jnp.float32), vary)
    (acc, m, l, _, _), _ = jax.lax.scan(
        local_block, (acc0, m0, l0, k, v), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(b, h, t, d).astype(q.dtype)


def ring_self_attention(q: Array, k: Array, v: Array, mesh: Mesh,
                        *, seq_axis: str = "seq", causal: bool = False,
                        scale: Optional[float] = None) -> Array:
    """Convenience wrapper: shard [B,H,T,D] q/k/v on ``seq_axis`` of
    ``mesh`` and run ring attention.  T must divide by the axis size."""
    n = mesh.shape[seq_axis]
    if q.shape[2] % n:
        raise ValueError(f"seq len {q.shape[2]} not divisible by seq axis {n}")
    spec = P(None, None, seq_axis, None)

    fn = shard_map(
        functools.partial(ring_attention, axis_name=seq_axis, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
