"""Deterministic fault injection for the training stack (chaos testing).

The recovery loop around :class:`~.elastic.ElasticTrainer` — failure
detection, checkpoint restore, mesh rebuild, divergence guard — is exactly
the code that never runs in a healthy CI pass, yet at pod scale device
loss, torn checkpoint writes, hung collectives, and NaN-poisoned steps are
routine events (PAPERS.md: the TPU-supercomputer retrospective names
resilience, not peak FLOPs, as the availability lever).  This module makes
those events *scriptable and reproducible*:

- :class:`FaultSchedule` — a seeded/scripted map of step → fault kinds.
- :class:`ChaosInjector` — wraps a trainer (and optionally its
  CheckpointManager) and injects each scheduled fault at the scripted
  step: recoverable device errors, checkpoint-write crashes mid-zip,
  truncated / bit-flipped checkpoint files, hung steps, NaN gradients.

Usage (the chaos-soak harness, scripts/chaos_soak.py):

    schedule = FaultSchedule.scripted({5: [FaultKind.DEVICE_LOSS],
                                       9: [FaultKind.NAN_GRADS]})
    inj = ChaosInjector(trainer, schedule)
    et = ElasticTrainer(inj, ckpt_dir, step_timeout=30.0, backoff_base=0.1)
    inj.attach_checkpoints(et.ckpt)      # arm the I/O faults too
    et.fit(data, epochs=1)               # faults fire; recovery must hold
    assert inj.unrecovered == 0

Every fault is injected exactly once (consumed from the schedule), at a
deterministic step index, with any randomness (bit-flip offsets, random
schedules) drawn from seeded generators — a failing chaos run replays
bit-for-bit.  Injection happens INSIDE the ElasticTrainer's try block, so
a fault the stack cannot recover from fails the run loudly instead of
flaking.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..obs import trace as obs_trace

logger = logging.getLogger("deeplearning4j_tpu")


class FaultKind:
    """The fault menu.  String constants (not an enum) so schedules stay
    JSON/CLI-friendly — ``--chaos device_loss@5,nan_grads@9``."""

    #: recoverable infra error raised from the step (XLA device loss)
    DEVICE_LOSS = "device_loss"
    #: the next checkpoint write crashes mid-zip, leaving a stale .tmp
    CKPT_WRITE_CRASH = "ckpt_write_crash"
    #: truncate the newest on-disk checkpoint (torn write after rename)
    CKPT_TRUNCATE = "ckpt_truncate"
    #: flip bits in the middle of the newest on-disk checkpoint
    CKPT_BITFLIP = "ckpt_bitflip"
    #: the step blocks for ``hang_seconds`` (hung collective/dispatch)
    HUNG_STEP = "hung_step"
    #: the step's batch is poisoned with NaN features → NaN gradients
    NAN_GRADS = "nan_grads"
    #: SIGKILL this worker PROCESS at the scheduled step (host loss /
    #: preemption) — recovery is the LAUNCHER's job: it observes the
    #: death, bumps the membership epoch, and relaunches the worker,
    #: which resumes from the newest checkpoint (ElasticTrainer.resume)
    PROC_KILL = "proc_kill"
    #: SIGSTOP this worker — the process stays alive but its heartbeats
    #: stop, exercising the heartbeat-expiry path: the launcher must
    #: declare it dead, SIGKILL it, and relaunch
    PROC_HANG = "proc_hang"
    #: ANNOUNCED preemption: SIGTERM this worker at the scheduled step —
    #: its PreemptionHandler flips the notice flag, the step completes,
    #: and the next step boundary writes the grace-window emergency
    #: checkpoint and exits PREEMPTED (planned leave: the launcher
    #: relaunches WITHOUT consuming the restart budget)
    PREEMPT_NOTICE = "preempt_notice"
    #: SIGKILL the COORDINATOR process (legal only on the worker hosting
    #: the coordinator role, i.e. process 0) — recovery is coordinator
    #: restart: the launcher relaunches it, survivors re-initialize (or,
    #: if it never comes back, elect the lowest alive id from the ledger
    #: — launcher.elect_coordinator)
    COORD_KILL = "coord_kill"
    #: make THIS worker a straggler: every step from the scheduled one on
    #: is slowed by ``slow_seconds`` — the launcher must flag it (step
    #: time > k x the peer median for m consecutive beats) and, under the
    #: opt-in policy, kill-and-relaunch it
    SLOW_WORKER = "slow_worker"
    #: a serving replica THREAD dies mid-batch (uncaught exception) —
    #: the engine supervisor must complete the stranded futures, retry
    #: them on another replica, and respawn the thread (re-warmed)
    REPLICA_CRASH = "replica_crash"
    #: a serving replica's forward blocks past forward_timeout_s — the
    #: supervisor must abandon the hung incarnation, redispatch its
    #: batch, and respawn; the late wake-up's results are discarded
    REPLICA_HANG = "replica_hang"
    #: a serving request whose features are all-NaN — the batch's
    #: forward goes non-finite and the engine must bisect to isolate the
    #: poison request so co-batched requests still succeed (driver-side:
    #: the workload submits the poisoned request itself)
    POISON_INPUT = "poison_input"
    #: a regressed model version is canary-promoted — the registry's
    #: shadow-traffic comparison must auto-roll-back (driver-side: the
    #: workload registers the bad version and calls set_alias(canary=))
    BAD_VERSION = "bad_version"
    #: a whole serving HOST dies under live traffic (engine shutdown /
    #: process kill) — the fleet router must fail it over: in-flight
    #: futures resolve, survivors absorb the retries, nothing stranded
    #: (scripts/fleet_load_soak.py schedules one mid-rolling-swap)
    HOST_KILL = "host_kill"
    #: a serving host takes an ANNOUNCED preemption notice — the router
    #: drains it within the grace budget (peers absorb the load) and
    #: takes it out of rotation as a planned leave
    HOST_PREEMPT = "host_preempt"
    #: a serving host turns straggler: every request it serves from the
    #: scheduled one on is slowed — least-loaded routing plus per-request
    #: timeouts must steer traffic away without failing the fleet SLO
    HOST_STRAGGLE = "host_straggle"

    ALL = (DEVICE_LOSS, CKPT_WRITE_CRASH, CKPT_TRUNCATE, CKPT_BITFLIP,
           HUNG_STEP, NAN_GRADS, PROC_KILL, PROC_HANG,
           PREEMPT_NOTICE, COORD_KILL, SLOW_WORKER,
           REPLICA_CRASH, REPLICA_HANG, POISON_INPUT, BAD_VERSION,
           HOST_KILL, HOST_PREEMPT, HOST_STRAGGLE)

    #: kinds that take down the whole PROCESS — only meaningful under a
    #: multi-process launcher (in-process soaks must not schedule them).
    #: preempt_notice is announced (SIGTERM -> graceful exit), coord_kill
    #: and proc_kill are unannounced (SIGKILL), proc_hang is a wedge.
    PROCESS_KINDS = (PROC_KILL, PROC_HANG, COORD_KILL, PREEMPT_NOTICE)

    #: kinds the TRAINING ChaosInjector can act on (FaultSchedule.random's
    #: default pool — serving kinds would be silent no-ops in a trainer)
    TRAINER_KINDS = (DEVICE_LOSS, CKPT_WRITE_CRASH, CKPT_TRUNCATE,
                     CKPT_BITFLIP, HUNG_STEP, NAN_GRADS, PROC_KILL,
                     PROC_HANG)

    #: serving-engine fault kinds (scripts/serving_chaos_soak.py);
    #: the first two are ENGINE-side (ServingChaos, armed on an Engine),
    #: the last two are DRIVER-side (the workload injects them)
    SERVING_KINDS = (REPLICA_CRASH, REPLICA_HANG, POISON_INPUT, BAD_VERSION)
    SERVING_ENGINE_KINDS = (REPLICA_CRASH, REPLICA_HANG)

    #: fleet-level fault kinds (scripts/fleet_load_soak.py) — all
    #: DRIVER-side: the load harness pops them per submitted request and
    #: acts on the fleet (kill/preempt/slow a host); the router under
    #: test only sees the consequences
    FLEET_KINDS = (HOST_KILL, HOST_PREEMPT, HOST_STRAGGLE)


def truncate_file(path: str, keep_fraction: float = 0.5) -> None:
    """Truncate ``path`` to ``keep_fraction`` of its bytes — a torn write."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(1, int(size * keep_fraction)))


def bitflip_file(path: str, n_flips: int = 8, seed: int = 0) -> None:
    """Flip ``n_flips`` random bits in the middle half of ``path`` —
    deterministic for a given seed.  The middle half targets entry
    payloads (zip magic at the start and the central directory at the end
    fail loudly on their own; payload corruption is what only the v4
    integrity digests catch)."""
    rng = np.random.default_rng(seed)
    size = os.path.getsize(path)
    lo, hi = size // 4, max(size // 4 + 1, (3 * size) // 4)
    with open(path, "r+b") as f:
        for off in rng.integers(lo, hi, size=n_flips):
            f.seek(int(off))
            b = f.read(1)
            if not b:
                continue
            f.seek(int(off))
            f.write(bytes([b[0] ^ (1 << int(rng.integers(0, 8)))]))


class FaultSchedule:
    """step index → list of fault kinds, deterministic and replayable.

    Steps are 1-based *injector call* indices (the first ``fit_batch`` the
    injector sees is step 1), counted across retries — a fault consumed at
    step k is not re-injected when recovery replays that step.
    """

    def __init__(self, faults: Optional[Dict[int, List[str]]] = None):
        self.faults: Dict[int, List[str]] = {
            int(k): list(v) for k, v in (faults or {}).items()}
        for kinds in self.faults.values():
            for kind in kinds:
                if kind not in FaultKind.ALL:
                    raise ValueError(f"unknown fault kind {kind!r} — one of "
                                     f"{FaultKind.ALL}")

    @classmethod
    def scripted(cls, faults: Dict[int, Any]) -> "FaultSchedule":
        """{step: kind or [kinds]} → schedule."""
        return cls({s: ([k] if isinstance(k, str) else list(k))
                    for s, k in faults.items()})

    @classmethod
    def random(cls, seed: int, n_steps: int, rate: float = 0.05,
               kinds: Optional[List[str]] = None) -> "FaultSchedule":
        """Seeded random schedule: each step draws a fault with probability
        ``rate``, kind uniform over ``kinds`` (default: the trainer-
        injectable kinds).  Same seed → same schedule, so a failing soak
        replays exactly."""
        kinds = list(kinds or FaultKind.TRAINER_KINDS)
        rng = np.random.default_rng(seed)
        faults: Dict[int, List[str]] = {}
        for step in range(1, n_steps + 1):
            if rng.random() < rate:
                faults[step] = [kinds[int(rng.integers(0, len(kinds)))]]
        return cls(faults)

    def pop(self, step: int) -> List[str]:
        """Faults scheduled at ``step``, consumed (injected once)."""
        return self.faults.pop(step, [])

    def pending(self) -> int:
        return sum(len(v) for v in self.faults.values())

    def __repr__(self) -> str:
        return f"FaultSchedule({self.faults!r})"


class ChaosInjector:
    """Wraps a trainer-like object (``fit_batch`` + ``net``) and injects
    scheduled faults.  Sits BETWEEN the ElasticTrainer and the real
    trainer, so every injected fault exercises the real recovery path::

        ElasticTrainer(ChaosInjector(trainer, schedule), ckpt_dir, ...)

    Checkpoint-I/O faults (write crash, corrupt-on-disk) additionally need
    ``attach_checkpoints(et.ckpt)`` to arm the manager wrappers.
    """

    def __init__(self, trainer, schedule: FaultSchedule,
                 hang_seconds: float = 0.0,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 seed: int = 0,
                 slow_seconds: Optional[float] = None):
        self.trainer = trainer
        self.schedule = schedule
        self.hang_seconds = hang_seconds
        self.sleep_fn = sleep_fn
        self.seed = seed
        # slow_worker persistent per-step drag (defaults to hang_seconds
        # so a bare 'slow_worker@k' spec still slows something)
        self.slow_seconds = (hang_seconds if slow_seconds is None
                             else slow_seconds)
        self._slow_s = 0.0
        self.step = 0              # injector call index (1-based in events)
        self.events: List[dict] = []   # (step, kind) log, replayable
        self._ckpt = None
        self._crash_next_write = False

    # -- trainer protocol --------------------------------------------------

    @property
    def net(self):
        return getattr(self.trainer, "net", self.trainer)

    def _place_model(self) -> None:
        if hasattr(self.trainer, "_place_model"):
            self.trainer._place_model()

    # -- checkpoint I/O faults ---------------------------------------------

    def attach_checkpoints(self, ckpt) -> None:
        """Arm checkpoint-write faults on a CheckpointManager: its ``save``
        / ``save_async`` are wrapped so a scheduled CKPT_WRITE_CRASH makes
        the NEXT write die mid-zip — a partial ``.tmp`` is left behind
        (the stale-tmp leak CheckpointManager.__init__ cleans) and the
        final rename never happens, exactly a crash between write and
        rename."""
        self._ckpt = ckpt
        real_save, real_save_async = ckpt.save, ckpt.save_async

        def save(net, step):
            self._maybe_crash_write(step)
            return real_save(net, step)

        def save_async(net, step):
            self._maybe_crash_write(step)
            return real_save_async(net, step)

        ckpt.save, ckpt.save_async = save, save_async

    def _maybe_crash_write(self, step: int) -> None:
        if not self._crash_next_write:
            return
        self._crash_next_write = False
        tmp = self._ckpt._path(step) + ".tmp"
        with open(tmp, "wb") as f:        # the torn half-written zip
            f.write(b"PK\x03\x04 chaos: torn checkpoint write")
        self._log(self.step, FaultKind.CKPT_WRITE_CRASH,
                  f"crashed write of step {step}, stale tmp left")
        raise RuntimeError(
            "DATA_LOSS: chaos — checkpoint write crashed mid-zip")

    def _corrupt_latest(self, kind: str) -> None:
        if self._ckpt is None:
            raise RuntimeError(f"{kind} scheduled but no CheckpointManager "
                               "attached (call attach_checkpoints)")
        latest = self._ckpt.latest()
        if latest is None:
            self._log(self.step, kind, "no checkpoint on disk yet — no-op")
            return
        path, step = latest
        if kind == FaultKind.CKPT_TRUNCATE:
            truncate_file(path)
        else:
            bitflip_file(path, n_flips=16, seed=self.seed + self.step)
        self._log(self.step, kind, f"corrupted {os.path.basename(path)}")

    # -- the wrapped step --------------------------------------------------

    def _log(self, step: int, kind: str, detail: str = "") -> None:
        self.events.append({"step": step, "kind": kind, "detail": detail})
        obs_trace.instant("fault", cat="chaos", kind=kind, step=step,
                          detail=detail)
        logger.warning("chaos @%d: %s %s", step, kind, detail)

    def injected(self, kind: Optional[str] = None) -> int:
        if kind is None:
            return len(self.events)
        return sum(1 for e in self.events if e["kind"] == kind)

    def fit_batch(self, ds):
        self.step += 1
        faults = self.schedule.pop(self.step)
        for kind in faults:
            if kind == FaultKind.DEVICE_LOSS:
                self._log(self.step, kind)
                raise RuntimeError("UNAVAILABLE: chaos — device lost")
            if kind == FaultKind.CKPT_WRITE_CRASH:
                self._crash_next_write = True   # fires inside the manager
            elif kind in (FaultKind.CKPT_TRUNCATE, FaultKind.CKPT_BITFLIP):
                self._corrupt_latest(kind)
            elif kind == FaultKind.HUNG_STEP:
                self._log(self.step, kind, f"sleeping {self.hang_seconds}s")
                self.sleep_fn(self.hang_seconds)
            elif kind == FaultKind.NAN_GRADS:
                self._log(self.step, kind, "poisoning batch features")
                ds = _poison_dataset(ds)
            elif kind == FaultKind.SLOW_WORKER:
                self._log(self.step, kind,
                          f"+{self.slow_seconds}s per step from here on")
                self._slow_s = self.slow_seconds
            elif kind == FaultKind.PREEMPT_NOTICE:
                self._announce_preemption()
            elif kind in FaultKind.PROCESS_KINDS:
                self._kill_self(kind)
        out = self.trainer.fit_batch(ds)
        if self._slow_s:
            # the straggler drag: stretch THIS worker's step wall time
            # (never the math) so the launcher's peer-median detection
            # has a real slow host to flag
            self.sleep_fn(self._slow_s)
        return out

    def _announce_preemption(self) -> None:
        """SIGTERM self at the scheduled step — the ANNOUNCED failure:
        unlike _kill_self the process survives the signal; the installed
        PreemptionHandler flips its flag, this step completes normally,
        and the next step boundary runs the grace-window emergency
        checkpoint and exits PREEMPTED."""
        import signal
        self._log(self.step, FaultKind.PREEMPT_NOTICE,
                  f"SIGTERM (notice) pid {os.getpid()}")
        os.kill(os.getpid(), signal.SIGTERM)

    def _kill_self(self, kind: str) -> None:
        """Take down THIS worker process — SIGKILL (proc_kill/coord_kill)
        or SIGSTOP (proc_hang).  Self-injection makes the death exactly
        step-deterministic (no launcher-side polling race): the schedule
        says step k, the process is gone before step k runs.  The signal
        fires before any file I/O of the step, so checkpoints on disk stay
        atomic-rename-clean.  coord_kill is proc_kill aimed at the
        COORDINATOR process (process 0) — the distinct kind keeps the
        event log honest about WHAT died, because recovery differs:
        survivors must re-initialize against the restarted (or re-elected)
        coordinator, not just keep training."""
        import signal
        if kind == FaultKind.COORD_KILL:
            from .distributed import resolve_process_index
            if resolve_process_index() != 0:
                raise RuntimeError(
                    "coord_kill scheduled on a non-coordinator worker "
                    f"(process {resolve_process_index()}) — aim it at "
                    "process 0, the coordinator host")
        sig = (getattr(signal, "SIGSTOP", None)
               if kind == FaultKind.PROC_HANG else signal.SIGKILL)
        if sig is None:
            raise RuntimeError(f"{kind} unsupported on this platform "
                               "(no SIGSTOP)")
        self._log(self.step, kind,
                  f"{'SIGSTOP' if kind == FaultKind.PROC_HANG else 'SIGKILL'}"
                  f" pid {os.getpid()}")
        # flush logging AND the trace ring before the process vanishes
        # mid-statement — the proc_kill instant must survive into the
        # worker's trace file so the merged pod timeline shows the death
        # (docs/OBSERVABILITY.md "Reading a pod timeline")
        for h in logging.getLogger().handlers + logger.handlers:
            try:
                h.flush()
            except (OSError, ValueError):
                pass   # closed/broken stream — the process dies next line
        obs_trace.flush()
        os.kill(os.getpid(), sig)
        # SIGSTOP parks the process here until the launcher SIGKILLs (or
        # SIGCONTs) it; SIGKILL never returns


class ServingChaos:
    """Deterministic fault injection for the serving engine — the
    serving analog of :class:`ChaosInjector`.

    The schedule is keyed by the engine's GLOBAL batch-execution index
    (1-based: the first batch any replica dequeues is 1, counted across
    all replicas under a lock, so a schedule replays deterministically
    for a deterministic workload).  Only ENGINE-side kinds are legal
    here (``replica_crash``, ``replica_hang``); driver-side kinds
    (``poison_input``, ``bad_version``) are injected by the workload
    itself — see scripts/serving_chaos_soak.py.

    Arm it with ``Engine(..., chaos=ServingChaos(schedule))``.  A
    ``replica_crash`` raises out of the replica loop so the thread
    genuinely dies with its batch in limbo; a ``replica_hang`` parks the
    replica thread in a sleep longer than the engine's
    ``forward_timeout_s`` — both must be recovered by the supervisor.
    """

    def __init__(self, schedule: FaultSchedule, hang_seconds: float = 2.0,
                 sleep_fn: Callable[[float], None] = time.sleep):
        for kinds in schedule.faults.values():
            for kind in kinds:
                if kind not in FaultKind.SERVING_ENGINE_KINDS:
                    raise ValueError(
                        f"{kind!r} is not an engine-side serving fault — "
                        f"ServingChaos takes {FaultKind.SERVING_ENGINE_KINDS}"
                        "; poison_input/bad_version are injected by the "
                        "workload driver")
        self.schedule = schedule
        self.hang_seconds = hang_seconds
        self.sleep_fn = sleep_fn
        self.batch_index = 0
        self.events: List[dict] = []
        self._lock = threading.Lock()

    def pop_batch(self, replica_idx: int) -> List[str]:
        """Faults scheduled for the next global batch index, consumed.
        Called by every replica thread as it dequeues a batch."""
        with self._lock:
            self.batch_index += 1
            kinds = self.schedule.pop(self.batch_index)
            for kind in kinds:
                self.events.append({"batch": self.batch_index, "kind": kind,
                                    "replica": replica_idx,
                                    "t": time.monotonic()})
                obs_trace.instant("fault", cat="chaos", kind=kind,
                                  batch=self.batch_index,
                                  replica=replica_idx)
                logger.warning("serving chaos @batch %d: %s (replica %d)",
                               self.batch_index, kind, replica_idx)
        return kinds

    def injected(self, kind: Optional[str] = None) -> int:
        with self._lock:
            if kind is None:
                return len(self.events)
            return sum(1 for e in self.events if e["kind"] == kind)


class FleetChaos:
    """Deterministic fault injection for a serving FLEET — the fleet
    analog of :class:`ServingChaos`, keyed by the 1-based index of
    requests SUBMITTED to the router (not batches executed: the load
    harness is open-loop, so submission order is the deterministic,
    replayable axis — execution order under failover is not).

    All fleet kinds are driver-side: the load harness calls
    ``pop_request()`` before each submission and acts on what comes back
    (kill a host's engine, deliver a preemption notice, slow a host) —
    the :class:`~..serving.fleet.FleetRouter` under test only observes
    the consequences.  See scripts/fleet_load_soak.py.
    """

    def __init__(self, schedule: FaultSchedule,
                 clock: Callable[[], float] = time.monotonic):
        for kinds in schedule.faults.values():
            for kind in kinds:
                if kind not in FaultKind.FLEET_KINDS:
                    raise ValueError(
                        f"{kind!r} is not a fleet fault — FleetChaos takes "
                        f"{FaultKind.FLEET_KINDS}")
        self.schedule = schedule
        self.clock = clock
        self.request_index = 0
        self.events: List[dict] = []
        self._lock = threading.Lock()

    def pop_request(self) -> List[str]:
        """Faults scheduled for the next request index, consumed.
        Called by the load harness once per submitted request."""
        with self._lock:
            self.request_index += 1
            kinds = self.schedule.pop(self.request_index)
            for kind in kinds:
                self.events.append({"request": self.request_index,
                                    "kind": kind, "t": self.clock()})
                obs_trace.instant("fault", cat="chaos", kind=kind,
                                  request=self.request_index)
                logger.warning("fleet chaos @request %d: %s",
                               self.request_index, kind)
        return kinds

    def injected(self, kind: Optional[str] = None) -> int:
        with self._lock:
            if kind is None:
                return len(self.events)
            return sum(1 for e in self.events if e["kind"] == kind)


def _poison_dataset(ds):
    """A copy of ``ds`` whose features are all-NaN — the forward/backward
    then produces genuinely non-finite gradients, exercising the REAL
    divergence-guard path (not a simulated flag)."""
    feats = np.full_like(np.asarray(ds.features, dtype=np.float32), np.nan)
    clone = type(ds)(feats, ds.labels, ds.features_mask, ds.labels_mask)
    return clone
