"""Announced failures: preemption notices + grace-window emergency exit.

The chaos stack (chaos.py / launcher.py) models UNANNOUNCED death —
SIGKILL, SIGSTOP, torn writes.  Real pods mostly die the other way: the
scheduler *announces* maintenance/preemption and grants a grace window
(PAPERS.md: the TPU-supercomputer retrospective frames surviving these
announced events as the headline production problem).  Before this
module, a planned preemption was handled as a crash: full
heartbeat-timeout detection latency, a burned restart-budget slot, and
every step since the last interval checkpoint lost.

:class:`PreemptionHandler` is the announced path:

- ``install()`` catches SIGTERM/SIGUSR1 (the notice).  The signal
  handler only flips a flag and stamps the deadline — everything heavy
  runs at the next STEP BOUNDARY, where model state is consistent.
  A second notice is idempotent (schedulers re-signal).
- On notice it marks this worker **leaving** in the shared
  :class:`~.launcher.Membership` ledger, so survivors observe a fast
  LEAVE instead of waiting out the heartbeat timeout.
- ``check(trainer)`` — called by ``ElasticTrainer`` at every step
  boundary — runs the deadline-bounded **emergency checkpoint**: the
  in-memory :class:`~.elastic._HostSnapshot` is captured immediately
  (host RAM is safe even if devices are reclaimed mid-write), then
  written deflate-compressed when the remaining grace affords it, or
  uncompressed (``ZIP_STORED``) when it doesn't — a torn emergency
  checkpoint is worthless, a fat one is fine.  Then it raises
  :class:`PreemptedError`.
- The CLI/worker entry points convert ``PreemptedError`` into the
  distinct :data:`~.distributed.PREEMPTED_EXIT_CODE` so the launcher
  can tell a planned leave (relaunch WITHOUT consuming the restart
  budget) from a crash.

The grace budget comes from ``DL4J_TPU_GRACE_S`` (exported by the
launcher, overridable per worker) or the CLI ``--grace`` flag.
docs/FAULT_TOLERANCE.md "Announced failures" has the lifecycle table.
"""

from __future__ import annotations

import logging
import os
import signal
import time
from typing import Callable, Optional

from ..obs import trace as obs_trace
from ..obs.metrics import get_registry
from .distributed import (
    ENV_GRACE_S, ENV_RUN_DIR, PREEMPTED_EXIT_CODE, resolve_process_index,
)

logger = logging.getLogger("deeplearning4j_tpu")

#: default grace budget when neither env nor caller specifies one —
#: Cloud TPU / GCE preemption grants 30s
DEFAULT_GRACE_S = 30.0


class PreemptedError(RuntimeError):
    """This worker received a preemption notice and has written its
    emergency checkpoint — the process must now exit with
    :data:`PREEMPTED_EXIT_CODE`.  ``recoverable = False`` tells the
    elastic FailureDetector this is NOT a failure to retry: the host is
    going away and recovery belongs to the launcher (fast LEAVE +
    relaunch + ``ElasticTrainer.resume``)."""

    recoverable = False
    exit_code = PREEMPTED_EXIT_CODE

    def __init__(self, step: int, checkpoint_path: Optional[str] = None,
                 stored: bool = False, seconds: Optional[float] = None):
        where = (f"emergency checkpoint {os.path.basename(checkpoint_path)}"
                 f" ({'stored' if stored else 'deflate'}, {seconds:.2f}s)"
                 if checkpoint_path else "no emergency checkpoint "
                 "(non-writer host — state is replicated)")
        super().__init__(
            f"preempted at step {step}: {where}; exiting "
            f"{PREEMPTED_EXIT_CODE} (planned leave)")
        self.step = step
        self.checkpoint_path = checkpoint_path
        self.stored = stored
        self.seconds = seconds


class PreemptionHandler:
    """Catch preemption notices and drive the grace-window emergency
    checkpoint.  See the module docstring for the lifecycle.

    ``grace_s`` — seconds between notice and the host going away
    (default: ``DL4J_TPU_GRACE_S`` env, else 30).  ``membership`` /
    ``process_id`` — when set (or resolvable from the launcher env),
    the notice marks this worker *leaving* in the shared ledger.
    ``stored_floor_s`` and ``deflate_margin`` tune the codec decision:
    the deflate path is taken only when the remaining grace exceeds
    ``max(deflate_margin * last_save_seconds, stored_floor_s)`` — with
    no prior save measurement the floor alone decides.  ``clock`` is
    injectable for deterministic tests."""

    def __init__(self, grace_s: Optional[float] = None,
                 signals=(signal.SIGTERM, signal.SIGUSR1),
                 membership=None, process_id: Optional[int] = None,
                 stored_floor_s: float = 1.0,
                 deflate_margin: float = 3.0,
                 clock: Callable[[], float] = time.monotonic):
        if grace_s is None:
            grace_s = float(os.environ.get(ENV_GRACE_S, DEFAULT_GRACE_S))
        if grace_s <= 0:
            raise ValueError(f"grace_s must be > 0, got {grace_s}")
        self.grace_s = grace_s
        self.signals = tuple(signals)
        self.membership = membership
        self.process_id = resolve_process_index(process_id)
        self.stored_floor_s = stored_floor_s
        self.deflate_margin = deflate_margin
        self.clock = clock
        self.notice_count = 0
        self._notice_t: Optional[float] = None
        self._prev_handlers: dict = {}
        reg = get_registry()
        self._m_notices = reg.counter("preemption_notices_total")
        self._m_emergency = reg.counter("emergency_checkpoints_total")

    # -- signal plumbing ---------------------------------------------------

    def install(self) -> "PreemptionHandler":
        """Register the signal handlers (main thread only — Python's
        constraint); previous handlers are saved for ``uninstall``."""
        for sig in self.signals:
            self._prev_handlers[sig] = signal.signal(sig, self._on_signal)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass
        self._prev_handlers.clear()

    @classmethod
    def install_from_env(cls, grace_s: Optional[float] = None,
                         **kw) -> "PreemptionHandler":
        """The worker entry point's one-liner: grace from the env
        contract, leaving-marker wired to the launcher's run dir when
        present (standalone runs simply skip the ledger)."""
        membership = kw.pop("membership", None)
        if membership is None:
            run_dir = os.environ.get(ENV_RUN_DIR)
            if run_dir:
                from .launcher import Membership
                membership = Membership(run_dir)
        return cls(grace_s=grace_s, membership=membership, **kw).install()

    def _on_signal(self, signum, frame) -> None:
        self.notice(signum)

    # -- notice ------------------------------------------------------------

    @property
    def requested(self) -> bool:
        return self._notice_t is not None

    @property
    def remaining_s(self) -> float:
        """Grace budget left (full budget before any notice)."""
        if self._notice_t is None:
            return self.grace_s
        return self.grace_s - (self.clock() - self._notice_t)

    def notice(self, signum: Optional[int] = None) -> None:
        """Record a preemption notice.  Idempotent: the FIRST notice
        stamps the deadline and marks the ledger; repeats only count
        (schedulers re-signal, and the launcher may forward its own
        SIGTERM on top of the scheduler's)."""
        self.notice_count += 1
        if self._notice_t is not None:
            logger.info("preemption notice repeated (%d) — deadline "
                        "unchanged, %.1fs remaining", self.notice_count,
                        self.remaining_s)
            return
        self._notice_t = self.clock()
        self._m_notices.inc()
        obs_trace.instant("preempt/notice", cat="preempt",
                          signum=signum, grace_s=self.grace_s,
                          process=self.process_id)
        logger.warning("preemption notice (signal %s): %.1fs grace — "
                       "emergency checkpoint at the next step boundary",
                       signum, self.grace_s)
        if self.membership is not None:
            try:
                self.membership.mark_leaving(self.process_id,
                                             grace_s=self.grace_s)
            except OSError as exc:   # ledger gone — notice still stands
                logger.debug("leaving marker write failed: %s", exc)

    # -- the grace-window exit ---------------------------------------------

    def check(self, trainer) -> None:
        """Step-boundary hook (``ElasticTrainer`` calls this before every
        step): no-op until a notice arrived, then emergency-checkpoint
        and raise :class:`PreemptedError`."""
        if self._notice_t is None:
            return
        path, stored, seconds = self.emergency_checkpoint(
            trainer.ckpt, trainer.net, trainer.global_step)
        if hasattr(trainer, "_record_durable"):
            trainer._record_durable(trainer.global_step, path)
        raise PreemptedError(trainer.global_step, path, stored, seconds)

    def emergency_checkpoint(self, ckpt, net, step: int):
        """Deadline-bounded checkpoint: snapshot NOW, then pick the codec
        the remaining grace affords.  → (path | None, used_stored,
        seconds)."""
        from .elastic import _HostSnapshot

        t0 = self.clock()
        with obs_trace.span("ckpt/emergency", cat="ckpt", step=step,
                            grace_s=self.grace_s) as sp:
            # host copy first: device buffers may be reclaimed any moment
            snap = _HostSnapshot(net)
            remaining = self.remaining_s
            deflate_cost = max(
                self.deflate_margin * (ckpt.last_save_seconds or 0.0),
                self.stored_floor_s)
            stored = remaining < deflate_cost
            path = ckpt.save_snapshot(snap, step, compressed=not stored,
                                      prune=False)
            seconds = self.clock() - t0
            self._m_emergency.inc()
            sp.set(stored=stored, seconds=round(seconds, 3),
                   within_grace=seconds <= self.grace_s,
                   path=os.path.basename(path) if path else None)
        logger.warning(
            "emergency checkpoint @%d: %s in %.2fs (%.1fs of grace left)",
            step, (os.path.basename(path) if path
                   else "skipped (non-writer)"), seconds,
            self.remaining_s)
        return path, stored, seconds
