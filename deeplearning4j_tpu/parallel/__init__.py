"""Parallelism (L4): mesh builders + sharded training + parallel inference.

Replaces the reference's entire scale-out menu (SURVEY.md §2.3) with ONE
mechanism: a jax.sharding.Mesh + GSPMD-partitioned jit programs.

  reference ParallelWrapper (model clones + averaging every N iters,
    parallelism/ParallelWrapper.java:58,326)           → data-axis sharding;
    the per-step psum XLA inserts is mathematically the averagingFrequency=1
    case, strictly stronger
  reference Spark ParameterAveragingTrainingMaster     → same code path,
    multi-host via jax.distributed
  reference SharedTrainingMaster (threshold-compressed
    gradients over Aeron UDP, SharedTrainingMaster.java:55)
                                                       → TWO-tier exchange:
    dense grad allreduce over the intra-slice ICI axis (where bandwidth
    makes compression pointless) + opt-in compressed exchange over the
    cross-slice ``dcn`` axis, whose DCN links are orders of magnitude
    slower — ``ShardedTrainer(grad_compression="threshold"|"bitmap")``
    is the EncodingHandler thresholdEncode/bitmapEncode analog, with the
    reference's error-feedback residual (ops/compression.py,
    docs/PARALLELISM.md "Gradient compression over DCN")
  reference ParameterServerTrainer                     → subsumed by
    collectives (documented non-goal)
  reference CheckpointListener + Spark task-retry      → ElasticTrainer
    (checkpoint-restore recovery with backoff+jitter, step watchdog,
    divergence guard) — chaos-tested by deterministic fault injection
    (chaos.py, scripts/chaos_soak.py, docs/FAULT_TOLERANCE.md)
  reference Spark TrainingMaster cluster entry point
    (VoidConfiguration controller address + shard index) → the pod-scale
    elastic runtime (launcher.py + CLI ``launch``): a multi-process
    launcher with heartbeat membership epochs, host join/leave recovery
    (relaunch + ElasticTrainer.resume from the shared checkpoint store),
    process-kill chaos (FaultKind.PROC_KILL/PROC_HANG), and ANNOUNCED
    failures (preemption.py: SIGTERM notice → grace-window emergency
    checkpoint → PREEMPTED exit relaunched without burning the restart
    budget; coordinator restart/failover; straggler flagging —
    docs/FAULT_TOLERANCE.md "Announced failures")
  TP / PP / SP — absent in the reference — are first-class here.

Inference serving moved to the ``serving/`` subsystem (deadline-aware
batching, AOT warmup, replicas, versioned hot-swap, admission control —
docs/SERVING.md); the ``ParallelInference`` exported here is a thin
back-compat shim over one ``serving.Engine``.
"""

from .mesh import (
    build_mesh, build_two_tier_mesh, replicated, shard_batch,
    infer_param_shardings, surviving_mesh,
)
from .trainer import ShardedTrainer
from .inference import ParallelInference
from .ring import ring_attention, ring_self_attention
from .ulysses import ulysses_attention, ulysses_self_attention
from .pipeline import (
    pipeline_apply, pipeline_schedule_stats, stack_stage_params,
    stage_sharding,
)
from .transformer import ShardedTransformerLM
from .elastic import (
    CheckpointManager, ElasticTrainer, FailureDetector,
    RecoverableInfraError, StepHangError,
)
from .chaos import (
    ChaosInjector, FaultKind, FaultSchedule, FleetChaos, ServingChaos,
    bitflip_file, truncate_file,
)
from .moe import MoE, init_moe_params, moe_forward_dense, moe_forward_ep
from .distributed import (
    CoordinatorUnreachableError, PREEMPTED_EXIT_CODE, detect_num_slices,
    initialize, is_coordinator, local_batch_slice,
    probe_multiprocess_support, process_count, process_index,
    reinitialize, resolve_process_index, validate_coordinator_address,
)
from .launcher import (
    Heartbeat, HostLostError, Membership, MembershipChangedError,
    PodLauncher, ProcessFailureDetector, elect_coordinator,
    maybe_bootstrap_from_env,
)
from .preemption import PreemptedError, PreemptionHandler
