"""Mesh + sharding utilities — the collectives layer (SURVEY.md §7 M0).

The reference's communication backends (libnd4j device copies +
`Nd4j.averageAndPropagate`, Aeron UDP VoidParameterServer — SURVEY.md §5
"Distributed communication backend") are replaced by a device mesh with
named axes; XLA GSPMD inserts the psum/all-gather/reduce-scatter collectives
that ride ICI intra-slice and DCN across slices.

Axis convention: ``data`` (DP), ``model`` (TP), ``seq`` (SP/CP),
``pipe`` (PP), ``dcn`` (cross-slice DP).  Build a mesh with the axes you
use; absent axes = size 1.

The two-tier interconnect is first-class: axes over devices WITHIN a TPU
slice ride the ICI (fast — dense collectives are free at that bandwidth),
while an outer ``dcn`` axis spans slices over the data-center network,
which is orders of magnitude slower — the tier where
``ShardedTrainer(grad_compression=...)`` swaps the dense psum for the
compressed exchange (ops/compression.py).  ``build_two_tier_mesh`` builds
the slice-major device layout so consecutive devices (ICI neighbors on
Cloud TPU) land in the same slice row.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.jax_compat import vary_over  # noqa: F401  (re-export: the
# historical home of vary_over; pipeline/ring import it from here)

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
DCN_AXIS = "dcn"


def build_mesh(axes: Optional[Dict[str, int]] = None,
               devices: Optional[Sequence] = None) -> Mesh:
    """Create a Mesh from {axis_name: size}.  Default: all local devices on
    the data axis (the ParallelWrapper-equivalent ceremony: one line).

    Sizes must multiply to the device count; use -1 for one inferred axis.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    axes = dict(axes) if axes else {DATA_AXIS: n}
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"mesh axes {dict(zip(names, sizes))} != {n} devices")
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, names)


def build_two_tier_mesh(n_slices: int,
                        axes: Optional[Dict[str, int]] = None,
                        devices: Optional[Sequence] = None) -> Mesh:
    """Mesh with an OUTER ``dcn`` axis of ``n_slices`` plus inner ICI axes
    (default: all remaining devices on ``data``).

    The dcn axis is placed first so each slice's devices form one
    contiguous row — on Cloud TPU, ``jax.devices()`` orders devices
    slice-major, so the row boundary is the real ICI/DCN boundary.  Pair
    with ``ShardedTrainer(grad_compression=...)`` to compress the
    cross-slice gradient exchange; ``distributed.detect_num_slices()``
    reads the multislice runtime's slice count."""
    if n_slices < 1:
        raise ValueError(f"n_slices must be >= 1, got {n_slices}")
    inner = dict(axes) if axes else {DATA_AXIS: -1}
    if DCN_AXIS in inner:
        raise ValueError("pass the dcn size as n_slices, not in axes")
    return build_mesh({DCN_AXIS: n_slices, **inner}, devices)


def surviving_mesh(alive_slices: Sequence[int], n_slices: int,
                   axes: Optional[Dict[str, int]] = None,
                   devices: Optional[Sequence] = None) -> Mesh:
    """Two-tier mesh over the devices of the SURVIVING slices only —
    slice-granular recovery: when a host/slice leaves the membership, the
    job re-provisions a (possibly smaller ``dcn``) mesh over what's left
    instead of dying or waiting for the full pod to return.

    ``alive_slices`` are slice row indices into the ORIGINAL ``n_slices``
    slice-major device order (the layout ``build_two_tier_mesh`` assumes);
    inner axes default to ``data=-1`` over each slice's devices.  Restore
    the newest checkpoint after rebuilding — params placed for the old
    mesh don't transfer (``ElasticTrainer(rebuild_fn=...)`` wires both
    steps into one recovery)."""
    devs = list(devices if devices is not None else jax.devices())
    if n_slices < 1 or len(devs) % n_slices:
        raise ValueError(f"{len(devs)} devices not divisible into "
                         f"{n_slices} slices")
    alive = sorted(set(int(s) for s in alive_slices))
    if not alive:
        raise ValueError("no surviving slices — nothing to rebuild on")
    if alive[0] < 0 or alive[-1] >= n_slices:
        raise ValueError(f"alive slices {alive} out of range "
                         f"[0, {n_slices})")
    per = len(devs) // n_slices
    keep = [d for s in alive for d in devs[s * per:(s + 1) * per]]
    return build_two_tier_mesh(len(alive), axes, keep)


def put_global(arr, sharding: NamedSharding):
    """Place a host array onto a (possibly multi-process) sharding.

    Single-process: plain ``device_put``.  Multi-process: ``device_put``
    cannot target non-addressable devices, so the global array is built
    from per-shard callbacks — each process materializes only the rows its
    local devices own (replicated specs read the same full array
    everywhere).  Callers pass the GLOBAL array on every host; per-host
    disjoint loading composes via ``distributed.local_batch_slice``."""
    import numpy as np
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    a = np.asarray(arr)
    return jax.make_array_from_callback(a.shape, sharding, lambda idx: a[idx])


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch_axis: str = DATA_AXIS):
    """Sharding for [batch, ...] arrays: batch split on the data axis."""
    return NamedSharding(mesh, P(batch_axis))


def _shard_leaf(mesh: Mesh, arr, model_axis: str, min_size: int = 2):
    """Tensor-parallel rule for one weight tensor: shard the largest axis
    divisible by the model-axis size; replicate if none divides.

    This is the generic Megatron-ish default — XLA GSPMD propagates the
    choice through the graph and inserts the all-gathers/reduce-scatters.
    Layer-specific overrides can refine it later without changing callers.
    """
    msize = mesh.shape.get(model_axis, 1)
    if msize <= 1 or arr.ndim == 0:
        return NamedSharding(mesh, P())
    # prefer trailing axes (output features) — weight layouts here are
    # [in, out] / HWIO, so the last axis is the output-feature axis
    for ax in reversed(range(arr.ndim)):
        if arr.shape[ax] % msize == 0 and arr.shape[ax] >= msize * min_size:
            spec = [None] * arr.ndim
            spec[ax] = model_axis
            return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())


def infer_param_shardings(params, mesh: Mesh, model_axis: str = MODEL_AXIS):
    """Pytree of NamedShardings for a params tree (TP rules, DP-replicated)."""
    return jax.tree_util.tree_map(lambda a: _shard_leaf(mesh, a, model_axis), params)
