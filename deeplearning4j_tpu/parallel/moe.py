"""Mixture-of-Experts with expert parallelism (EP).

No reference analog (DL4J predates MoE); SURVEY §2.3 lists EP as the
remaining first-class TPU parallelism axis.  Design follows the
Shazeer/Switch lineage the TPU stack was built around:

  - router: tokens → top-k experts (softmax over the selected logits)
  - experts: per-expert FFN [d_model → d_ff → d_model], params stacked on
    a leading expert dim so ALL experts compute as one batched einsum
    (MXU-shaped, no ragged work)
  - EP sharding: experts split over a mesh axis inside ``shard_map``;
    tokens stay replicated on that axis, each shard computes only its
    local experts' capacity slots, and one ``psum`` merges expert
    contributions — collective traffic = activations once per layer,
    the standard replicated-token/sharded-expert formulation
  - capacity: fixed per-expert slots (ceil(k·N/E·capacity_factor));
    overflow tokens are dropped by the dispatch one-hot exactly as in
    Switch — keeps every shape static for XLA

``moe_forward_dense`` is the exact (every expert sees every token's
gate-weighted input) single-device path used for parity tests and the
``MoE`` layer; ``moe_forward_ep`` is the sharded production path.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.jax_compat import shard_map

Array = jax.Array


def init_moe_params(rng: Array, d_model: int, d_ff: int, n_experts: int,
                    dtype=jnp.float32) -> Dict[str, Array]:
    k1, k2, k3 = jax.random.split(rng, 3)
    s_in = (2.0 / d_model) ** 0.5
    s_ff = (2.0 / d_ff) ** 0.5
    return {
        "Wg": jax.random.normal(k1, (d_model, n_experts), dtype) * s_in,
        "W1": jax.random.normal(k2, (n_experts, d_model, d_ff), dtype) * s_in,
        "b1": jnp.zeros((n_experts, d_ff), dtype),
        "W2": jax.random.normal(k3, (n_experts, d_ff, d_model), dtype) * s_ff,
        "b2": jnp.zeros((n_experts, d_model), dtype),
    }


def _router(params, x, k: int):
    """→ (gates [N,E] with nonzeros only on the top-k, aux load-balance
    loss).  Gates renormalize softmax over the selected logits (Shazeer
    2017); aux loss is the Switch E·Σ f_e·p_e balance term."""
    logits = x @ params["Wg"].astype(x.dtype)            # [N,E]
    E = logits.shape[-1]
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(logits, k)                # [N,k]
    gate_v = jax.nn.softmax(topv, axis=-1)               # renormalized
    gates = jnp.zeros_like(probs).at[
        jnp.arange(x.shape[0])[:, None], topi].set(gate_v)
    # load balance: fraction routed vs mean prob per expert
    frac = jnp.mean((gates > 0).astype(x.dtype), axis=0)  # [E]
    mean_p = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac * mean_p)
    return gates, aux


def moe_forward_dense(params: Dict[str, Array], x: Array, k: int = 2
                      ) -> Tuple[Array, Array]:
    """Exact MoE: every expert processes every token, outputs combined by
    the (sparse) gates.  O(E·N·d·f) — the test/teaching path.
    x [N, d_model] → (y [N, d_model], aux_loss)."""
    gates, aux = _router(params, x, k)
    h = jnp.einsum("nd,edf->nef", x, params["W1"].astype(x.dtype))
    h = jax.nn.relu(h + params["b1"].astype(x.dtype)[None])
    y_e = jnp.einsum("nef,efd->ned", h, params["W2"].astype(x.dtype))
    y_e = y_e + params["b2"].astype(x.dtype)[None]
    y = jnp.einsum("ne,ned->nd", gates, y_e)
    return y, aux


def capacity(n_tokens: int, n_experts: int, k: int,
             capacity_factor: float = 1.25) -> int:
    """Per-expert token slots (Switch capacity), computed statically."""
    return max(1, int(np.ceil(k * n_tokens / n_experts * capacity_factor)))


def moe_forward_ep(params: Dict[str, Array], x: Array, mesh: Mesh,
                   expert_axis: str = "model", k: int = 2,
                   capacity_factor: float = 1.25,
                   data_axis: Optional[str] = "data") -> Tuple[Array, Array]:
    """Expert-parallel MoE over ``expert_axis``, DP-composable.

    Experts are sharded over ``expert_axis``; tokens are sharded over
    ``data_axis`` (when the mesh has one) and replicated over the expert
    axis.  Each shard builds dispatch/combine one-hots for its LOCAL
    experts on its LOCAL tokens, computes its capacity slots, and a psum
    over the expert axis merges the gate-weighted expert outputs.
    Capacity is per data shard (each shard routes its own tokens).
    Dropped (over-capacity) tokens contribute zero, exactly like Switch.
    """
    E = params["Wg"].shape[-1]
    M = mesh.shape[expert_axis]
    if E % M:
        raise ValueError(f"n_experts {E} not divisible by {expert_axis} "
                         f"axis size {M}")
    if data_axis is not None and data_axis not in mesh.shape:
        data_axis = None
    D = mesh.shape[data_axis] if data_axis else 1
    N = x.shape[0]
    if N % D:
        raise ValueError(f"token count {N} not divisible by {data_axis} "
                         f"axis size {D}")
    C = capacity(N // D, E, k, capacity_factor)
    e_loc = E // M

    expert_keys = ("W1", "b1", "W2", "b2")
    in_specs = (
        {kk: (P(expert_axis) if kk in expert_keys else P())
         for kk in params},
        P(data_axis),   # tokens sharded over data, replicated over experts
    )
    out_specs = (P(data_axis), P())

    def shard_fn(p, xs):
        idx = jax.lax.axis_index(expert_axis)
        gates, aux = _router(p, xs, k)          # identical across expert axis
        aux = aux / M                           # psum'd below → global value
        if data_axis:
            aux = jax.lax.pmean(aux, data_axis)  # average over token shards
        local_gates = jax.lax.dynamic_slice_in_dim(
            gates, idx * e_loc, e_loc, axis=1)  # [N, e_loc]
        # position of each token within its expert's capacity buffer:
        # cumulative count of prior routed tokens for that expert
        routed = (local_gates > 0).astype(jnp.int32)          # [N, e_loc]
        pos = jnp.cumsum(routed, axis=0) - routed             # [N, e_loc]
        keep = routed * (pos < C)
        # dispatch one-hot [N, e_loc, C]
        disp = keep[..., None] * jax.nn.one_hot(pos, C, dtype=xs.dtype)
        exp_in = jnp.einsum("nec,nd->ecd", disp, xs)          # [e_loc, C, d]
        # expert params cast to the token dtype — same mixed-precision
        # contract as moe_forward_dense
        W1, b1 = p["W1"].astype(xs.dtype), p["b1"].astype(xs.dtype)
        W2, b2 = p["W2"].astype(xs.dtype), p["b2"].astype(xs.dtype)
        h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", exp_in, W1)
                        + b1[:, None, :])
        out = jnp.einsum("ecf,efd->ecd", h, W2) + b2[:, None, :]
        combine = disp * local_gates[..., None]               # gate-weighted
        y_local = jnp.einsum("nec,ecd->nd", combine, out)
        y = jax.lax.psum(y_local, expert_axis)
        return y, jax.lax.psum(aux, expert_axis)

    fn = shard_map(shard_fn, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs)
    return fn(params, x)


# ---------------------------------------------------------------------------
# layer wrapper (single-device / GSPMD path)
# ---------------------------------------------------------------------------

from ..nn.conf.inputs import InputType          # noqa: E402
from ..nn.layers.base import (                  # noqa: E402
    AUX_LOSS_KEY, ForwardOut, Layer, register_layer,
)


@register_layer
@dataclasses.dataclass
class MoE(Layer):
    """Mixture-of-Experts FFN layer (exact dense combine; use
    ``moe_forward_ep`` / ShardedTransformerLM for the sharded path).
    Accepts [mb, d] or [mb, t, d] (applied per token).

    The Switch load-balance auxiliary loss rides the ``AUX_LOSS_KEY``
    state slot, which the containers add to the training objective —
    without it the router can collapse onto one expert."""

    n_in: int = 0
    d_ff: int = 0
    n_experts: int = 4
    top_k: int = 2
    aux_weight: float = 0.01

    def infer_nin(self, in_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = in_type.size
        if self.d_ff == 0:
            self.d_ff = 4 * self.n_in

    def output_type(self, in_type: InputType) -> InputType:
        return in_type

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        return init_moe_params(rng, self.n_in, self.d_ff, self.n_experts, dtype)

    def init_state(self, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        return {AUX_LOSS_KEY: jnp.zeros((), jnp.float32)}

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        x = self._maybe_dropout(x, train, rng)
        shape = x.shape
        flat = x.reshape(-1, shape[-1])
        y, aux = moe_forward_dense(params, flat, self.top_k)
        new_state = dict(state)
        new_state[AUX_LOSS_KEY] = (self.aux_weight * aux).astype(jnp.float32)
        return ForwardOut(self._act(y.reshape(shape)), new_state, mask)
