"""ShardedTransformerLM — the 4D-parallel (DP×TP×SP×PP) training step.

The north-star composition mandated by SURVEY.md §7-M5, with no reference
analog (DL4J's only distributed axis is DP — §2.3): one jitted XLA program
per step in which

  - ``data``  shards the batch (grad psum inserted by shard_map transpose),
  - ``model`` tensor-parallels attention heads + FFN columns
    (Megatron-style column/row split with an explicit psum),
  - ``seq``   shards the sequence; attention runs as ring attention with
    K/V blocks rotating over ICI (parallel/ring.py),
  - ``pipe``  pipelines the homogeneous block stack with a GPipe or
    interleaved-1F1B microbatch schedule (parallel/pipeline.py,
    ``schedule=`` ctor flag).

Embedding/head run under GSPMD outside the manual shard_map island; the
block math is models/transformer.block_apply — the same function the
single-chip TransformerBlock layer uses.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.transformer import block_apply, block_params
from ..utils.jax_compat import set_mesh
from ..nn.updaters import Adam
from .pipeline import SCHEDULES, pipeline_apply, stack_stage_params
from .ring import ring_attention

Array = jax.Array


def _block_tp_specs(pipe: str = "pipe", model: str = "model"):
    """Per-leaf PartitionSpecs for stacked block params: column-parallel
    q/k/v/FFN-up, row-parallel o/FFN-down (psum after), norms replicated."""
    return {
        "ln1_g": P(pipe, None), "ln1_b": P(pipe, None),
        "Wq": P(pipe, None, model), "Wk": P(pipe, None, model),
        "Wv": P(pipe, None, model),
        "Wo": P(pipe, model, None), "bo": P(pipe, None),
        "ln2_g": P(pipe, None), "ln2_b": P(pipe, None),
        "W1": P(pipe, None, model), "b1": P(pipe, model),
        "W2": P(pipe, model, None), "b2": P(pipe, None),
    }


class ShardedTransformerLM:
    """Decoder-only LM trained with DP×TP×SP×PP over a named mesh.

    >>> mesh = build_mesh({"data": 2, "model": 2, "seq": 2, "pipe": 1})
    >>> lm = ShardedTransformerLM(vocab_size=256, n_layers=4, d_model=128,
    ...                           n_heads=8, mesh=mesh)
    >>> loss = lm.fit_batch(tokens, targets)   # [B,T] int32 each
    """

    def __init__(self, vocab_size: int, n_layers: int, d_model: int,
                 n_heads: int, mesh: Mesh, d_ff: int = 0, max_len: int = 512,
                 n_microbatches: int = 2, seed: int = 0, updater=None,
                 compute_dtype=None, seq_parallel: str = "ring",
                 attention_impl: str = "flash", schedule: str = "gpipe"):
        d_ff = d_ff or 4 * d_model
        # normalize to the canonical 4-axis mesh (absent axes = size 1) so
        # specs/collectives can reference every axis unconditionally
        canonical = ("data", "model", "seq", "pipe")
        unknown = [n for n in mesh.axis_names if n not in canonical]
        if unknown:
            raise ValueError(f"unexpected mesh axes {unknown}; use {canonical}")
        if tuple(mesh.axis_names) != canonical:
            from .mesh import build_mesh
            mesh = build_mesh({n: mesh.shape.get(n, 1) for n in canonical},
                              devices=mesh.devices.flatten())
        tp = mesh.shape.get("model", 1)
        if n_heads % tp:
            raise ValueError(f"n_heads {n_heads} not divisible by model={tp}")
        if seq_parallel not in ("ring", "ulysses"):
            raise ValueError(f"seq_parallel must be 'ring' or 'ulysses', "
                             f"got {seq_parallel!r}")
        if seq_parallel == "ulysses" and \
                (n_heads // tp) % mesh.shape.get("seq", 1):
            raise ValueError(
                f"ulysses scatters heads over seq={mesh.shape.get('seq', 1)} "
                f"but only {n_heads // tp} heads remain after TP — use "
                "seq_parallel='ring' or raise n_heads")
        self.seq_parallel = seq_parallel
        if attention_impl not in ("flash", "xla"):
            raise ValueError(f"attention_impl must be 'flash' or 'xla', "
                             f"got {attention_impl!r}")
        if attention_impl == "xla" and mesh.shape.get("seq", 1) > 1:
            raise ValueError(
                "attention_impl='xla' requires seq=1 — the sequence-"
                "parallel paths (ring/ulysses) are built on the blockwise/"
                "flash update and cannot honor plain einsum attention")
        # mirrors TransformerBlock.kernel: "flash" = fused pallas kernels;
        # "xla" = plain einsum attention on the single-device seq path
        self.attention_impl = attention_impl
        if n_layers % mesh.shape.get("pipe", 1):
            raise ValueError(
                f"n_layers {n_layers} not divisible by pipe={mesh.shape['pipe']}")
        if schedule not in SCHEDULES:
            raise ValueError(f"schedule must be one of {SCHEDULES}, "
                             f"got {schedule!r}")
        # microbatch order on the pipe axis: "gpipe" = all-forward-then-
        # all-backward; "1f1b" = interleaved, depth-bounded activation
        # memory at a recompute cost (parallel/pipeline.py docstring)
        self.schedule = schedule
        self.mesh = mesh
        self.vocab_size = vocab_size
        self.n_heads = n_heads
        self.n_heads_local = n_heads // tp
        self.n_microbatches = n_microbatches
        self.compute_dtype = compute_dtype
        self.updater = updater or Adam(lr=3e-4)
        self.iteration = 0

        rng = jax.random.PRNGKey(seed)
        ke, kp, kh, *kb = jax.random.split(rng, 3 + n_layers)
        blocks = stack_stage_params(
            [block_params(k, d_model, n_heads, d_ff) for k in kb])
        params = {
            "embed": 0.02 * jax.random.normal(ke, (vocab_size, d_model)),
            "pos": 0.02 * jax.random.normal(kp, (max_len, d_model)),
            "blocks": blocks,
            "lnf_g": jnp.ones((d_model,)), "lnf_b": jnp.zeros((d_model,)),
            "head": 0.02 * jax.random.normal(kh, (d_model, vocab_size)),
        }
        self.block_specs = _block_tp_specs()
        shardings = {
            "embed": NamedSharding(mesh, P(None, None)),
            "pos": NamedSharding(mesh, P(None, None)),
            "blocks": {k: NamedSharding(mesh, s)
                       for k, s in self.block_specs.items()},
            "lnf_g": NamedSharding(mesh, P()), "lnf_b": NamedSharding(mesh, P()),
            "head": NamedSharding(mesh, P(None, "model")),
        }
        self.params = jax.device_put(params, shardings)
        # optimizer state mirrors params structurally → same shardings
        opt = self.updater.init_state(params)
        self.opt_state = jax.device_put(opt, self._opt_shardings(opt, shardings))
        self.token_sharding = NamedSharding(mesh, P("data", "seq"))
        self._jit_step = None
        self._jit_multi_step = None
        self._jit_logits = None

    def _opt_shardings(self, opt, param_shardings):
        """Each opt-state subtree ('m'/'v'/...) mirrors the params tree."""
        def place(sub):
            if jax.tree_util.tree_structure(sub) == \
                    jax.tree_util.tree_structure(param_shardings):
                return param_shardings
            return jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, P()), sub)
        return {k: place(v) for k, v in opt.items()}

    # -- forward -----------------------------------------------------------

    def _forward(self, params, tokens):
        cd = self.compute_dtype
        embed = params["embed"] if cd is None else params["embed"].astype(cd)
        pos = params["pos"] if cd is None else params["pos"].astype(cd)
        h = embed[tokens] + pos[: tokens.shape[1]]
        blocks = params["blocks"] if cd is None else jax.tree_util.tree_map(
            lambda a: a.astype(cd), params["blocks"])

        if self.mesh.shape.get("seq", 1) == 1:
            # degenerate SP: single-device attention — O(T) saved residuals
            # (o + lse) per layer, where the ring's blockwise-XLA path
            # would checkpoint full [T,T] probability tiles
            if self.attention_impl == "xla":
                from ..ops.attention import mha
                attn = functools.partial(mha, causal=True)
            else:
                from ..ops.attention import flash_mha
                attn = functools.partial(flash_mha, causal=True)
        elif self.seq_parallel == "ulysses":
            from .ulysses import ulysses_attention
            attn = functools.partial(ulysses_attention, axis_name="seq",
                                     causal=True)
        else:
            attn = functools.partial(ring_attention, axis_name="seq",
                                     causal=True)
        block_fn = functools.partial(
            block_apply, n_heads=self.n_heads_local, causal=True,
            attention_fn=attn,
            psum_axis="model" if self.mesh.shape.get("model", 1) > 1 else None)

        if self.mesh.shape.get("pipe", 1) == 1 and \
                self.mesh.shape.get("seq", 1) == 1 and \
                self.mesh.shape.get("model", 1) == 1:
            # (model==1 too: block_fn's TP psums need the axis bound, which
            # only pipeline_apply's shard_map provides)
            # no pipeline/ring stage structure → unroll the block stack
            # instead of scanning it: XLA schedules each layer's fusions
            # independently (no dynamic-update-slice stacking of residuals,
            # no loop-carried copies — measured ~15% step time on the
            # single-chip TransformerLM bench, docs/transformer_profile.md)
            n_layers = jax.tree_util.tree_leaves(blocks)[0].shape[0]
            for i in range(n_layers):
                h = block_fn(jax.tree_util.tree_map(lambda a: a[i], blocks), h)
        else:
            h = pipeline_apply(
                lambda p, h: block_fn(p, h), blocks, h, self.mesh,
                n_microbatches=self.n_microbatches,
                schedule=self.schedule,
                param_specs=self.block_specs,
                x_spec=P("data", "seq", None))
        from ..nn.layers.normalization import layer_norm
        h = layer_norm(h, params["lnf_g"].astype(h.dtype),
                       params["lnf_b"].astype(h.dtype))
        head = params["head"] if cd is None else params["head"].astype(cd)
        return h @ head  # [B, T, V] logits

    def _loss(self, params, tokens, targets):
        from ..ops.losses import sparse_softmax_xent
        logits = self._forward(params, tokens)
        return sparse_softmax_xent(logits, targets)

    # -- training ----------------------------------------------------------

    def _build_step(self):
        updater = self.updater

        def step(params, opt_state, it, tokens, targets):
            loss, grads = jax.value_and_grad(self._loss)(params, tokens, targets)
            updates, new_opt = updater.update(grads, opt_state, it)
            new_params = jax.tree_util.tree_map(
                lambda p, u: (p - u.astype(p.dtype)), params, updates)
            return new_params, new_opt, loss

        return jax.jit(step, donate_argnums=(0, 1))

    def fit_batch(self, tokens: np.ndarray, targets: np.ndarray):
        if self._jit_step is None:
            self._jit_step = self._build_step()
        tokens = jax.device_put(jnp.asarray(tokens, jnp.int32), self.token_sharding)
        targets = jax.device_put(jnp.asarray(targets, jnp.int32), self.token_sharding)
        with set_mesh(self.mesh):
            self.params, self.opt_state, loss = self._jit_step(
                self.params, self.opt_state,
                jnp.asarray(self.iteration, jnp.int32), tokens, targets)
        self.iteration += 1
        from ..optimize.score import LazyScore
        return LazyScore(loss)

    def _build_multi_step(self):
        """k train steps fused into one dispatch via lax.scan (round-4
        verdict Next #5: the profile's 12.6% device-IDLE bucket is the
        per-step dispatch gap through the tunnel; k-chaining amortizes it
        to 1/k).  Identical math to k fit_batch calls — sequential
        optimizer steps, per-step iteration counter."""
        updater = self.updater

        def multi(params, opt_state, it0, toks, tgts):
            its = it0 + jnp.arange(toks.shape[0], dtype=jnp.int32)

            def body(carry, inp):
                params, opt = carry
                tok, tgt, it = inp
                loss, grads = jax.value_and_grad(self._loss)(params, tok, tgt)
                updates, opt = updater.update(grads, opt, it)
                params = jax.tree_util.tree_map(
                    lambda p, u: (p - u.astype(p.dtype)), params, updates)
                return (params, opt), loss

            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state), (toks, tgts, its))
            return params, opt_state, losses

        return jax.jit(multi, donate_argnums=(0, 1))

    def fit_batches(self, tokens: np.ndarray, targets: np.ndarray):
        """k steps in ONE dispatch: ``tokens``/``targets`` are [k, B, T]
        (k stacked minibatches).  Returns [k] LazyScores."""
        if self._jit_multi_step is None:
            self._jit_multi_step = self._build_multi_step()
        stacked = NamedSharding(self.mesh, P(None, "data", "seq"))
        tokens = jax.device_put(jnp.asarray(tokens, jnp.int32), stacked)
        targets = jax.device_put(jnp.asarray(targets, jnp.int32), stacked)
        k = tokens.shape[0]
        with set_mesh(self.mesh):
            self.params, self.opt_state, losses = self._jit_multi_step(
                self.params, self.opt_state,
                jnp.asarray(self.iteration, jnp.int32), tokens, targets)
        self.iteration += k
        from ..optimize.score import LazyScore
        return [LazyScore(losses[i]) for i in range(k)]

    def logits(self, tokens: np.ndarray) -> Array:
        if self._jit_logits is None:
            self._jit_logits = jax.jit(self._forward)
        tokens = jax.device_put(jnp.asarray(tokens, jnp.int32), self.token_sharding)
        with set_mesh(self.mesh):
            return self._jit_logits(self.params, tokens)

    # -- autoregressive decode (serving/decode.py) -------------------------

    def decode_program(self, page_size: int = 16,
                       max_len: Optional[int] = None):
        """Pure prefill / decode-step / re-encode functions over the
        paged KV-cache (ops/kv_cache.py) for the serving decode engine.

        The decode path is a different execution mode from training —
        stateful, one query row per step — but shares the block weights
        and the block math split (models/transformer.block_kv_project /
        block_finish), and uses ops/kv_cache.det_attention so the
        incremental logits are BIT-identical to ``reencode`` of the same
        tokens (the ``continuous_batching_ab`` gate).

        On a multi-device mesh (all devices folded into the ``data``
        axis) the program is TENSOR-PARALLEL: every entry point is
        shard_map'd with attention heads split over ``data``, the page
        pool sharded to match (each device holds 1/n of the KV bytes),
        an explicit psum after the row-parallel output projection, and
        logits replicated so the samplers see the full vocabulary.  All
        shards run the identical psum in both the incremental and
        re-encode paths, so the bit-identity contract holds PER SHARD
        LAYOUT (an n-way program's bits match its own re-encode, not a
        1-way program's).  Int8 KV stays single-device: its per-row
        quantization scale is an amax over ALL heads, which a head
        shard cannot compute locally (the engine enforces this).
        """
        from ..models.transformer import block_finish, block_kv_project
        from ..nn.layers.normalization import layer_norm
        from ..ops.kv_cache import (
            NEG_INF, DecodeProgram, det_attention, gather_layer,
            write_prefill, write_step, write_tokens,
        )
        from ..ops.sampling import sample_token

        n_dev = int(np.prod(list(self.mesh.shape.values())))
        tp = 1
        if n_dev != 1:
            tp = int(self.mesh.shape.get("data", 1))
            if tp != n_dev:
                raise NotImplementedError(
                    "sharded decode shards attention heads over the "
                    "'data' axis only — fold all devices into data= "
                    f"(got {dict(self.mesh.shape)})")
            if self.n_heads % tp:
                raise ValueError(
                    f"n_heads {self.n_heads} not divisible by the decode "
                    f"mesh's data={tp}")
        if self.compute_dtype is not None:
            raise NotImplementedError(
                "decode_program serves the f32 params path; compute_dtype "
                "casting would break the re-encode bit-identity contract")
        pos_rows = int(self.params["pos"].shape[0])
        if max_len is None:
            max_len = (pos_rows // page_size) * page_size
        if max_len % page_size or not (0 < max_len <= pos_rows):
            raise ValueError(
                f"max_len {max_len} must be a positive multiple of "
                f"page_size {page_size} and <= the position table "
                f"({pos_rows})")
        L = int(max_len)
        n_heads = self.n_heads
        n_layers = int(jax.tree_util.tree_leaves(
            self.params["blocks"])[0].shape[0])
        d_model = int(self.params["embed"].shape[1])

        def _blocks(params):
            return [jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
                    for i in range(n_layers)]

        def prefill(params, k_pages, v_pages, page_table_row, tokens, n_real):
            """One slot's prompt (bucket length Tb) -> cache writes for
            positions 0..Tb-1 plus the last REAL position's logits.
            Pad-position K/V rows are garbage-but-finite; the step bias
            masks them until a decode step overwrites each one."""
            tb = tokens.shape[0]
            h = (params["embed"][tokens] + params["pos"][:tb])[None]
            bias = jnp.where(
                jnp.arange(L, dtype=jnp.int32)[None, :]
                <= jnp.arange(tb, dtype=jnp.int32)[:, None],
                0.0, NEG_INF)[None, None]              # [1,1,Tb,L]
            pt = page_table_row[None]
            for i, bp in enumerate(_blocks(params)):
                q, k, v = block_kv_project(bp, h, n_heads)  # [1,H,Tb,dh]
                k_pages = write_prefill(k_pages, i, page_table_row,
                                        k.transpose(0, 2, 1, 3)[0])
                v_pages = write_prefill(v_pages, i, page_table_row,
                                        v.transpose(0, 2, 1, 3)[0])
                k_all = gather_layer(k_pages, i, pt).transpose(0, 2, 1, 3)
                v_all = gather_layer(v_pages, i, pt).transpose(0, 2, 1, 3)
                h = block_finish(bp, h, det_attention(q, k_all, v_all, bias))
            h = layer_norm(h, params["lnf_g"], params["lnf_b"])
            return k_pages, v_pages, (h @ params["head"])[0, n_real - 1]

        def step(params, k_pages, v_pages, page_table, tokens, positions,
                 active):
            """One fixed-shape decode step over ALL slots ([S] inputs):
            masked slots' writes are routed to the scratch page (their
            table rows are zeroed here), so one compiled program serves
            any active subset — the zero-recompile contract continuous
            batching rides on."""
            h = (params["embed"][tokens]
                 + params["pos"][positions])[:, None]   # [S,1,D]
            bias = jnp.where(
                jnp.arange(L, dtype=jnp.int32)[None, :]
                <= positions[:, None], 0.0, NEG_INF)[:, None, None, :]
            pt = jnp.where(active[:, None], page_table, 0)
            for i, bp in enumerate(_blocks(params)):
                q, k, v = block_kv_project(bp, h, n_heads)  # [S,H,1,dh]
                k_pages = write_step(k_pages, i, pt, positions, k[:, :, 0])
                v_pages = write_step(v_pages, i, pt, positions, v[:, :, 0])
                k_all = gather_layer(k_pages, i, pt).transpose(0, 2, 1, 3)
                v_all = gather_layer(v_pages, i, pt).transpose(0, 2, 1, 3)
                h = block_finish(bp, h, det_attention(q, k_all, v_all, bias))
            h = layer_norm(h, params["lnf_g"], params["lnf_b"])
            return k_pages, v_pages, (h @ params["head"])[:, 0]

        def prefill_at(params, k_pages, v_pages, page_table_row, tokens,
                       n_real, offset):
            """Suffix prefill for a prefix-cache hit: the bucket's rows
            land at absolute positions offset..offset+Tb-1 and attend
            over the shared prefix rows already resident in the attached
            pages.  Same per-row ops as ``prefill`` (position gather vs
            slice reads the same table rows), so the last-real-position
            logits stay bit-identical to a cold full prefill."""
            tb = tokens.shape[0]
            pos_abs = offset + jnp.arange(tb, dtype=jnp.int32)
            h = (params["embed"][tokens]
                 + params["pos"][jnp.clip(pos_abs, 0, pos_rows - 1)])[None]
            bias = jnp.where(
                jnp.arange(L, dtype=jnp.int32)[None, :]
                <= pos_abs[:, None], 0.0, NEG_INF)[None, None]
            pt = page_table_row[None]
            for i, bp in enumerate(_blocks(params)):
                q, k, v = block_kv_project(bp, h, n_heads)
                k_pages = write_prefill(k_pages, i, page_table_row,
                                        k.transpose(0, 2, 1, 3)[0], offset)
                v_pages = write_prefill(v_pages, i, page_table_row,
                                        v.transpose(0, 2, 1, 3)[0], offset)
                k_all = gather_layer(k_pages, i, pt).transpose(0, 2, 1, 3)
                v_all = gather_layer(v_pages, i, pt).transpose(0, 2, 1, 3)
                h = block_finish(bp, h, det_attention(q, k_all, v_all, bias))
            h = layer_norm(h, params["lnf_g"], params["lnf_b"])
            return k_pages, v_pages, (h @ params["head"])[0, n_real - 1]

        def spec_step(params, k_pages, v_pages, page_table, tokens,
                      positions, active):
            """Speculative verify: score ``tokens`` [S, T] at absolute
            positions positions[s]..positions[s]+T-1 in ONE fixed-shape
            call, writing their K/V rows (overflow rows route to the
            scratch page inside write_tokens).  Rejected rows are
            garbage-but-finite and stay masked until the next round
            overwrites them.  Per-row math matches ``step``, so each
            row's logits are bit-identical to stepping tokens one at a
            time — the greedy temp-0 identity gate rides on this."""
            s_n, t_n = tokens.shape
            pos_abs = positions[:, None] + jnp.arange(t_n, dtype=jnp.int32)
            h = (params["embed"][tokens]
                 + params["pos"][jnp.clip(pos_abs, 0, pos_rows - 1)])
            bias = jnp.where(
                jnp.arange(L, dtype=jnp.int32)[None, None, :]
                <= pos_abs[:, :, None], 0.0, NEG_INF)[:, None]
            pt = jnp.where(active[:, None], page_table, 0)
            for i, bp in enumerate(_blocks(params)):
                q, k, v = block_kv_project(bp, h, n_heads)  # [S,H,T,dh]
                k_pages = write_tokens(k_pages, i, pt, positions,
                                       k.transpose(0, 2, 1, 3))
                v_pages = write_tokens(v_pages, i, pt, positions,
                                       v.transpose(0, 2, 1, 3))
                k_all = gather_layer(k_pages, i, pt).transpose(0, 2, 1, 3)
                v_all = gather_layer(v_pages, i, pt).transpose(0, 2, 1, 3)
                h = block_finish(bp, h, det_attention(q, k_all, v_all, bias))
            h = layer_norm(h, params["lnf_g"], params["lnf_b"])
            return k_pages, v_pages, h @ params["head"]

        def reencode(params, tokens):
            """Full forward at the SAME fixed length L with the SAME
            deterministic attention — the naive-baseline arm and the
            bit-identity oracle.  ``tokens`` [B, L]; row p of the output
            is the next-token logits after position p."""
            b, t = tokens.shape
            h = params["embed"][tokens] + params["pos"][:t]
            bias = jnp.where(
                jnp.arange(t, dtype=jnp.int32)[None, :]
                <= jnp.arange(t, dtype=jnp.int32)[:, None],
                0.0, NEG_INF)[None, None]
            for bp in _blocks(params):
                q, k, v = block_kv_project(bp, h, n_heads)
                h = block_finish(bp, h, det_attention(q, k, v, bias))
            h = layer_norm(h, params["lnf_g"], params["lnf_b"])
            return h @ params["head"]

        vocab = self.vocab_size

        def _sample_rows(lgs, temps, top_ks, top_ps, seeds, steps):
            return jax.vmap(
                lambda l, t, k, p, sd, st:
                    sample_token(l, t, k, p, sd, st, vocab)
            )(lgs, temps, top_ks, top_ps, seeds, steps)

        def step_multi(params, k_pages, v_pages, page_table, tokens,
                       positions, active, temps, top_ks, top_ps, seeds,
                       steps, budgets, eos_id, horizon):
            """H = horizon.shape[0] consecutive decode steps in ONE
            program: ``lax.scan`` of the ``step`` body with sampling
            device-resident (ops/sampling.sample_token keyed
            ``fold_in(seed, steps + j)`` — the identical key schedule
            the engine's per-step sampler uses, which is what makes
            horizon fusion bit-identical to step-by-step).  Per-slot
            EOS (``eos_id``; pass -1 to disable) / token-budget /
            poison masking runs on device: a finished slot leaves
            ``alive``, its page-table row zeroes, and its remaining
            writes route to the scratch page, so live slots' bits match
            H plain steps exactly.  Returns stacked per-iteration
            (tokens, finite, logits); the host records tokens up to
            each slot's stop and discards the device overrun."""
            def body(carry, j):
                k_pages, v_pages, tok, alive = carry
                pos_j = positions + j
                h = (params["embed"][tok]
                     + params["pos"][jnp.clip(pos_j, 0, pos_rows - 1)]
                     )[:, None]
                bias = jnp.where(
                    jnp.arange(L, dtype=jnp.int32)[None, :]
                    <= pos_j[:, None], 0.0, NEG_INF)[:, None, None, :]
                pt = jnp.where(alive[:, None], page_table, 0)
                for i, bp in enumerate(_blocks(params)):
                    q, k, v = block_kv_project(bp, h, n_heads)
                    k_pages = write_step(k_pages, i, pt, pos_j, k[:, :, 0])
                    v_pages = write_step(v_pages, i, pt, pos_j, v[:, :, 0])
                    k_all = gather_layer(
                        k_pages, i, pt).transpose(0, 2, 1, 3)
                    v_all = gather_layer(
                        v_pages, i, pt).transpose(0, 2, 1, 3)
                    h = block_finish(bp, h,
                                     det_attention(q, k_all, v_all, bias))
                h = layer_norm(h, params["lnf_g"], params["lnf_b"])
                lgs = (h @ params["head"])[:, 0]
                nxt, fin = _sample_rows(lgs, temps, top_ks, top_ps,
                                        seeds, steps + j)
                alive = (alive & fin & (nxt != eos_id)
                         & (j + 1 < budgets))
                return (k_pages, v_pages, nxt, alive), (nxt, fin, lgs)

            (k_pages, v_pages, _, _), (toks, fins, lgs) = jax.lax.scan(
                body, (k_pages, v_pages, tokens, active), horizon)
            return k_pages, v_pages, toks, fins, lgs

        if tp > 1:
            # tensor-parallel twins of the five entry points: identical
            # per-row math, but each shard projects only its local head
            # group (column-slices of Wq/Wk/Wv, the matching row-slice
            # of Wo) against a pool shard holding those heads' pages,
            # with ONE psum per layer restoring the full residual.  The
            # FFN and the vocab head run replicated — post-psum h is
            # identical on every shard, so the samplers' "gathered"
            # logits come for free.
            from jax.sharding import PartitionSpec
            from ..ops.kv_cache import QuantPages
            from ..utils.jax_compat import shard_map

            mesh = self.mesh
            hl = n_heads // tp
            dh = d_model // n_heads
            rep = PartitionSpec()

            def _pool_spec(pool):
                full = PartitionSpec(None, None, None, "data", None)
                if isinstance(pool, QuantPages):
                    return QuantPages(full, rep)
                return full

            def _local_blocks(params):
                idx = jax.lax.axis_index("data")
                out = []
                for i in range(n_layers):
                    bp = jax.tree_util.tree_map(
                        lambda a: a[i], params["blocks"])
                    lb = dict(bp)
                    for w in ("Wq", "Wk", "Wv"):
                        lb[w] = bp[w].reshape(d_model, tp, hl * dh)[:, idx]
                    lb["Wo"] = bp["Wo"].reshape(tp, hl * dh, d_model)[idx]
                    out.append(lb)
                return out

            def _prefill_sh(params, k_pages, v_pages, page_table_row,
                            tokens, n_real):
                tb = tokens.shape[0]
                h = (params["embed"][tokens] + params["pos"][:tb])[None]
                bias = jnp.where(
                    jnp.arange(L, dtype=jnp.int32)[None, :]
                    <= jnp.arange(tb, dtype=jnp.int32)[:, None],
                    0.0, NEG_INF)[None, None]
                pt = page_table_row[None]
                for i, bp in enumerate(_local_blocks(params)):
                    q, k, v = block_kv_project(bp, h, hl)
                    k_pages = write_prefill(k_pages, i, page_table_row,
                                            k.transpose(0, 2, 1, 3)[0])
                    v_pages = write_prefill(v_pages, i, page_table_row,
                                            v.transpose(0, 2, 1, 3)[0])
                    k_all = gather_layer(
                        k_pages, i, pt).transpose(0, 2, 1, 3)
                    v_all = gather_layer(
                        v_pages, i, pt).transpose(0, 2, 1, 3)
                    h = block_finish(bp, h,
                                     det_attention(q, k_all, v_all, bias),
                                     psum_axis="data")
                h = layer_norm(h, params["lnf_g"], params["lnf_b"])
                return k_pages, v_pages, (h @ params["head"])[0, n_real - 1]

            def _step_sh(params, k_pages, v_pages, page_table, tokens,
                         positions, active):
                h = (params["embed"][tokens]
                     + params["pos"][positions])[:, None]
                bias = jnp.where(
                    jnp.arange(L, dtype=jnp.int32)[None, :]
                    <= positions[:, None], 0.0, NEG_INF)[:, None, None, :]
                pt = jnp.where(active[:, None], page_table, 0)
                for i, bp in enumerate(_local_blocks(params)):
                    q, k, v = block_kv_project(bp, h, hl)
                    k_pages = write_step(k_pages, i, pt, positions,
                                         k[:, :, 0])
                    v_pages = write_step(v_pages, i, pt, positions,
                                         v[:, :, 0])
                    k_all = gather_layer(
                        k_pages, i, pt).transpose(0, 2, 1, 3)
                    v_all = gather_layer(
                        v_pages, i, pt).transpose(0, 2, 1, 3)
                    h = block_finish(bp, h,
                                     det_attention(q, k_all, v_all, bias),
                                     psum_axis="data")
                h = layer_norm(h, params["lnf_g"], params["lnf_b"])
                return k_pages, v_pages, (h @ params["head"])[:, 0]

            def _prefill_at_sh(params, k_pages, v_pages, page_table_row,
                               tokens, n_real, offset):
                tb = tokens.shape[0]
                pos_abs = offset + jnp.arange(tb, dtype=jnp.int32)
                h = (params["embed"][tokens]
                     + params["pos"][jnp.clip(pos_abs, 0,
                                              pos_rows - 1)])[None]
                bias = jnp.where(
                    jnp.arange(L, dtype=jnp.int32)[None, :]
                    <= pos_abs[:, None], 0.0, NEG_INF)[None, None]
                pt = page_table_row[None]
                for i, bp in enumerate(_local_blocks(params)):
                    q, k, v = block_kv_project(bp, h, hl)
                    k_pages = write_prefill(k_pages, i, page_table_row,
                                            k.transpose(0, 2, 1, 3)[0],
                                            offset)
                    v_pages = write_prefill(v_pages, i, page_table_row,
                                            v.transpose(0, 2, 1, 3)[0],
                                            offset)
                    k_all = gather_layer(
                        k_pages, i, pt).transpose(0, 2, 1, 3)
                    v_all = gather_layer(
                        v_pages, i, pt).transpose(0, 2, 1, 3)
                    h = block_finish(bp, h,
                                     det_attention(q, k_all, v_all, bias),
                                     psum_axis="data")
                h = layer_norm(h, params["lnf_g"], params["lnf_b"])
                return k_pages, v_pages, (h @ params["head"])[0, n_real - 1]

            def _spec_step_sh(params, k_pages, v_pages, page_table, tokens,
                              positions, active):
                s_n, t_n = tokens.shape
                pos_abs = positions[:, None] + jnp.arange(t_n,
                                                          dtype=jnp.int32)
                h = (params["embed"][tokens]
                     + params["pos"][jnp.clip(pos_abs, 0, pos_rows - 1)])
                bias = jnp.where(
                    jnp.arange(L, dtype=jnp.int32)[None, None, :]
                    <= pos_abs[:, :, None], 0.0, NEG_INF)[:, None]
                pt = jnp.where(active[:, None], page_table, 0)
                for i, bp in enumerate(_local_blocks(params)):
                    q, k, v = block_kv_project(bp, h, hl)
                    k_pages = write_tokens(k_pages, i, pt, positions,
                                           k.transpose(0, 2, 1, 3))
                    v_pages = write_tokens(v_pages, i, pt, positions,
                                           v.transpose(0, 2, 1, 3))
                    k_all = gather_layer(
                        k_pages, i, pt).transpose(0, 2, 1, 3)
                    v_all = gather_layer(
                        v_pages, i, pt).transpose(0, 2, 1, 3)
                    h = block_finish(bp, h,
                                     det_attention(q, k_all, v_all, bias),
                                     psum_axis="data")
                h = layer_norm(h, params["lnf_g"], params["lnf_b"])
                return k_pages, v_pages, h @ params["head"]

            def _reencode_sh(params, tokens):
                b, t = tokens.shape
                h = params["embed"][tokens] + params["pos"][:t]
                bias = jnp.where(
                    jnp.arange(t, dtype=jnp.int32)[None, :]
                    <= jnp.arange(t, dtype=jnp.int32)[:, None],
                    0.0, NEG_INF)[None, None]
                for bp in _local_blocks(params):
                    q, k, v = block_kv_project(bp, h, hl)
                    h = block_finish(bp, h, det_attention(q, k, v, bias),
                                     psum_axis="data")
                h = layer_norm(h, params["lnf_g"], params["lnf_b"])
                return h @ params["head"]

            def _step_multi_sh(params, k_pages, v_pages, page_table,
                               tokens, positions, active, temps, top_ks,
                               top_ps, seeds, steps, budgets, eos_id,
                               horizon):
                # fused scan of _step_sh's body; post-psum h is
                # replicated, so every shard samples the SAME token from
                # the same deterministic key — no gather needed
                def body(carry, j):
                    k_pages, v_pages, tok, alive = carry
                    pos_j = positions + j
                    h = (params["embed"][tok]
                         + params["pos"][jnp.clip(pos_j, 0, pos_rows - 1)]
                         )[:, None]
                    bias = jnp.where(
                        jnp.arange(L, dtype=jnp.int32)[None, :]
                        <= pos_j[:, None], 0.0,
                        NEG_INF)[:, None, None, :]
                    pt = jnp.where(alive[:, None], page_table, 0)
                    for i, bp in enumerate(_local_blocks(params)):
                        q, k, v = block_kv_project(bp, h, hl)
                        k_pages = write_step(k_pages, i, pt, pos_j,
                                             k[:, :, 0])
                        v_pages = write_step(v_pages, i, pt, pos_j,
                                             v[:, :, 0])
                        k_all = gather_layer(
                            k_pages, i, pt).transpose(0, 2, 1, 3)
                        v_all = gather_layer(
                            v_pages, i, pt).transpose(0, 2, 1, 3)
                        h = block_finish(
                            bp, h, det_attention(q, k_all, v_all, bias),
                            psum_axis="data")
                    h = layer_norm(h, params["lnf_g"], params["lnf_b"])
                    lgs = (h @ params["head"])[:, 0]
                    nxt, fin = _sample_rows(lgs, temps, top_ks, top_ps,
                                            seeds, steps + j)
                    alive = (alive & fin & (nxt != eos_id)
                             & (j + 1 < budgets))
                    return (k_pages, v_pages, nxt, alive), (nxt, fin, lgs)

                (k_pages, v_pages, _, _), (toks, fins, lgs) = jax.lax.scan(
                    body, (k_pages, v_pages, tokens, active), horizon)
                return k_pages, v_pages, toks, fins, lgs

            def _wrap(body, n_rep=1):
                # the pool specs depend on the pool KIND, so the
                # shard_map is built at trace time (inside the engine's
                # jit) where the pytree is known; n_rep = number of
                # replicated outputs after the two pool sides
                def fn(params, k_pages, v_pages, *rest):
                    ks, vs = _pool_spec(k_pages), _pool_spec(v_pages)
                    sm = shard_map(
                        body, mesh=mesh,
                        in_specs=(rep, ks, vs) + (rep,) * len(rest),
                        out_specs=(ks, vs) + (rep,) * n_rep)
                    return sm(params, k_pages, v_pages, *rest)
                return fn

            prefill = _wrap(_prefill_sh)
            step = _wrap(_step_sh)
            prefill_at = _wrap(_prefill_at_sh)
            spec_step = _wrap(_spec_step_sh)
            step_multi = _wrap(_step_multi_sh, n_rep=3)

            def reencode(params, tokens):
                return shard_map(_reencode_sh, mesh=mesh,
                                 in_specs=(rep, rep),
                                 out_specs=rep)(params, tokens)

        return DecodeProgram(
            prefill=prefill, step=step, reencode=reencode,
            n_layers=n_layers, n_heads=n_heads, d_head=d_model // n_heads,
            vocab_size=self.vocab_size, max_len=L, page_size=page_size,
            pages_per_slot=L // page_size,
            prefill_at=prefill_at, spec_step=spec_step,
            step_multi=step_multi, tp=tp)
