"""Elastic / fault-tolerant training: checkpoint-resume + failure recovery.

Parity target: SURVEY §5 "Failure detection / elasticity" — the reference
covers this operationally via Spark task retry + TrainingMaster state
(dl4j-spark SharedTrainingMaster) and CheckpointListener.  The TPU-native
equivalent is checkpoint/restore elasticity: pods fail as units, so the
recovery loop is (1) detect a failed step, (2) re-provision a mesh over
the devices that are still healthy, (3) restore the last checkpoint,
(4) continue.  Orbax-style periodic checkpointing rides the existing zip
serializer (utils/serializer.py) so restored models are plain framework
checkpoints.

``ElasticTrainer`` wraps any trainer-like object exposing
``fit_batch(ds) -> float`` plus a wrapped ``net``; failures are surfaced
to a pluggable ``FailureDetector`` so tests (and health monitors) can
inject/observe them.
"""

from __future__ import annotations

import logging
import os
import re
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from ..obs import trace as obs_trace
from ..obs.metrics import get_registry
from .distributed import resolve_process_index

logger = logging.getLogger("deeplearning4j_tpu")


class RecoverableInfraError(RuntimeError):
    """Base class for failures the elastic stack treats as recoverable
    *by construction* (host lost, membership change, hung step) — the
    FailureDetector recognizes the type, not a message marker, so
    subclasses anywhere in the stack opt into recovery without touching
    the marker list."""


class _HostSnapshot:
    """Detached host-side copy of a model's persistent state — quacks like
    the net for utils/serializer.save_model, so serialization can run on a
    background thread after the training loop has moved on (and donated
    the device buffers the snapshot was taken from)."""

    def __init__(self, net):
        import numpy as _np

        def host(t):
            return jax.tree_util.tree_map(lambda a: _np.asarray(a), t)

        self.conf = net.conf
        self.params = host(net.params)
        self.state = host(net.state)
        self.opt_state = host(net.opt_state)
        # compressed-exchange error-feedback residual (serializer format
        # v3): losing it on restore would drop in-flight compression error
        residual = getattr(net, "grad_residual", None)
        self.grad_residual = None if residual is None else host(residual)
        self.iteration = net.iteration
        self.epoch = getattr(net, "epoch", 0)
        # serializer writes this into meta.json — the checkpoint must
        # record the REAL network class, not the snapshot wrapper
        self._model_class = type(net).__name__

    def save(self, path: str, save_updater: bool = True,
             compression: Optional[int] = None) -> None:
        import zipfile

        from ..utils.serializer import save_model
        save_model(self, path, save_updater=save_updater,
                   compression=(zipfile.ZIP_DEFLATED if compression is None
                                else compression))


class CheckpointManager:
    """Rolling checkpoint store (reference CheckpointListener semantics:
    keep-last-N, save-every-N-iterations; zip format from utils/serializer).

    ``save_async`` overlaps the expensive part (zip/deflate, ~1s for
    100MB of params) with training: the device→host snapshot happens on
    the caller's thread (it must — the next step donates those buffers),
    then a single background writer thread serializes and atomically
    renames.  The orbax-style pattern, stdlib-only.

    Multi-host: every host of a pod job shares one checkpoint directory,
    and params are replicated across hosts — N hosts writing the same
    ``checkpoint_X.zip.tmp`` race each other's rename.  ``role`` decides
    who writes:

    - ``"auto"`` (default): only the host with process index 0 writes
      (the index resolves from an explicit ``process_id``, the launcher's
      ``DL4J_TPU_PROCESS_ID`` env, or ``jax.process_index()``); every
      other host's ``save``/``save_async``/prune are no-ops, while
      restore/list stay available everywhere — a rejoining host restores
      the coordinator's checkpoints.
    - ``"writer"`` / ``"reader"``: force the role regardless of index.
    - ``"per_host"``: every host writes its OWN shard under a distinct
      name (``checkpoint_X.h<process>.zip``) and lists only its own —
      for host-local state that is NOT replicated."""

    _NAME_RE = re.compile(r"^checkpoint_(\d+)(?:\.h(\d+))?\.zip$")

    def __init__(self, directory: str, keep_last: int = 3,
                 role: str = "auto", process_id: Optional[int] = None):
        if role not in ("auto", "writer", "reader", "per_host"):
            raise ValueError(f"role must be auto/writer/reader/per_host, "
                             f"got {role!r}")
        self.directory = directory
        self.keep_last = keep_last
        self.role = role
        self.process_id = resolve_process_index(process_id)
        self._suffix = f".h{self.process_id}" if role == "per_host" else ""
        self._executor = None
        self._pending = None
        # checkpoint -> registry provenance: which (name, version) a
        # checkpoint was registered as (serving/lifecycle.py stamps this
        # at REGISTER time); persisted as a sidecar so the mapping
        # survives the controller, like the checkpoints themselves
        self.registered: Dict[str, Tuple[str, int]] = {}
        # wall clock of the most recent completed (deflate) write — the
        # preemption handler's estimate of whether another deflate pass
        # still fits the remaining grace budget (parallel/preemption.py)
        self.last_save_seconds: Optional[float] = None
        os.makedirs(directory, exist_ok=True)
        if self.is_writer:
            self._clean_stale_tmp()
        self._load_provenance()

    @property
    def is_writer(self) -> bool:
        if self.role == "reader":
            return False
        if self.role in ("writer", "per_host"):
            return True
        return self.process_id == 0

    def _clean_stale_tmp(self) -> None:
        """Remove ``checkpoint_*.zip.tmp`` left by a crash mid-(async-)write.
        The atomic-rename protocol means a .tmp is never the newest valid
        state — without this they leak forever, one per crash.  Only this
        manager's OWN temp names are touched (suffix-matched): a rejoining
        host must never delete the temp another host is actively writing."""
        for fn in os.listdir(self.directory):
            if not (fn.startswith("checkpoint_") and fn.endswith(".zip.tmp")):
                continue
            m = self._NAME_RE.match(fn[:-len(".tmp")])
            if m is None:
                continue   # foreign name — not ours to judge
            host = m.group(2)
            own = (host is not None and int(host) == self.process_id
                   if self.role == "per_host" else host is None)
            if own:
                try:
                    os.remove(os.path.join(self.directory, fn))
                    logger.info("removed stale checkpoint temp file %s", fn)
                except OSError:
                    pass

    def _path(self, step: int) -> str:
        return os.path.join(self.directory,
                            f"checkpoint_{step:010d}{self._suffix}.zip")

    # -- checkpoint -> registry provenance ---------------------------------

    _PROVENANCE_FILE = "registry_provenance.json"

    def _provenance_path(self) -> str:
        return os.path.join(self.directory, self._PROVENANCE_FILE)

    def _load_provenance(self) -> None:
        import json
        prov = self._provenance_path()
        if not os.path.exists(prov):
            return
        try:
            with open(prov) as f:
                raw = json.load(f)
            self.registered = {k: (str(v[0]), int(v[1]))
                               for k, v in raw.items()}
        except Exception as exc:  # an unreadable sidecar must not take
            # down checkpointing itself — provenance is advisory metadata
            logger.warning("unreadable %s (%s) — starting with empty "
                           "registry provenance", self._PROVENANCE_FILE, exc)
            self.registered = {}

    def note_registered(self, path: str, name: str, version: int) -> None:
        """Record that checkpoint ``path`` was registered as
        ``(name, version)`` in a model registry — the lifecycle
        controller's REGISTER stage calls this so "which checkpoint
        produced which serving version" is answerable from the
        checkpoint store itself.  Persisted as an atomic sidecar
        (``registry_provenance.json``) with the same crash discipline
        as the checkpoints."""
        import json
        self.registered[os.path.basename(str(path))] = (str(name),
                                                        int(version))
        prov = self._provenance_path()
        tmp = f"{prov}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({k: list(v) for k, v in self.registered.items()}, f,
                      indent=2, sort_keys=True)
        os.replace(tmp, prov)

    def registered_version(self, path: str) -> Optional[Tuple[str, int]]:
        """The ``(registry name, version)`` checkpoint ``path`` was
        registered as, or None if it never reached a registry."""
        return self.registered.get(os.path.basename(str(path)))

    def save(self, net, step: int) -> Optional[str]:
        if not self.is_writer:
            logger.debug("checkpoint save @%d skipped on non-writer host %d",
                         step, self.process_id)
            return None
        path = self._path(step)
        # temp-file + atomic rename: a crash mid-write must never leave a
        # truncated zip as the latest (restore would load garbage)
        tmp = path + ".tmp"
        t0 = time.monotonic()
        net.save(tmp)
        self.last_save_seconds = time.monotonic() - t0
        os.replace(tmp, path)
        self._prune()
        return path

    def save_snapshot(self, snap: "_HostSnapshot", step: int,
                      compressed: bool = True,
                      prune: bool = True) -> Optional[str]:
        """Write an already-captured :class:`_HostSnapshot` — the
        emergency-checkpoint entry point (parallel/preemption.py): the
        snapshot was taken the moment the preemption notice was
        processed, and ``compressed=False`` writes ZIP_STORED when the
        remaining grace budget won't fit a deflate pass.  Same atomic
        temp-file + rename protocol and writer-role guard as ``save``;
        ``prune=False`` skips the keep-last sweep (every millisecond of
        grace goes to the write itself)."""
        import zipfile
        if not self.is_writer:
            logger.debug("emergency checkpoint @%d skipped on non-writer "
                         "host %d", step, self.process_id)
            return None
        path = self._path(step)
        tmp = path + ".tmp"
        snap.save(tmp, compression=(zipfile.ZIP_DEFLATED if compressed
                                    else zipfile.ZIP_STORED))
        os.replace(tmp, path)
        if prune:
            self._prune()
        return path

    def save_async(self, net, step: int):
        """Snapshot now, write in the background; returns a Future of the
        final path (``None`` on non-writer hosts — no snapshot is taken).
        At most one write is in flight — a second call first waits for the
        previous write (backpressure beats unbounded host copies of the
        full model)."""
        from concurrent.futures import ThreadPoolExecutor
        if not self.is_writer:
            return None
        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-writer")
        if self._pending is not None:
            # Clear before result() (mirrors wait()): a failed background
            # write must raise once, not poison every later save_async.
            pending, self._pending = self._pending, None
            pending.result()
        snap = _HostSnapshot(net)

        def write():
            path = self._path(step)
            tmp = path + ".tmp"
            t0 = time.monotonic()
            snap.save(tmp)
            self.last_save_seconds = time.monotonic() - t0
            os.replace(tmp, path)
            self._prune()
            return path

        self._pending = self._executor.submit(write)
        return self._pending

    def wait(self) -> None:
        """Block until any in-flight async write has landed.  The pending
        slot is cleared even when the write failed — a stale exception
        must not re-raise forever — but the failure still propagates to
        THIS caller."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            pending.result()

    def _prune(self) -> None:
        if not self.is_writer:
            return
        ckpts = self.list_checkpoints()
        for path, _ in ckpts[:-self.keep_last]:
            try:
                os.remove(path)
            except OSError:
                pass

    def list_checkpoints(self) -> List:
        out = []
        for fn in sorted(os.listdir(self.directory)):
            if not (fn.startswith("checkpoint_") and fn.endswith(".zip")):
                continue
            m = self._NAME_RE.match(fn)
            if m is None:
                # a foreign/renamed file matching the glob must not
                # take down every list/prune/restore in the store
                logger.warning("skipping unparsable checkpoint filename "
                               "%s", fn)
                continue
            step, host = int(m.group(1)), m.group(2)
            if self.role == "per_host":
                if host is None or int(host) != self.process_id:
                    continue   # another host's shard — not ours to touch
            elif host is not None:
                continue       # per-host shard in a shared-writer store
            out.append((os.path.join(self.directory, fn), step))
        return out

    def latest(self) -> Optional[Any]:
        ckpts = self.list_checkpoints()
        return ckpts[-1] if ckpts else None

    def _quarantine(self, path: str) -> None:
        """Rename a checkpoint that failed to load to ``<path>.corrupt`` —
        keeps the evidence for post-mortem while taking it out of the
        rotation, so the next restore/prune doesn't re-try (or protect)
        a file that is known garbage."""
        try:
            os.replace(path, path + ".corrupt")
            logger.warning("quarantined corrupt checkpoint as %s.corrupt",
                           os.path.basename(path))
        except OSError:
            pass

    def restore_latest(self, loader: Callable[[str], Any]):
        """→ (model, step) from the newest INTACT checkpoint, or (None, -1).

        Waits for any in-flight async write first, so the newest state is
        always restorable; a FAILED async write is logged and skipped —
        recovery must proceed from the newest checkpoint that did land,
        not die on the write that didn't.  A checkpoint whose load fails
        (truncated/bit-flipped zip, integrity-digest mismatch — serializer
        format v4) is quarantined and restore falls through to the next
        older one: a corrupt LATEST must cost one checkpoint interval, not
        the whole job."""
        try:
            self.wait()
        except Exception as exc:
            logger.warning("in-flight async checkpoint write failed (%s) — "
                           "restoring from the newest on-disk checkpoint", exc)
        candidates = list(reversed(self.list_checkpoints()))
        for path, step in candidates:
            try:
                return loader(path), step
            except Exception as exc:
                logger.error("checkpoint %s failed to load (%s: %s) — "
                             "falling back to the next older checkpoint",
                             os.path.basename(path), type(exc).__name__, exc)
                self._quarantine(path)
        if candidates:
            logger.error("all %d checkpoints failed to load — restarting "
                         "from current in-memory params", len(candidates))
        return None, -1


class StepHangError(RecoverableInfraError):
    """The step watchdog fired: a dispatch exceeded ``step_timeout`` wall
    clock.  Message carries DEADLINE_EXCEEDED so the default
    FailureDetector classifies it as recoverable."""

    def __init__(self, elapsed: float, timeout: float):
        super().__init__(
            f"DEADLINE_EXCEEDED: step watchdog — dispatch took "
            f"{elapsed:.1f}s (> step_timeout={timeout:.1f}s); treating the "
            "step as hung and recovering from checkpoint")
        self.elapsed = elapsed
        self.timeout = timeout


class FailureDetector:
    """Decides whether an exception is a recoverable infrastructure failure
    (device loss, RPC deadline) vs a programming error that must propagate.
    Subclass / replace for custom health signals."""

    #: specific infrastructure signatures only — broad words like "device"
    #: or "internal" would misclassify deterministic bugs as recoverable
    #: and burn the restart budget re-hitting them
    RECOVERABLE_MARKERS = ("DEADLINE_EXCEEDED", "UNAVAILABLE", "DATA_LOSS",
                           "ABORTED", "device halted", "device lost",
                           "connection reset", "socket closed",
                           "non-finite gradient")

    def is_recoverable(self, exc: Exception) -> bool:
        if getattr(exc, "recoverable", None) is False:
            return False   # non-recoverable by construction: a preemption
            # notice (PreemptedError) means the HOST is going away —
            # retrying the step here would burn the grace budget
        if isinstance(exc, RecoverableInfraError):
            return True    # recoverable by construction (hang, host lost)
        if isinstance(exc, (ValueError, TypeError, KeyError)):
            return False   # programming errors propagate
        text = f"{type(exc).__name__}: {exc}"
        return any(m.lower() in text.lower() for m in self.RECOVERABLE_MARKERS)

    def on_failure(self, exc: Exception, attempt: int) -> None:
        logger.warning("step failure (attempt %d): %s", attempt, exc)


class ElasticTrainer:
    """Checkpoint-resume training loop with failure recovery.

    >>> et = ElasticTrainer(trainer, ckpt_dir, checkpoint_every=100)
    >>> et.fit(iterator, epochs=3)

    On a recoverable failure: rebuild (via ``rebuild_fn``, e.g. re-creating
    the mesh over surviving devices), restore the newest checkpoint, and
    continue from there.  ``max_restarts`` bounds the retry budget.

    Restart pacing: ``backoff_base > 0`` sleeps
    ``min(backoff_max, backoff_base * 2**(restarts-1))`` scaled by a seeded
    jitter factor between restore attempts — at pod scale, thousands of
    workers restarting in lockstep re-stampede the very storage/network
    that just failed; the jitter decorrelates them.  ``step_timeout``
    arms a wall-clock watchdog: a dispatch that neither completes nor
    raises (hung collective, dead tunnel) is converted into a recoverable
    :class:`StepHangError` instead of blocking forever.  ``sleep_fn`` /
    ``clock`` are injectable so recovery timing is testable with a fake
    clock (tests/test_chaos.py).
    """

    def __init__(self, trainer, checkpoint_dir: str,
                 checkpoint_every: int = 100,
                 keep_last: int = 3,
                 max_restarts: int = 3,
                 failure_detector: Optional[FailureDetector] = None,
                 rebuild_fn: Optional[Callable[[], Any]] = None,
                 loader: Optional[Callable[[str], Any]] = None,
                 sync_every: int = 10,
                 restart_reset_after: Optional[int] = None,
                 async_checkpoints: bool = False,
                 backoff_base: float = 0.0,
                 backoff_max: float = 30.0,
                 backoff_jitter: float = 0.5,
                 jitter_seed: Optional[int] = None,
                 step_timeout: Optional[float] = None,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 membership_check: Optional[Callable[[], None]] = None,
                 checkpoint_role: str = "auto",
                 preemption=None,
                 run_id: Optional[str] = None):
        import random
        import uuid

        self.trainer = trainer
        # stable identity of THIS training run, stamped into registry
        # lineage by the promotion pipeline (docs/LIFECYCLE.md) — pass
        # one explicitly to correlate relaunched workers of the same
        # logical run (the launcher's relaunch keeps the id; a fresh
        # controller generates one)
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self.ckpt = CheckpointManager(checkpoint_dir, keep_last,
                                      role=checkpoint_role)
        self.checkpoint_every = max(1, checkpoint_every)
        self.max_restarts = max_restarts
        self.detector = failure_detector or FailureDetector()
        self.rebuild_fn = rebuild_fn
        self.loader = loader or self._default_loader
        self.sync_every = max(1, sync_every)
        self.async_checkpoints = async_checkpoints
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.backoff_jitter = backoff_jitter
        self._jitter_rng = random.Random(jitter_seed)
        self.step_timeout = step_timeout
        self.sleep_fn = sleep_fn
        self.clock = clock
        # pod-scale membership: a callable polled before every step that
        # raises a RecoverableInfraError (e.g. launcher.HostLostError) on
        # host join/leave — the failure flows through the SAME backoff →
        # rebuild_fn → restore machinery as a device loss, so slice-
        # granular recovery (smaller dcn mesh over the survivors) is the
        # existing recovery path, not a parallel one
        self.membership_check = membership_check
        # announced failures (parallel/preemption.py): a PreemptionHandler
        # whose notice flag is checked at every STEP BOUNDARY — the
        # handler then captures an emergency checkpoint inside the grace
        # budget and raises PreemptedError, which is NOT recoverable (the
        # host is going away; the launcher relaunches the worker and
        # resume() picks the emergency checkpoint up)
        self.preemption = preemption
        # newest checkpoint step known DURABLE on disk (-1 = none yet):
        # sync saves record it inline, async saves when the background
        # write lands — surfaced through the heartbeat so the launcher's
        # pod-liveness report can answer "how much work would we lose"
        self.last_checkpoint_step = -1
        # ...and its path: the lifecycle pipeline reads
        # `final_checkpoint_path` after fit() to register the run's
        # durable artifact without parsing checkpoint filenames
        self.last_checkpoint_path: Optional[str] = None
        self.restarts = 0        # consecutive-failure budget (resets)
        self.total_restarts = 0  # lifetime count, for observability
        self.recovery_seconds = 0.0  # total wall clock spent in recovery
        self.backoff_sleeps: List[float] = []  # delays slept, observability
        # the watchdog arms only after one step has completed since the
        # last (re)start (it re-disarms on every recovery): the first step
        # jit-compiles (unbounded, legitimate wall clock) and a restore
        # re-places + recompiles — counting compile time as a hang would
        # turn every cold start into a spurious recovery loop
        self._watchdog_armed = False
        self.global_step = 0
        # max_restarts bounds CONSECUTIVE failures, not lifetime failures:
        # after this many successful steps the counter resets, so a
        # months-long job surviving occasional pre-emptions doesn't
        # eventually die with 'exceeded max_restarts' despite every
        # incident having recovered
        self.restart_reset_after = (restart_reset_after
                                    if restart_reset_after is not None
                                    else checkpoint_every)
        self._ok_steps = 0
        # unified registry (docs/OBSERVABILITY.md): process-wide recovery
        # counters plus this trainer's structured stats as a collector —
        # one /metrics response answers "how often is this job failing"
        reg = get_registry()
        self._m_restarts = reg.counter("elastic_restarts_total")
        self._m_recovery_s = reg.counter("elastic_recovery_seconds_total")
        self._m_backoff = reg.counter("elastic_backoff_sleeps_total")
        reg.register_collector("elastic", self.recovery_stats, unique=True)

    def recovery_stats(self) -> dict:
        """Structured recovery counters (the registry collector view)."""
        return {"run_id": self.run_id,
                "global_step": self.global_step,
                "restarts": self.restarts,
                "total_restarts": self.total_restarts,
                "recovery_seconds": round(self.recovery_seconds, 3),
                "backoff_sleeps": len(self.backoff_sleeps),
                "last_checkpoint_step": self.last_checkpoint_step}

    def _record_durable(self, step: int, path) -> None:
        """A checkpoint write for ``step`` landed (path None = this host
        is not the writer — the durable step is unknown here)."""
        if path is not None and step > self.last_checkpoint_step:
            self.last_checkpoint_step = step
            self.last_checkpoint_path = str(path)

    @property
    def final_checkpoint_path(self) -> Optional[str]:
        """The newest checkpoint known durable on disk for this run —
        after ``fit()`` returns, the run's final artifact (``fit``
        always lands a last checkpoint).  None before any write landed
        on this host (non-writer hosts never observe a path)."""
        return self.last_checkpoint_path

    @staticmethod
    def _default_loader(path: str):
        from ..utils.serializer import load_model
        return load_model(path)

    @property
    def net(self):
        return getattr(self.trainer, "net", self.trainer)

    def _restore(self) -> None:
        with obs_trace.span("ckpt/restore", cat="ckpt"):
            model, step = self.ckpt.restore_latest(self.loader)
        if model is None:
            logger.warning("no checkpoint to restore — restarting from "
                           "current params")
            return
        net = self.net
        net.params = model.params
        net.state = model.state
        net.opt_state = model.opt_state
        # None when the checkpoint predates compression (or it is off) —
        # _place_model re-inits zeros in that case
        net.grad_residual = getattr(model, "grad_residual", None)
        net.iteration = model.iteration
        self.global_step = step
        # the checkpoint just loaded is by definition durable on disk
        if step >= self.last_checkpoint_step:
            self.last_checkpoint_step = step
            self.last_checkpoint_path = self.ckpt._path(step)
        logger.info("restored checkpoint @ step %d", step)

    def resume(self) -> int:
        """Restore the newest intact checkpoint before training starts and
        return the restored global step (0 when the store is empty) — the
        host-(re)join entry point: a relaunched worker calls ``resume()``
        and continues the loop from wherever the cluster's checkpoints
        left off, instead of only recovering after a mid-training
        failure."""
        if self.ckpt.latest() is None:
            return 0   # fresh store — nothing to resume, no warning
        with obs_trace.span("elastic/resume", cat="elastic") as sp:
            self._restore()
            if self.global_step > 0 and hasattr(self.trainer, "_place_model"):
                self.trainer._place_model()
            sp.set(step=self.global_step)
        self._watchdog_armed = False
        return self.global_step

    def _materialize(self, loss) -> None:
        """Force the device barrier (``loss.value()``), under the watchdog
        when ``step_timeout`` is armed: a dispatch that never completes
        (hung collective, dead device tunnel) raises neither — the read
        just blocks.  Running the read on a worker thread bounds the wait;
        on timeout the worker is abandoned (it stays parked on the dead
        dispatch) and the step surfaces as a recoverable StepHangError."""
        if self.step_timeout is None or not self._watchdog_armed:
            loss.value()
            return
        import threading
        box: dict = {}

        def read():
            try:
                box["v"] = loss.value()
            except Exception as exc:  # surfaced below, on the caller
                box["e"] = exc

        # a bare DAEMON thread, not an executor worker: a genuinely hung
        # read parks this thread forever, and a non-daemon worker would
        # then block interpreter exit at the executor's atexit join
        t = threading.Thread(target=read, daemon=True, name="step-watchdog")
        t.start()
        t.join(self.step_timeout)
        if t.is_alive():
            raise StepHangError(self.step_timeout, self.step_timeout)
        if "e" in box:
            raise box["e"]

    def _backoff_delay(self) -> float:
        """Exponential backoff with seeded jitter for restart ``restarts``
        (1-based; call after incrementing).  0 when backoff is disabled."""
        if self.backoff_base <= 0:
            return 0.0
        base = min(self.backoff_max,
                   self.backoff_base * (2.0 ** (self.restarts - 1)))
        return base * (1.0 + self.backoff_jitter * self._jitter_rng.random())

    def fit_batch(self, ds) -> float:
        """One step with checkpoint + recovery semantics.

        The underlying fit_batch is async (device-resident LazyScore); a
        device failure would otherwise surface at some later read, outside
        this try block.  Materializing every ``sync_every`` steps keeps the
        failure inside the recovery loop while amortizing the host sync —
        at most sync_every steps are replayed from the last checkpoint.
        With ``step_timeout`` set, a step whose wall clock exceeds the
        budget — whether it blocked in the dispatch (caught by the
        watchdog thread) or crawled through a degraded link (caught by the
        elapsed check) — is treated as hung and recovered."""
        while True:
            # step boundary: the preemption flag is processed here, OUTSIDE
            # the recovery try — a notice is not a failure to retry, it is
            # an order to checkpoint and leave (PreemptedError propagates)
            if self.preemption is not None:
                self.preemption.check(self)
            t_start = self.clock()
            try:
                if self.membership_check is not None:
                    # inside the try: a HostLostError / membership change
                    # takes the normal recovery path (backoff → rebuild →
                    # restore), not an unhandled crash
                    self.membership_check()
                loss = self.trainer.fit_batch(ds)
                self.global_step += 1
                saving = self.global_step % self.checkpoint_every == 0
                if (saving or self.global_step % self.sync_every == 0) \
                        and hasattr(loss, "value"):
                    # device barrier: surfaces async failures — ALWAYS
                    # before a checkpoint write, so a latent failure can't
                    # first materialize mid-save and corrupt the newest
                    # checkpoint
                    self._materialize(loss)
                if self.step_timeout is not None:
                    elapsed = self.clock() - t_start
                    if self._watchdog_armed and elapsed > self.step_timeout:
                        raise StepHangError(elapsed, self.step_timeout)
                    self._watchdog_armed = True
                if saving:
                    with obs_trace.span("ckpt/save", cat="ckpt",
                                        step=self.global_step,
                                        is_async=self.async_checkpoints):
                        if self.async_checkpoints:
                            # zip/deflate overlaps the next training
                            # steps; the device→host snapshot happens
                            # here (the next step donates these buffers)
                            fut = self.ckpt.save_async(self.net,
                                                       self.global_step)
                            if fut is not None:
                                step_saved = self.global_step
                                fut.add_done_callback(
                                    lambda f, s=step_saved:
                                    self._record_durable(
                                        s, None if f.exception()
                                        else f.result()))
                        else:
                            self._record_durable(
                                self.global_step,
                                self.ckpt.save(self.net, self.global_step))
                self._ok_steps += 1
                if self._ok_steps >= self.restart_reset_after and self.restarts:
                    logger.info("%d successful steps since last failure — "
                                "resetting restart counter", self._ok_steps)
                    self.restarts = 0
                return loss
            except Exception as exc:
                if not self.detector.is_recoverable(exc):
                    raise
                t_fail = self.clock()
                self._ok_steps = 0
                self.restarts += 1
                self.total_restarts += 1
                obs_trace.instant("fault", cat="elastic",
                                  kind=type(exc).__name__,
                                  step=self.global_step,
                                  restart=self.restarts)
                self._m_restarts.inc()
                self.detector.on_failure(exc, self.restarts)
                if self.restarts > self.max_restarts:
                    raise RuntimeError(
                        f"exceeded max_restarts={self.max_restarts}") from exc
                with obs_trace.span("elastic/recovery", cat="elastic",
                                    kind=type(exc).__name__,
                                    step=self.global_step):
                    delay = self._backoff_delay()
                    if delay > 0:
                        logger.info("backing off %.2fs before restart %d "
                                    "(exponential + jitter)", delay,
                                    self.restarts)
                        self.backoff_sleeps.append(delay)
                        self._m_backoff.inc()
                        self.sleep_fn(delay)
                    if self.rebuild_fn is not None:
                        self.trainer = self.rebuild_fn()
                    self._restore()
                    # restored params are host arrays — a sharded trainer
                    # must re-place them on its (possibly rebuilt) mesh
                    # before the next step, or the jit step sees
                    # uncommitted inputs
                    if hasattr(self.trainer, "_place_model"):
                        self.trainer._place_model()
                # re-placement/rebuild recompiles: the next step gets the
                # cold-start compile grace again
                self._watchdog_armed = False
                spent = self.clock() - t_fail
                self.recovery_seconds += spent
                self._m_recovery_s.inc(max(0.0, spent))

    def fit(self, data, epochs: int = 1) -> List[float]:
        losses: List[float] = []
        net = self.net
        it = net._as_iterator(data) if hasattr(net, "_as_iterator") else data
        for _ in range(epochs):
            for ds in it:
                losses.append(self.fit_batch(ds))
        # final checkpoint so a clean shutdown is always resumable: FLUSH
        # any in-flight save_async first — without the wait() a clean exit
        # could return while the newest state is still half-written on the
        # background thread — then skip the re-serialization when the last
        # step already checkpointed durably
        self.ckpt.wait()
        latest = self.ckpt.latest()
        if latest is None or latest[1] != self.global_step:
            self._record_durable(self.global_step,
                                 self.ckpt.save(self.net, self.global_step))
        return losses
