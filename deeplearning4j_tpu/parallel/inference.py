"""ParallelInference — back-compat shim over the serving engine.

Reference: parallelism/ParallelInference.java:32,82,130 — BATCHED mode.
The original implementation here (a worker thread draining a request
queue on a fixed ``queue_timeout_s`` poll) is superseded by the
``serving/`` subsystem; this class keeps the old constructor and the
``output`` / ``output_async`` / ``shutdown`` semantics as a thin wrapper
over one ``serving.Engine`` so existing callers and tests keep working.

Semantics preserved exactly:
  - requests are answered in arrival order, fused up to ``max_batch``;
  - model errors propagate to every waiter of the failed batch;
  - ``shutdown()`` fails queued/late requests with RuntimeError instead
    of hanging them (now deterministic even for a request enqueued
    concurrently with shutdown — the old worker could exit between the
    shutdown flag and the queue read, stranding that future).

Semantics improved (the old implementation's bugs, fixed in serving/):
  - drains split at ``max_batch`` BEFORE shape-bucketing, so a 33-row
    drain at ``max_batch=32`` runs as 32+1, not as one unbucketed
    33-row program;
  - the fixed poll becomes the engine's event-driven close
    (``queue_timeout_s`` maps to the batch-forming window), removing
    the per-batch poll stall.

New code should use ``deeplearning4j_tpu.serving.Engine`` directly —
it adds deadlines, AOT warmup, replicas, admission control, hot-swap,
and metrics (docs/SERVING.md).
"""

from __future__ import annotations

from concurrent.futures import Future
from typing import List, Optional

import numpy as np

from ..serving.engine import Engine

# the old queue had no deadline concept: requests waited forever.  The
# shim keeps that by setting a deadline far beyond any real wait.
_NO_DEADLINE_MS = 3_600_000.0


class ParallelInference:
    """Batched inference server around any model with ``.output(x)``.

    ``max_batch`` caps the fused batch (reference batchLimit);
    ``bucket_sizes`` quantizes batch shapes so XLA compiles a handful of
    programs instead of one per size; ``queue_timeout_s`` — the old
    fixed poll interval — now bounds how long the oldest request waits
    for companions before its batch closes.
    """

    def __init__(self, model, max_batch: int = 32, queue_timeout_s: float = 0.005,
                 bucket_sizes: Optional[List[int]] = None):
        self.model = model
        self.max_batch = max_batch
        self.timeout = queue_timeout_s
        self.engine = Engine(
            model, max_batch=max_batch, bucket_sizes=bucket_sizes,
            slo_ms=_NO_DEADLINE_MS, max_wait_ms=queue_timeout_s * 1000.0,
            replicas=1, max_queue=1_000_000, admission="block")
        self.buckets = list(self.engine.batcher.buckets)

    def output(self, x: np.ndarray) -> np.ndarray:
        """Submit one request (any leading batch size); blocks for result."""
        return self.engine.output(np.asarray(x))

    def output_async(self, x: np.ndarray) -> Future:
        return self.engine.output_async(np.asarray(x))

    def shutdown(self) -> None:
        self.engine.shutdown()
