"""ParallelInference — batched concurrent serving.

Reference: parallelism/ParallelInference.java:32,82,130 — BATCHED mode
collects concurrent requests into one device call via an observable queue
(inference/observers/BatchedInferenceObservable.java).  Here: a worker
thread drains a request queue, pads/concatenates into one jit'd forward,
and resolves per-request futures.  The jit'd apply replaces the reference's
per-model replica pool — one compiled program serves any batch size bucket.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import List, Optional, Tuple

import numpy as np


class ParallelInference:
    """Batched inference server around any model with .output(x).

    ``max_batch`` caps the fused batch (reference batchLimit); requests are
    answered in arrival order.  ``bucket_sizes`` quantizes batch shapes so
    XLA compiles a handful of programs instead of one per size.
    """

    def __init__(self, model, max_batch: int = 32, queue_timeout_s: float = 0.005,
                 bucket_sizes: Optional[List[int]] = None):
        self.model = model
        self.max_batch = max_batch
        self.timeout = queue_timeout_s
        if bucket_sizes is None:
            bucket_sizes, b = [], 1
            while b < max_batch:
                bucket_sizes.append(b)
                b *= 2
            bucket_sizes.append(max_batch)
        self.buckets = sorted(set(bucket_sizes))
        self._queue: "queue.Queue[Tuple[np.ndarray, Future]]" = queue.Queue()
        self._shutdown = threading.Event()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def output(self, x: np.ndarray) -> np.ndarray:
        """Submit one request (any leading batch size); blocks for result."""
        fut: Future = Future()
        self._queue.put((np.asarray(x), fut))
        return fut.result()

    def output_async(self, x: np.ndarray) -> Future:
        fut: Future = Future()
        self._queue.put((np.asarray(x), fut))
        return fut

    def shutdown(self) -> None:
        self._shutdown.set()
        self._worker.join(timeout=5)
        # fail any requests still queued (or submitted after shutdown) so
        # callers blocked in fut.result() wake up instead of hanging
        while True:
            try:
                _, fut = self._queue.get_nowait()
            except queue.Empty:
                break
            if not fut.done():
                fut.set_exception(RuntimeError("ParallelInference is shut down"))

    # -- worker ------------------------------------------------------------

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return n

    def _run(self) -> None:
        while not self._shutdown.is_set():
            batch: List[Tuple[np.ndarray, Future]] = []
            try:
                batch.append(self._queue.get(timeout=0.05))
            except queue.Empty:
                continue
            try:
                total = batch[0][0].shape[0]
                # coalesce whatever arrived within the window (BATCHED mode)
                while total < self.max_batch:
                    try:
                        item = self._queue.get(timeout=self.timeout)
                        batch.append(item)
                        total += item[0].shape[0]
                    except queue.Empty:
                        break
                xs = np.concatenate([b[0] for b in batch], axis=0)
                padded_n = self._bucket(xs.shape[0])
                if padded_n > xs.shape[0]:
                    pad = np.zeros((padded_n - xs.shape[0],) + xs.shape[1:], xs.dtype)
                    xs = np.concatenate([xs, pad], axis=0)
                out = self.model.output(xs)
                if isinstance(out, list):  # ComputationGraph returns a list
                    out = out[0]
                ofs = 0
                for x, fut in batch:
                    n = x.shape[0]
                    fut.set_result(out[ofs:ofs + n])
                    ofs += n
            except Exception as e:  # propagate to all waiters
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
