"""Ulysses sequence parallelism — all-to-all head-scatter / seq-gather.

No reference analog (DL4J 0.9.2 handles sequence scale with TBPTT +
masking only — SURVEY.md §5 "Long-context"); designed TPU-first per SURVEY
§7-M5 as the LOW-COMMUNICATION alternative to ring attention:

  ring:    n hops × ppermute of the full local K/V block — traffic
           O(T·D·H) per device per layer, overlapped with compute.
  ulysses: TWO all-to-alls per attention — q/k/v head-scatter+seq-gather
           in, output seq-scatter+head-gather out.  Traffic O(T·D·H/P)
           per device: a P-fold reduction, at the cost of requiring
           n_heads % P == 0 (heads are the scattered resource).

After the first all-to-all each device holds the FULL sequence for
n_heads/P heads, so the local attention is just ``flash_mha`` — the
pallas kernel, causal masking and key-padding masks all work unchanged.
(DeepSpeed-Ulysses, Jacobs et al. 2023, is the published pattern.)
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import flash_mha
from ..utils.jax_compat import axis_size, shard_map

Array = jax.Array


def ulysses_attention(q: Array, k: Array, v: Array, axis_name: str,
                      *, causal: bool = False,
                      scale: Optional[float] = None,
                      kmask: Optional[Array] = None) -> Array:
    """All-to-all attention — call INSIDE shard_map/pjit.

    q/k/v: [B, H, T_local, D] with the sequence axis sharded on
    ``axis_name`` (T_global = T_local · P).  ``kmask`` [B, T_local] is the
    local slice of the key-padding mask.  H must divide by the axis size.
    Returns [B, H, T_local, D] sharded the same way.
    """
    p = axis_size(axis_name)
    h = q.shape[1]
    if h % p:
        raise ValueError(f"n_heads {h} not divisible by '{axis_name}' axis "
                         f"size {p} — Ulysses scatters heads; use ring "
                         "attention for head counts below the axis size")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])

    def a2a_in(x):
        # [B, H, T/P, D] → [B, H/P, T, D]: scatter heads, gather sequence
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qg, kg, vg = a2a_in(q), a2a_in(k), a2a_in(v)
    mg = None
    if kmask is not None:
        # every device needs the FULL key mask for its heads
        mg = jax.lax.all_gather(kmask, axis_name, axis=1, tiled=True)
    o = flash_mha(qg, kg, vg, causal, scale, kmask=mg)
    # [B, H/P, T, D] → [B, H, T/P, D]: gather heads back, scatter sequence
    return jax.lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def ulysses_self_attention(q: Array, k: Array, v: Array, mesh: Mesh,
                           *, seq_axis: str = "seq", causal: bool = False,
                           scale: Optional[float] = None,
                           kmask: Optional[Array] = None) -> Array:
    """Convenience wrapper: shard [B,H,T,D] q/k/v on ``seq_axis`` of
    ``mesh`` and run Ulysses attention.  T and n_heads must divide by the
    axis size.  Mirrors ``ring_self_attention`` — the two are drop-in
    alternatives behind the same calling convention."""
    n = mesh.shape[seq_axis]
    if q.shape[2] % n:
        raise ValueError(f"seq len {q.shape[2]} not divisible by seq axis {n}")
    spec = P(None, None, seq_axis, None)
    mspec = P(None, seq_axis)
    if kmask is None:
        fn = shard_map(
            functools.partial(ulysses_attention, axis_name=seq_axis,
                              causal=causal, scale=scale),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return fn(q, k, v)

    def body(q, k, v, m):
        return ulysses_attention(q, k, v, seq_axis, causal=causal,
                                 scale=scale, kmask=m)

    fn = shard_map(body, mesh=mesh,
                       in_specs=(spec, spec, spec, mspec), out_specs=spec)
    return fn(q, k, v, kmask)
