"""Dataset fetchers — MNIST / EMNIST / CIFAR-10 / IRIS.

Parity targets: reference datasets/fetchers/MnistDataFetcher.java (custom
IDX binary reader via MnistManager), iterator/impl/{Mnist,Emnist,Cifar,
Iris}DataSetIterator (SURVEY.md §2.4).

Environment note: this build runs zero-egress, so unlike the reference
there is NO auto-download.  Fetchers read the standard binary formats from
a local cache directory (``DL4J_TPU_DATA_DIR`` env var, default
``~/.deeplearning4j_tpu``) — drop the canonical files there (same files
the reference caches) and they load; otherwise a deterministic synthetic
surrogate with the same shapes/classes is generated when
``allow_synthetic=True`` (the default, loudly logged) so training code and
benchmarks run anywhere.  IRIS ships embedded (150 rows, public domain).
"""

from __future__ import annotations

import gzip
import logging
import os
import struct
from typing import Optional, Tuple

import numpy as np

from .dataset import DataSet
from .iterators import ListDataSetIterator

logger = logging.getLogger("deeplearning4j_tpu")


def data_dir() -> str:
    return os.environ.get("DL4J_TPU_DATA_DIR",
                          os.path.join(os.path.expanduser("~"), ".deeplearning4j_tpu"))


def _open_maybe_gz(path: str):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def _find(*names: str) -> Optional[str]:
    for name in names:
        for root in (data_dir(), os.path.join(data_dir(), "mnist"),
                     os.path.join(data_dir(), "cifar10")):
            p = os.path.join(root, name)
            if os.path.exists(p):
                return p
    return None


# ---------------------------------------------------------------------------
# IDX (MNIST/EMNIST) readers — reference MnistManager/MnistImageFile
# ---------------------------------------------------------------------------


def read_idx_images(path: str) -> np.ndarray:
    """Parse an IDX3 image file → [n, rows, cols] uint8."""
    with _open_maybe_gz(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"{path}: bad IDX image magic {magic}")
        data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
    return data.reshape(n, rows, cols)


def read_idx_labels(path: str) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"{path}: bad IDX label magic {magic}")
        return np.frombuffer(f.read(n), dtype=np.uint8)


def _synthetic_images(n: int, h: int, w: int, c: int, classes: int, seed: int):
    """Deterministic class-dependent image surrogate: each class lights a
    distinct spatial cell pattern + noise — learnable, MNIST-shaped."""
    rng = np.random.default_rng(seed)
    ys = rng.integers(0, classes, size=n)
    xs = rng.normal(0, 0.15, size=(n, h, w, c)).astype(np.float32)
    gh, gw = max(h // 4, 1), max(w // 4, 1)
    for cls in range(classes):
        mask = ys == cls
        r, col = divmod(cls, 4)
        r, col = r % 4, col % 4
        xs[mask, r * gh:(r + 1) * gh, col * gw:(col + 1) * gw, :] += 1.0
    return xs, ys.astype(np.int32)


def load_mnist(train: bool = True, allow_synthetic: bool = True,
               synthetic_n: int = 2048) -> Tuple[np.ndarray, np.ndarray]:
    """→ (images [n,28,28,1] float32 in [0,1]-ish, labels [n] int32)."""
    prefix = "train" if train else "t10k"
    img = _find(f"{prefix}-images-idx3-ubyte", f"{prefix}-images-idx3-ubyte.gz")
    lbl = _find(f"{prefix}-labels-idx1-ubyte", f"{prefix}-labels-idx1-ubyte.gz")
    if img and lbl:
        xs = read_idx_images(img).astype(np.float32)[..., None] / 255.0
        ys = read_idx_labels(lbl).astype(np.int32)
        return xs, ys
    if not allow_synthetic:
        raise FileNotFoundError(
            f"MNIST IDX files not found under {data_dir()} (zero-egress: no "
            "auto-download; place the canonical files there)")
    logger.warning("MNIST files not found under %s — using synthetic surrogate",
                   data_dir())
    xs, ys = _synthetic_images(synthetic_n, 28, 28, 1, 10, seed=42 if train else 43)
    return xs, ys


def load_cifar10(train: bool = True, allow_synthetic: bool = True,
                 synthetic_n: int = 2048) -> Tuple[np.ndarray, np.ndarray]:
    """→ (images [n,32,32,3] float32, labels [n] int32).  Reads the
    canonical cifar-10-batches-bin format (reference CifarDataSetIterator)."""
    names = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
             else ["test_batch.bin"])
    found = []
    for name in names:
        p = (_find(name)
             or _find(os.path.join("cifar-10-batches-bin", name)))
        if p:
            found.append(p)
    if len(found) == len(names):
        xs_list, ys_list = [], []
        for p in found:
            raw = np.fromfile(p, dtype=np.uint8).reshape(-1, 3073)
            ys_list.append(raw[:, 0].astype(np.int32))
            imgs = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            xs_list.append(imgs.astype(np.float32) / 255.0)
        return np.concatenate(xs_list), np.concatenate(ys_list)
    if not allow_synthetic:
        raise FileNotFoundError(f"CIFAR-10 binaries not found under {data_dir()}")
    logger.warning("CIFAR-10 files not found under %s — using synthetic surrogate",
                   data_dir())
    return _synthetic_images(synthetic_n, 32, 32, 3, 10, seed=44 if train else 45)


# ---------------------------------------------------------------------------
# EMNIST — IDX like MNIST, stored transposed (reference EmnistDataFetcher)
# ---------------------------------------------------------------------------

#: split → class count (reference EmnistDataSetIterator.Set)
EMNIST_SPLITS = {"balanced": 47, "byclass": 62, "bymerge": 47,
                 "digits": 10, "letters": 26, "mnist": 10}


def load_emnist(split: str = "balanced", train: bool = True,
                allow_synthetic: bool = True,
                synthetic_n: int = 2048) -> Tuple[np.ndarray, np.ndarray]:
    """→ ([n,28,28,1] float32, [n] int32).  Canonical emnist-<split>-...
    IDX files; EMNIST images are stored transposed vs MNIST and are
    un-transposed here (reference EmnistDataFetcher)."""
    if split not in EMNIST_SPLITS:
        raise ValueError(f"unknown EMNIST split '{split}' — one of "
                         f"{sorted(EMNIST_SPLITS)}")
    kind = "train" if train else "test"
    img = _find(f"emnist-{split}-{kind}-images-idx3-ubyte",
                f"emnist-{split}-{kind}-images-idx3-ubyte.gz",
                os.path.join("emnist", f"emnist-{split}-{kind}-images-idx3-ubyte.gz"))
    lbl = _find(f"emnist-{split}-{kind}-labels-idx1-ubyte",
                f"emnist-{split}-{kind}-labels-idx1-ubyte.gz",
                os.path.join("emnist", f"emnist-{split}-{kind}-labels-idx1-ubyte.gz"))
    classes = EMNIST_SPLITS[split]
    if img and lbl:
        xs = read_idx_images(img).astype(np.float32)[..., None] / 255.0
        xs = np.transpose(xs, (0, 2, 1, 3))  # EMNIST stores transposed
        ys = read_idx_labels(lbl).astype(np.int32)
        if split == "letters":
            ys = ys - 1  # letters labels are 1-based
        return xs, ys
    if not allow_synthetic:
        raise FileNotFoundError(f"EMNIST({split}) IDX files not found under {data_dir()}")
    logger.warning("EMNIST(%s) files not found under %s — synthetic surrogate",
                   split, data_dir())
    return _synthetic_images(synthetic_n, 28, 28, 1, classes, seed=46 if train else 47)


# ---------------------------------------------------------------------------
# SVHN — .mat cropped-digits format (reference SvhnDataFetcher)
# ---------------------------------------------------------------------------


def load_svhn(train: bool = True, allow_synthetic: bool = True,
              synthetic_n: int = 2048) -> Tuple[np.ndarray, np.ndarray]:
    """→ ([n,32,32,3] float32, [n] int32).  Canonical train_32x32.mat /
    test_32x32.mat (X [32,32,3,N], y [N] with '10' meaning digit 0)."""
    name = "train_32x32.mat" if train else "test_32x32.mat"
    p = _find(name, os.path.join("svhn", name))
    if p:
        import scipy.io
        mat = scipy.io.loadmat(p)
        xs = np.transpose(mat["X"], (3, 0, 1, 2)).astype(np.float32) / 255.0
        ys = mat["y"].reshape(-1).astype(np.int32)
        ys = np.where(ys == 10, 0, ys)
        return xs, ys
    if not allow_synthetic:
        raise FileNotFoundError(f"SVHN {name} not found under {data_dir()}")
    logger.warning("SVHN files not found under %s — synthetic surrogate", data_dir())
    return _synthetic_images(synthetic_n, 32, 32, 3, 10, seed=48 if train else 49)


# ---------------------------------------------------------------------------
# TinyImageNet — directory-of-JPEGs layout (reference TinyImageNetFetcher)
# ---------------------------------------------------------------------------


def load_tiny_imagenet(train: bool = True, allow_synthetic: bool = True,
                       synthetic_n: int = 1024,
                       limit_per_class: Optional[int] = None
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """→ ([n,64,64,3] float32, [n] int32, 200 classes).  Reads the standard
    tiny-imagenet-200/ layout (train/<wnid>/images/*.JPEG; val via
    val_annotations.txt)."""
    root = os.path.join(data_dir(), "tiny-imagenet-200")
    if os.path.isdir(root):
        from PIL import Image
        wnids = sorted(os.listdir(os.path.join(root, "train")))
        wnid_to_idx = {w: i for i, w in enumerate(wnids)}
        xs_list, ys_list = [], []
        if train:
            for w in wnids:
                img_dir = os.path.join(root, "train", w, "images")
                files = sorted(os.listdir(img_dir))[:limit_per_class]
                for fn in files:
                    img = Image.open(os.path.join(img_dir, fn)).convert("RGB")
                    xs_list.append(np.asarray(img, np.float32) / 255.0)
                    ys_list.append(wnid_to_idx[w])
        else:
            ann = os.path.join(root, "val", "val_annotations.txt")
            with open(ann) as f:
                for line in f:
                    parts = line.split("\t")
                    img = Image.open(os.path.join(root, "val", "images",
                                                  parts[0])).convert("RGB")
                    xs_list.append(np.asarray(img, np.float32) / 255.0)
                    ys_list.append(wnid_to_idx[parts[1]])
        return np.stack(xs_list), np.asarray(ys_list, np.int32)
    if not allow_synthetic:
        raise FileNotFoundError(f"tiny-imagenet-200/ not found under {data_dir()}")
    logger.warning("TinyImageNet not found under %s — synthetic surrogate", data_dir())
    return _synthetic_images(synthetic_n, 64, 64, 3, 200, seed=50 if train else 51)


def load_lfw(train: bool = True, allow_synthetic: bool = True,
             synthetic_n: int = 256, min_faces_per_person: int = 2,
             image_size: int = 250,
             limit: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Labeled Faces in the Wild → ([n,S,S,3] float32 in [0,1], [n] int32).

    Reads the standard ``lfw/<person_name>/<person>_NNNN.jpg`` layout
    (reference LFWDataSetIterator.java:31 / LFWLoader); labels are person
    indices over people with ≥ ``min_faces_per_person`` images, and the
    deterministic 80/20 per-person split replaces the reference's random
    train/test sampling.  Falls back to a synthetic surrogate when the
    archive is absent (zero-egress environments)."""
    root = os.path.join(data_dir(), "lfw")
    if os.path.isdir(root):
        from PIL import Image
        people = sorted(d for d in os.listdir(root)
                        if os.path.isdir(os.path.join(root, d)))
        kept = []
        for p in people:
            files = sorted(f for f in os.listdir(os.path.join(root, p))
                           if f.lower().endswith((".jpg", ".jpeg", ".png")))
            if len(files) >= min_faces_per_person:
                kept.append((p, files))
        if not kept:
            raise ValueError(f"no people with >= {min_faces_per_person} "
                             f"faces under {root}")
        xs_list, ys_list = [], []
        for idx, (p, files) in enumerate(kept):
            cut = max(1, int(len(files) * 0.8))
            use = files[:cut] if train else files[cut:]
            for fn in use:
                img = Image.open(os.path.join(root, p, fn)).convert("RGB")
                if img.size != (image_size, image_size):
                    img = img.resize((image_size, image_size))
                xs_list.append(np.asarray(img, np.float32) / 255.0)
                ys_list.append(idx)
                if limit and len(xs_list) >= limit:
                    return np.stack(xs_list), np.asarray(ys_list, np.int32)
        if not xs_list:  # tiny archives can have empty test splits
            raise ValueError("empty LFW split — lower min_faces_per_person")
        return np.stack(xs_list), np.asarray(ys_list, np.int32)
    if not allow_synthetic:
        raise FileNotFoundError(f"lfw/ not found under {data_dir()}")
    logger.warning("LFW not found under %s — synthetic surrogate", data_dir())
    return _synthetic_images(synthetic_n, image_size, image_size, 3, 5,
                             seed=60 if train else 61)


# ---------------------------------------------------------------------------
# UCI synthetic control — sequence classification (reference
# UciSequenceDataFetcher: 600 series × 60 steps, 6 classes)
# ---------------------------------------------------------------------------


def load_uci_synthetic_control(train: bool = True, allow_synthetic: bool = True
                               ) -> Tuple[np.ndarray, np.ndarray]:
    """→ (sequences [n,60,1] float32, labels [n] int32).  Canonical
    synthetic_control.data: 600 whitespace rows, 100 per class in order;
    the reference's 75/25 train/test split per class is reproduced."""
    p = _find("synthetic_control.data", os.path.join("uci", "synthetic_control.data"))
    if p:
        raw = np.loadtxt(p, dtype=np.float64)
        if raw.shape != (600, 60):
            raise ValueError(f"{p}: expected 600x60, got {raw.shape}")
        xs = raw.reshape(600, 60, 1).astype(np.float32)
        ys = np.repeat(np.arange(6), 100).astype(np.int32)
    else:
        if not allow_synthetic:
            raise FileNotFoundError(f"synthetic_control.data not found under {data_dir()}")
        logger.warning("UCI synthetic_control not found under %s — surrogate",
                       data_dir())
        rng = np.random.default_rng(52)
        t = np.arange(60)
        rows = []
        for cls in range(6):
            base = {0: np.zeros(60), 1: 0.5 * np.sin(t / 4), 2: 0.08 * t,
                    3: -0.08 * t, 4: np.where(t > 30, 3.0, 0.0),
                    5: np.where(t > 30, -3.0, 0.0)}[cls]
            rows.append(base[None, :] + rng.normal(0, 0.3, (100, 60)))
        xs = np.concatenate(rows).reshape(600, 60, 1).astype(np.float32)
        ys = np.repeat(np.arange(6), 100).astype(np.int32)
    # per-class 75/25 split (reference UciSequenceDataFetcher)
    sel = np.zeros(600, bool)
    for cls in range(6):
        sel[cls * 100: cls * 100 + 75] = True
    keep = sel if train else ~sel
    return xs[keep], ys[keep]


# ---------------------------------------------------------------------------
# IRIS — embedded (reference IrisDataFetcher hardcodes the 150 rows too)
# ---------------------------------------------------------------------------

_IRIS = None


def load_iris() -> Tuple[np.ndarray, np.ndarray]:
    """Fisher's Iris, 150×4 + 3 classes (public domain)."""
    global _IRIS
    if _IRIS is None:
        from ._iris_data import IRIS_DATA
        arr = np.asarray(IRIS_DATA, dtype=np.float32)
        _IRIS = (arr[:, :4], arr[:, 4].astype(np.int32))
    return _IRIS


# ---------------------------------------------------------------------------
# iterator constructors (reference iterator/impl/*DataSetIterator)
# ---------------------------------------------------------------------------


def _one_hot(ys: np.ndarray, classes: int) -> np.ndarray:
    return np.eye(classes, dtype=np.float32)[ys]


def MnistDataSetIterator(batch_size: int, train: bool = True, seed: int = 123,
                         flatten: bool = False, **kw) -> ListDataSetIterator:
    xs, ys = load_mnist(train=train, **kw)
    if flatten:
        xs = xs.reshape(xs.shape[0], -1)
    ds = DataSet(xs, _one_hot(ys, 10)).shuffle(seed)
    return ListDataSetIterator(ds.batch_by(batch_size))


def Cifar10DataSetIterator(batch_size: int, train: bool = True, seed: int = 123,
                           **kw) -> ListDataSetIterator:
    xs, ys = load_cifar10(train=train, **kw)
    ds = DataSet(xs, _one_hot(ys, 10)).shuffle(seed)
    return ListDataSetIterator(ds.batch_by(batch_size))


def IrisDataSetIterator(batch_size: int = 150, seed: int = 123) -> ListDataSetIterator:
    xs, ys = load_iris()
    ds = DataSet(xs, _one_hot(ys, 3)).shuffle(seed)
    return ListDataSetIterator(ds.batch_by(batch_size))


def EmnistDataSetIterator(batch_size: int, split: str = "balanced",
                          train: bool = True, seed: int = 123,
                          **kw) -> ListDataSetIterator:
    xs, ys = load_emnist(split=split, train=train, **kw)
    ds = DataSet(xs, _one_hot(ys, EMNIST_SPLITS[split])).shuffle(seed)
    return ListDataSetIterator(ds.batch_by(batch_size))


def SvhnDataSetIterator(batch_size: int, train: bool = True, seed: int = 123,
                        **kw) -> ListDataSetIterator:
    xs, ys = load_svhn(train=train, **kw)
    ds = DataSet(xs, _one_hot(ys, 10)).shuffle(seed)
    return ListDataSetIterator(ds.batch_by(batch_size))


def TinyImageNetDataSetIterator(batch_size: int, train: bool = True,
                                seed: int = 123, **kw) -> ListDataSetIterator:
    xs, ys = load_tiny_imagenet(train=train, **kw)
    ds = DataSet(xs, _one_hot(ys, 200)).shuffle(seed)
    return ListDataSetIterator(ds.batch_by(batch_size))


def LFWDataSetIterator(batch_size: int, train: bool = True, seed: int = 123,
                       **kw) -> ListDataSetIterator:
    """Face classification batches (reference LFWDataSetIterator.java:31);
    label width adapts to the people found in the archive."""
    xs, ys = load_lfw(train=train, **kw)
    n_classes = int(ys.max()) + 1
    ds = DataSet(xs, _one_hot(ys, n_classes)).shuffle(seed)
    return ListDataSetIterator(ds.batch_by(batch_size))


def UciSequenceDataSetIterator(batch_size: int, train: bool = True,
                               seed: int = 123, **kw) -> ListDataSetIterator:
    """Sequence classification: [mb,60,1] features, per-sequence one-hot
    labels (reference UciSequenceDataSetIterator)."""
    xs, ys = load_uci_synthetic_control(train=train, **kw)
    ds = DataSet(xs, _one_hot(ys, 6)).shuffle(seed)
    return ListDataSetIterator(ds.batch_by(batch_size))
