"""Dataset fetchers — MNIST / EMNIST / CIFAR-10 / IRIS.

Parity targets: reference datasets/fetchers/MnistDataFetcher.java (custom
IDX binary reader via MnistManager), iterator/impl/{Mnist,Emnist,Cifar,
Iris}DataSetIterator (SURVEY.md §2.4).

Environment note: this build runs zero-egress, so unlike the reference
there is NO auto-download.  Fetchers read the standard binary formats from
a local cache directory (``DL4J_TPU_DATA_DIR`` env var, default
``~/.deeplearning4j_tpu``) — drop the canonical files there (same files
the reference caches) and they load; otherwise a deterministic synthetic
surrogate with the same shapes/classes is generated when
``allow_synthetic=True`` (the default, loudly logged) so training code and
benchmarks run anywhere.  IRIS ships embedded (150 rows, public domain).
"""

from __future__ import annotations

import gzip
import logging
import os
import struct
from typing import Optional, Tuple

import numpy as np

from .dataset import DataSet
from .iterators import ListDataSetIterator

logger = logging.getLogger("deeplearning4j_tpu")


def data_dir() -> str:
    return os.environ.get("DL4J_TPU_DATA_DIR",
                          os.path.join(os.path.expanduser("~"), ".deeplearning4j_tpu"))


def _open_maybe_gz(path: str):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def _find(*names: str) -> Optional[str]:
    for name in names:
        for root in (data_dir(), os.path.join(data_dir(), "mnist"),
                     os.path.join(data_dir(), "cifar10")):
            p = os.path.join(root, name)
            if os.path.exists(p):
                return p
    return None


# ---------------------------------------------------------------------------
# IDX (MNIST/EMNIST) readers — reference MnistManager/MnistImageFile
# ---------------------------------------------------------------------------


def read_idx_images(path: str) -> np.ndarray:
    """Parse an IDX3 image file → [n, rows, cols] uint8."""
    with _open_maybe_gz(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"{path}: bad IDX image magic {magic}")
        data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
    return data.reshape(n, rows, cols)


def read_idx_labels(path: str) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"{path}: bad IDX label magic {magic}")
        return np.frombuffer(f.read(n), dtype=np.uint8)


def _synthetic_images(n: int, h: int, w: int, c: int, classes: int, seed: int):
    """Deterministic class-dependent image surrogate: each class lights a
    distinct spatial cell pattern + noise — learnable, MNIST-shaped."""
    rng = np.random.default_rng(seed)
    ys = rng.integers(0, classes, size=n)
    xs = rng.normal(0, 0.15, size=(n, h, w, c)).astype(np.float32)
    gh, gw = max(h // 4, 1), max(w // 4, 1)
    for cls in range(classes):
        mask = ys == cls
        r, col = divmod(cls, 4)
        r, col = r % 4, col % 4
        xs[mask, r * gh:(r + 1) * gh, col * gw:(col + 1) * gw, :] += 1.0
    return xs, ys.astype(np.int32)


def load_mnist(train: bool = True, allow_synthetic: bool = True,
               synthetic_n: int = 2048) -> Tuple[np.ndarray, np.ndarray]:
    """→ (images [n,28,28,1] float32 in [0,1]-ish, labels [n] int32)."""
    prefix = "train" if train else "t10k"
    img = _find(f"{prefix}-images-idx3-ubyte", f"{prefix}-images-idx3-ubyte.gz")
    lbl = _find(f"{prefix}-labels-idx1-ubyte", f"{prefix}-labels-idx1-ubyte.gz")
    if img and lbl:
        xs = read_idx_images(img).astype(np.float32)[..., None] / 255.0
        ys = read_idx_labels(lbl).astype(np.int32)
        return xs, ys
    if not allow_synthetic:
        raise FileNotFoundError(
            f"MNIST IDX files not found under {data_dir()} (zero-egress: no "
            "auto-download; place the canonical files there)")
    logger.warning("MNIST files not found under %s — using synthetic surrogate",
                   data_dir())
    xs, ys = _synthetic_images(synthetic_n, 28, 28, 1, 10, seed=42 if train else 43)
    return xs, ys


def load_cifar10(train: bool = True, allow_synthetic: bool = True,
                 synthetic_n: int = 2048) -> Tuple[np.ndarray, np.ndarray]:
    """→ (images [n,32,32,3] float32, labels [n] int32).  Reads the
    canonical cifar-10-batches-bin format (reference CifarDataSetIterator)."""
    names = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
             else ["test_batch.bin"])
    found = []
    for name in names:
        p = (_find(name)
             or _find(os.path.join("cifar-10-batches-bin", name)))
        if p:
            found.append(p)
    if len(found) == len(names):
        xs_list, ys_list = [], []
        for p in found:
            raw = np.fromfile(p, dtype=np.uint8).reshape(-1, 3073)
            ys_list.append(raw[:, 0].astype(np.int32))
            imgs = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            xs_list.append(imgs.astype(np.float32) / 255.0)
        return np.concatenate(xs_list), np.concatenate(ys_list)
    if not allow_synthetic:
        raise FileNotFoundError(f"CIFAR-10 binaries not found under {data_dir()}")
    logger.warning("CIFAR-10 files not found under %s — using synthetic surrogate",
                   data_dir())
    return _synthetic_images(synthetic_n, 32, 32, 3, 10, seed=44 if train else 45)


# ---------------------------------------------------------------------------
# IRIS — embedded (reference IrisDataFetcher hardcodes the 150 rows too)
# ---------------------------------------------------------------------------

_IRIS = None


def load_iris() -> Tuple[np.ndarray, np.ndarray]:
    """Fisher's Iris, 150×4 + 3 classes (public domain)."""
    global _IRIS
    if _IRIS is None:
        from ._iris_data import IRIS_DATA
        arr = np.asarray(IRIS_DATA, dtype=np.float32)
        _IRIS = (arr[:, :4], arr[:, 4].astype(np.int32))
    return _IRIS


# ---------------------------------------------------------------------------
# iterator constructors (reference iterator/impl/*DataSetIterator)
# ---------------------------------------------------------------------------


def _one_hot(ys: np.ndarray, classes: int) -> np.ndarray:
    return np.eye(classes, dtype=np.float32)[ys]


def MnistDataSetIterator(batch_size: int, train: bool = True, seed: int = 123,
                         flatten: bool = False, **kw) -> ListDataSetIterator:
    xs, ys = load_mnist(train=train, **kw)
    if flatten:
        xs = xs.reshape(xs.shape[0], -1)
    ds = DataSet(xs, _one_hot(ys, 10)).shuffle(seed)
    return ListDataSetIterator(ds.batch_by(batch_size))


def Cifar10DataSetIterator(batch_size: int, train: bool = True, seed: int = 123,
                           **kw) -> ListDataSetIterator:
    xs, ys = load_cifar10(train=train, **kw)
    ds = DataSet(xs, _one_hot(ys, 10)).shuffle(seed)
    return ListDataSetIterator(ds.batch_by(batch_size))


def IrisDataSetIterator(batch_size: int = 150, seed: int = 123) -> ListDataSetIterator:
    xs, ys = load_iris()
    ds = DataSet(xs, _one_hot(ys, 3)).shuffle(seed)
    return ListDataSetIterator(ds.batch_by(batch_size))
