"""DataSetIterator pipeline — composable minibatch iterators.

Parity with reference datasets/iterator/ (SURVEY.md §2.1 "Dataset iterator
layer"): AsyncDataSetIterator (background prefetch thread wrapped around any
iterator — reference AsyncDataSetIterator.java:30, used by
MultiLayerNetwork.fit():1169-1172), MultipleEpochsIterator,
EarlyTerminationDataSetIterator, SamplingDataSetIterator.

Iterators follow the reference's contract: ``reset()``, ``has_next()``,
``next()`` → DataSet, ``batch_size``, plus Python iteration sugar.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional

import numpy as np

from .dataset import DataSet


class DataSetIterator:
    """Abstract base (reference org.nd4j.linalg.dataset.api.iterator).

    ``set_pre_processor`` attaches a normalizer (datasets/normalizers.py)
    — the reference's ``iterator.setPreProcessor(normalizer)`` hook.  Like
    the reference, the preprocessor runs inside ``next()`` (every subclass
    override is auto-wrapped via ``__init_subclass__``), so wrapper
    iterators that pull batches through an inner iterator's ``next()``
    (AsyncDataSetIterator's producer thread, MultipleEpochs, ...) see
    normalized batches too — and async prefetch genuinely overlaps the
    normalization with device compute."""

    batch_size: int = 0
    pre_processor = None

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        nxt = cls.__dict__.get("next")
        if nxt is not None and not getattr(nxt, "_applies_pre", False):
            import functools

            @functools.wraps(nxt)
            def wrapped(self, *a, **kw):
                # reentrancy guard: a subclass next() that delegates via
                # super().next() hits TWO wraps on the same instance —
                # only the outermost may apply the preprocessor, or a
                # fitted normalizer would run twice
                if getattr(self, "_in_next", False):
                    return nxt(self, *a, **kw)
                self._in_next = True
                try:
                    ds = nxt(self, *a, **kw)
                finally:
                    self._in_next = False
                if self.pre_processor is not None and ds is not None:
                    ds = self.pre_processor.pre_process(ds)
                return ds

            wrapped._applies_pre = True
            cls.next = wrapped

    def set_pre_processor(self, pre_processor) -> "DataSetIterator":
        self.pre_processor = pre_processor
        return self

    def reset(self) -> None:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self) -> DataSet:
        raise NotImplementedError

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        while self.has_next():
            yield self.next()

    def total_examples(self) -> Optional[int]:
        return None


class ListDataSetIterator(DataSetIterator):
    """Iterate a list of pre-built minibatches (reference ListDataSetIterator
    / ExistingDataSetIterator)."""

    def __init__(self, batches: List[DataSet]):
        self._batches = list(batches)
        self._pos = 0
        self.batch_size = batches[0].num_examples() if batches else 0

    @staticmethod
    def from_arrays(features: np.ndarray, labels: np.ndarray, batch_size: int,
                    shuffle: bool = False, seed: Optional[int] = None) -> "ListDataSetIterator":
        ds = DataSet(features, labels)
        if shuffle:
            ds = ds.shuffle(seed)
        return ListDataSetIterator(ds.batch_by(batch_size))

    def reset(self) -> None:
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._batches)

    def next(self) -> DataSet:
        b = self._batches[self._pos]
        self._pos += 1
        return b

    def total_examples(self) -> int:
        return sum(b.num_examples() for b in self._batches)


class _ProducerFailure:
    """Exception carrier for background-producer iterators: a raise on the
    producer thread is enqueued instead of a batch and re-raised in
    ``next()``/``has_next()`` on the CONSUMER thread — never swallowed
    into a silently truncated epoch.  Shared with
    ``device_prefetch.DevicePrefetchIterator``."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch (reference AsyncDataSetIterator.java:30:
    'used to load batches in the background while training proceeds').

    ``prefetch`` matches the reference's queue capacity (default 2×).
    The producer thread fills a bounded queue; a sentinel marks exhaustion;
    a producer exception is enqueued and re-raised on the consumer thread.
    """

    _SENTINEL = object()

    def __init__(self, base: DataSetIterator, prefetch: int = 4):
        self._base = base
        self._prefetch = prefetch
        self.batch_size = base.batch_size
        self._queue: queue.Queue = queue.Queue(maxsize=prefetch)
        self._thread: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        self._peeked = None
        self._start()

    def _start(self) -> None:
        self._queue = queue.Queue(maxsize=self._prefetch)
        stop = threading.Event()
        self._stop = stop
        q = self._queue

        def _enqueue(item) -> None:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        def producer():
            err = None
            try:
                self._base.reset()
                while self._base.has_next() and not stop.is_set():
                    _enqueue(self._base.next())
            except BaseException as e:  # noqa: BLE001 — carried to consumer
                # a raise in base.next() used to hit the finally, enqueue
                # the sentinel, and truncate the epoch SILENTLY; carry it
                err = e
            finally:
                _enqueue(self._SENTINEL if err is None
                         else _ProducerFailure(err))

        self._thread = threading.Thread(target=producer, daemon=True)
        self._thread.start()

    def reset(self) -> None:
        """Tear down the producer (deadlock-free even mid-stream or after
        exhaustion) and start a fresh pass."""
        if self._thread is not None:
            self._stop.set()
            while self._thread.is_alive():
                try:  # unblock a producer stuck on a full queue
                    self._queue.get(timeout=0.05)
                except queue.Empty:
                    pass
            self._thread.join()
        self._peeked = None
        self._start()

    def _peek(self):
        if self._peeked is None:
            self._peeked = self._queue.get()
        return self._peeked

    def has_next(self) -> bool:
        item = self._peek()
        if isinstance(item, _ProducerFailure):
            # stays peeked: every subsequent call re-raises until reset()
            raise item.exc
        return item is not self._SENTINEL

    def next(self) -> DataSet:
        item = self._peek()
        if isinstance(item, _ProducerFailure):
            raise item.exc
        if item is self._SENTINEL:
            raise StopIteration
        self._peeked = None
        return item

    def total_examples(self):
        return self._base.total_examples()


class MultipleEpochsIterator(DataSetIterator):
    """Replays a base iterator for N epochs (reference MultipleEpochsIterator)."""

    def __init__(self, epochs: int, base: DataSetIterator):
        self._base = base
        self._epochs = epochs
        self._epoch = 0
        self.batch_size = base.batch_size
        base.reset()

    def reset(self) -> None:
        self._epoch = 0
        self._base.reset()

    def has_next(self) -> bool:
        if self._base.has_next():
            return True
        if self._epoch + 1 < self._epochs:
            self._epoch += 1
            self._base.reset()
            return self._base.has_next()
        return False

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self._base.next()


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Caps the number of minibatches per epoch (reference
    EarlyTerminationDataSetIterator)."""

    def __init__(self, base: DataSetIterator, max_batches: int):
        self._base = base
        self._max = max_batches
        self._count = 0
        self.batch_size = base.batch_size

    def reset(self) -> None:
        self._count = 0
        self._base.reset()

    def has_next(self) -> bool:
        return self._count < self._max and self._base.has_next()

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        self._count += 1
        return self._base.next()


class SamplingDataSetIterator(DataSetIterator):
    """Samples minibatches with replacement from a full DataSet (reference
    SamplingDataSetIterator)."""

    def __init__(self, dataset: DataSet, batch_size: int, batches_per_epoch: int,
                 seed: Optional[int] = None):
        self._ds = dataset
        self.batch_size = batch_size
        self._n_batches = batches_per_epoch
        self._count = 0
        self._rng = np.random.default_rng(seed)

    def reset(self) -> None:
        self._count = 0

    def has_next(self) -> bool:
        return self._count < self._n_batches

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        self._count += 1
        idx = self._rng.integers(0, self._ds.num_examples(), size=self.batch_size)
        return self._ds.get_rows(idx)


class KFoldIterator(DataSetIterator):
    """K-fold cross-validation over a DataSet (reference KFoldIterator):
    each ``next()`` yields the TRAIN split of the current fold; the
    held-out fold is available as ``test_fold()`` until the next call.

    >>> kf = KFoldIterator(ds, k=5)
    >>> for train in kf:
    ...     net.fit(train); scores.append(net.evaluate(kf.test_fold()))
    """

    def __init__(self, dataset: DataSet, k: int = 10,
                 shuffle_seed: Optional[int] = None):
        n = dataset.num_examples()
        if not 2 <= k <= n:
            raise ValueError(f"k must be in [2, num_examples={n}], got {k}")
        self._ds = dataset.shuffle(shuffle_seed) if shuffle_seed is not None \
            else dataset
        self.k = k
        # reference semantics: n % k remainder goes to the LAST fold
        base = n // k
        sizes = [base] * k
        sizes[-1] += n - base * k
        self._bounds = np.cumsum([0] + sizes)
        self._fold = 0
        self._test: Optional[DataSet] = None

    def reset(self) -> None:
        self._fold = 0
        self._test = None

    def has_next(self) -> bool:
        return self._fold < self.k

    def _take(self, idx) -> DataSet:
        return self._ds.get_rows(idx)

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        lo, hi = self._bounds[self._fold], self._bounds[self._fold + 1]
        n = self._ds.num_examples()
        test_idx = np.arange(lo, hi)
        train_idx = np.concatenate([np.arange(0, lo), np.arange(hi, n)])
        self._test = self._take(test_idx)
        self._fold += 1
        return self._take(train_idx)

    def test_fold(self) -> DataSet:
        """The held-out fold for the most recent ``next()`` — normalized by
        the attached pre_processor like the train split (evaluating raw
        features against a model trained on normalized ones would produce
        near-chance scores silently)."""
        if self._test is None:
            raise ValueError("call next() first")
        if self.pre_processor is not None:
            return self.pre_processor.pre_process(self._test)
        return self._test
