"""ctypes binding for the C++ data loader (native/data_loader.cpp).

The reference's data path crosses into native code for decode + minibatch
assembly (DataVec + libnd4j; AsyncDataSetIterator feeds device queues from
a Java prefetch thread — AsyncDataSetIterator.java:30).  Here the native
piece is a C++ shuffle/gather ring: registration once, background thread
assembles shuffled float32 minibatches, Python pops buffers and hands them
to jax.device_put.  GIL-free, no per-batch numpy fancy-indexing.

Build-on-first-use with g++ (toolchain baked into the image); falls back
to the pure-Python iterators when compilation is unavailable.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import Optional

import numpy as np

from .dataset import DataSet
from .iterators import DataSetIterator

logger = logging.getLogger("deeplearning4j_tpu")

_LIB = None
_LIB_LOCK = threading.Lock()
_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native", "data_loader.cpp")


def load_native_lib() -> Optional[ctypes.CDLL]:
    """Compile (once) and dlopen the loader; None if unavailable."""
    from ..utils.native_build import build_and_load
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB or None
        lib = build_and_load(_SRC, "libdl4jtpu_data.so", ("-lpthread",))
        if lib is None:
            _LIB = False
            return None
        lib.dl4j_loader_create.restype = ctypes.c_void_p
        lib.dl4j_loader_create.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_int]
        lib.dl4j_loader_next.restype = ctypes.c_int
        lib.dl4j_loader_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.dl4j_loader_reset.argtypes = [ctypes.c_void_p]
        lib.dl4j_loader_destroy.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return lib


class NativeDataSetIterator(DataSetIterator):
    """Shuffled minibatch iterator backed by the C++ prefetch ring.

    Epoch semantics match ListDataSetIterator(shuffle=True): a fresh
    deterministic shuffle per epoch (seed + epoch), remainder batch kept
    unless ``drop_remainder``.
    """

    def __init__(self, features: np.ndarray, labels: Optional[np.ndarray],
                 batch_size: int, seed: int = 123, prefetch: int = 3,
                 drop_remainder: bool = False):
        lib = load_native_lib()
        if lib is None:
            raise RuntimeError("native loader unavailable — use ListDataSetIterator")
        self._lib = lib
        # keep flat float32 copies alive for the C++ side to borrow
        self._x = np.ascontiguousarray(features, dtype=np.float32)
        self._y = None if labels is None else np.ascontiguousarray(labels, np.float32)
        self._feat_shape = self._x.shape[1:]
        self._lab_shape = None if self._y is None else self._y.shape[1:]
        n = self._x.shape[0]
        row_f = int(np.prod(self._feat_shape)) if self._feat_shape else 1
        row_y = int(np.prod(self._lab_shape)) if self._lab_shape else 0
        self.batch_size = batch_size
        self._row_f, self._row_y, self._n = row_f, row_y, n
        self._out_x = np.empty((batch_size, row_f), np.float32)
        self._out_y = np.empty((batch_size, max(row_y, 1)), np.float32)
        self._handle = lib.dl4j_loader_create(
            self._x.ctypes.data_as(ctypes.c_void_p),
            None if self._y is None else self._y.ctypes.data_as(ctypes.c_void_p),
            n, row_f, row_y, batch_size, prefetch, seed, int(drop_remainder))
        self._peeked: Optional[DataSet] = None
        self._done = False

    def _pull(self) -> Optional[DataSet]:
        rows = self._lib.dl4j_loader_next(
            self._handle,
            self._out_x.ctypes.data_as(ctypes.c_void_p),
            self._out_y.ctypes.data_as(ctypes.c_void_p))
        if rows == 0:
            return None
        x = self._out_x[:rows].reshape((rows,) + self._feat_shape).copy()
        y = None
        if self._y is not None:
            y = self._out_y[:rows, :self._row_y].reshape(
                (rows,) + self._lab_shape).copy()
        return DataSet(x, y)

    def has_next(self) -> bool:
        if self._done:
            return False
        if self._peeked is None:
            self._peeked = self._pull()
            if self._peeked is None:
                self._done = True
        return self._peeked is not None

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        ds, self._peeked = self._peeked, None
        return ds

    def reset(self) -> None:
        self._lib.dl4j_loader_reset(self._handle)
        self._peeked = None
        self._done = False

    def total_examples(self) -> int:
        return self._n

    def close(self) -> None:
        if self._handle:
            self._lib.dl4j_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except (OSError, AttributeError):
            # interpreter teardown: the ctypes lib or attrs may already
            # be gone — nothing to release at that point
            pass
