"""Device-resident input pipeline — double-buffered async H2D prefetch.

AsyncDataSetIterator overlaps host ETL (decode, batching, normalization)
with device compute, but the batch it hands over is still HOST memory:
every training step then pays a synchronous host→device copy inside
``fit_batch`` (``jnp.asarray``) — exactly the infeed stall the TensorFlow
input-pipeline design (Abadi et al., 2016, §4.2 "Input Operations") and
the TPU concurrency study (Kumar et al., 2020) identify as the dominant
overhead at high step rates.  :class:`DevicePrefetchIterator` closes that
gap: a background thread issues **non-blocking** ``jax.device_put`` calls
ahead of the consumer, keeping a depth-k ring of batches that are already

  * **on device** (the put is dispatched while the previous step computes,
    so the transfer rides under compute instead of serializing with it),
  * **pre-sharded** (pass a ``jax.sharding.Sharding`` and the whole batch
    pytree lands split across the mesh in one ``device_put(batch,
    sharding)`` — ``ShardedTrainer``'s per-step placement then passes it
    through untouched),
  * **narrow on the wire** (``cast_dtype="bfloat16"`` truncates floating
    feature arrays on the host side of the copy, halving wire bytes;
    uint8 pixels already cross at 1 byte/px and scale on chip), and
  * **already normalized** (``transform=`` a fitted normalizer compiles
    its statistics into a jitted on-device op — host numpy drops out of
    the steady-state path entirely).

Input-stall accounting: every ``next()`` measures the gap between "step
requested a batch" and "a batch was ready".  ``stall_stats()`` returns the
stall fraction / queue depth snapshot that ``ui.profiler
.input_pipeline_snapshot()`` and the StatsListener surface, and that the
``input_pipeline_overlap`` bench config gates on.

The synchronous path is untouched: not wrapping (or CLI ``--prefetch 0``)
runs exactly the pre-prefetch code, bit for bit.  See
docs/INPUT_PIPELINE.md.
"""

from __future__ import annotations

import queue
import threading
import time
import weakref
from typing import Any, Callable, Optional

import numpy as np

from ..obs import trace as obs_trace
from ..obs.metrics import get_registry
from .dataset import DataSet
from .iterators import DataSetIterator, _ProducerFailure

# live prefetchers, for the profiler/stats snapshot (weak: a dropped
# iterator must not be kept alive — its producer thread would be too)
_LIVE: "weakref.WeakSet[DevicePrefetchIterator]" = weakref.WeakSet()


def live_pipelines():
    """Snapshot list over the currently-live prefetch iterators (the
    ``ui.profiler.input_pipeline_snapshot`` backing store)."""
    return list(_LIVE)


# stall stats ride the unified registry too: one /metrics response
# answers "is this job input-bound" (docs/OBSERVABILITY.md)
get_registry().register_collector(
    "input_pipeline", lambda: [p.stall_stats() for p in live_pipelines()])


def device_put_batch(batch, placement=None):
    """Asynchronously transfer a pytree of host arrays to device.

    ``placement`` is a ``jax.sharding.Sharding`` (the whole pytree lands
    pre-sharded), a ``jax.Device``, or None (default device).  Leaves that
    are already ``jax.Array`` s on the requested placement pass through
    untouched — never a device→host→device round trip.  Shared by the
    prefetcher, ``ShardedTrainer`` consumers, and ``serving.Engine``'s
    per-replica parameter loads.
    """
    import jax

    def put(a):
        if isinstance(a, jax.Array):
            if placement is None:
                return a
            try:
                if isinstance(placement, jax.sharding.Sharding):
                    if a.sharding.is_equivalent_to(placement, a.ndim):
                        return a
                elif a.committed and a.devices() == {placement}:
                    return a
            # graftcheck: disable=GC404 (placement probe over jax APIs that differ across supported jax versions; the fall-through device_put is the always-correct path)
            except Exception:
                pass  # conservative: fall through to an explicit put
        return jax.device_put(a, placement)

    return jax.tree_util.tree_map(put, batch)


class DevicePrefetchIterator(DataSetIterator):
    """Wrap any DataSetIterator with a depth-k ring of device-resident
    batches (k=2 double-buffers: one batch feeding the current step, one
    in flight).

    Parameters
    ----------
    base: the host-side iterator to wrap (its ``next()`` — including any
        attached host pre_processor — runs on the producer thread).
    depth: ring size — batches transferred ahead of the consumer.
    sharding: optional ``jax.sharding.Sharding``; the batch pytree is
        placed with ONE ``device_put(batch, sharding)`` so a
        ``ShardedTrainer`` (pass its ``batch_sharding``) sees pre-sharded
        input and skips its per-step placement path.
    device: optional ``jax.Device`` (mutually exclusive with sharding).
    cast_dtype: optional wire dtype for FLOATING feature arrays — cast on
        the host side of the copy (``"bfloat16"`` halves wire bytes; the
        model's compute-dtype cast then runs on chip).  Labels, masks and
        integer features (uint8 pixels, token ids) are never cast.  Lossy
        for narrowing casts — the bitwise-parity guarantee vs the sync
        path holds only with ``cast_dtype=None``.
    transform: optional device-side batch transform — either a fitted
        normalizer (``datasets.normalizers``; its ``device_transform()``
        compiles the statistics into a jitted on-chip op) or any callable
        DataSet→DataSet over jax arrays.  Runs after the put, on the
        producer thread (dispatch is async).  If ``transform`` is the very
        normalizer attached to ``base`` as pre_processor, it is detached
        from the base for this pipeline — normalization moves on-device
        instead of running twice.
    """

    _SENTINEL = object()

    def __init__(self, base: DataSetIterator, depth: int = 2,
                 sharding=None, device=None,
                 cast_dtype: Optional[Any] = None,
                 transform: Optional[Callable] = None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        if sharding is not None and device is not None:
            raise ValueError("pass sharding OR device, not both")
        self._base = base
        self._depth = depth
        self._placement = sharding if sharding is not None else device
        if cast_dtype is None:
            self._cast = None
        else:
            import jax.numpy as jnp
            # "bfloat16" resolves through jnp (ml_dtypes-backed — plain
            # numpy has no bfloat16); numpy names resolve directly
            self._cast = np.dtype(getattr(jnp, str(cast_dtype), cast_dtype))
        if transform is not None and hasattr(transform, "device_transform"):
            if getattr(base, "pre_processor", None) is transform:
                base.pre_processor = None
            transform = transform.device_transform()
        self._transform = transform
        self.batch_size = base.batch_size
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._thread: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        self._peeked = None
        self._closed = False
        # stall accounting (cumulative across epochs/resets)
        self._lock = threading.Lock()
        self._batches = 0
        self._stalls = 0
        self._stall_seconds = 0.0
        self._first_request: Optional[float] = None
        self._last_ready: Optional[float] = None
        _LIVE.add(self)
        self._start()

    # -- producer ----------------------------------------------------------

    def _place(self, ds: DataSet) -> DataSet:
        """Host-cast (wire dtype) → async device put → jitted on-device
        transform.  Runs on the producer thread; device_put and jit
        dispatch are non-blocking, so by the time the consumer asks, the
        transfer has been riding under the previous step's compute."""
        feats = ds.features
        if self._cast is not None:
            a = np.asarray(feats)
            if np.issubdtype(a.dtype, np.floating):
                feats = a.astype(self._cast)
        placed = device_put_batch(
            (feats, ds.labels, ds.features_mask, ds.labels_mask),
            self._placement)
        out = DataSet(*placed)
        if self._transform is not None:
            out = self._transform(out)
        return out

    def _start(self) -> None:
        self._queue = queue.Queue(maxsize=self._depth)
        stop = threading.Event()
        self._stop = stop
        q = self._queue

        def _enqueue(item) -> None:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        def producer():
            err: Optional[BaseException] = None
            try:
                self._base.reset()
                while not stop.is_set() and self._base.has_next():
                    _enqueue(self._place(self._base.next()))
            except BaseException as e:  # noqa: BLE001 — carried, not eaten
                err = e
            finally:
                # exhaustion OR failure both close the stream explicitly;
                # a raise must reach the consumer, never truncate an epoch
                _enqueue(self._SENTINEL if err is None
                         else _ProducerFailure(err))

        self._thread = threading.Thread(
            target=producer, daemon=True, name="DevicePrefetchIterator")
        self._thread.start()

    def _teardown(self) -> None:
        """Stop the producer deadlock-free (it may be blocked on a full
        queue) and join it — no thread leaks on reset/close mid-stream."""
        if self._thread is None:
            return
        self._stop.set()
        while self._thread.is_alive():
            try:
                self._queue.get(timeout=0.05)
            except queue.Empty:
                pass
        self._thread.join()
        self._thread = None
        self._peeked = None

    # -- consumer ----------------------------------------------------------

    def _peek(self):
        if self._peeked is None:
            if self._closed:
                return self._SENTINEL
            t0 = time.perf_counter()
            # the data-wait leg of the step span taxonomy: how long the
            # consumer sat waiting for a device-resident batch
            with obs_trace.span("input/data_wait", cat="input"):
                item = self._queue.get()
            waited = time.perf_counter() - t0
            with self._lock:
                if self._first_request is None:
                    self._first_request = t0
                self._stall_seconds += waited
                if waited > 1e-3:
                    self._stalls += 1
                self._last_ready = t0 + waited
            self._peeked = item
        return self._peeked

    def has_next(self) -> bool:
        item = self._peek()
        if isinstance(item, _ProducerFailure):
            raise item.exc
        return item is not self._SENTINEL

    def next(self) -> DataSet:
        item = self._peek()
        if isinstance(item, _ProducerFailure):
            raise item.exc
        if item is self._SENTINEL:
            raise StopIteration
        self._peeked = None
        with self._lock:
            self._batches += 1
        return item

    def reset(self) -> None:
        """Restart the stream: tear the producer down (even mid-stream or
        after a failure) and spin a fresh pass.  Stall statistics are
        cumulative across resets — an epoch boundary is not a new run."""
        self._teardown()
        self._closed = False
        self._start()

    def close(self) -> None:
        """Tear down without restarting (mid-stream teardown); the
        iterator reports exhausted until ``reset()``."""
        self._teardown()
        self._closed = True

    def total_examples(self):
        return self._base.total_examples()

    # -- input-stall accounting --------------------------------------------

    def stall_stats(self) -> dict:
        """Snapshot of the request-vs-ready accounting.

        ``stall_fraction`` is the share of the consumer's wall clock (first
        request → last batch ready) spent waiting on the pipeline: ~0 means
        input is fully hidden under compute; → 1 means the step is
        input-bound (grow ``depth``, move ETL on-device, or shrink wire
        bytes).  The first batch always stalls — the ring starts empty."""
        with self._lock:
            wall = ((self._last_ready - self._first_request)
                    if self._first_request is not None
                    and self._last_ready is not None else 0.0)
            stall = self._stall_seconds
            n = self._batches
            return {
                "depth": self._depth,
                "queue_depth": self._queue.qsize(),
                "batches": n,
                "stalls": self._stalls,
                "stall_seconds": round(stall, 6),
                "stall_fraction": round(stall / wall, 6) if wall > 0 else (
                    1.0 if stall > 0 else 0.0),
                "avg_stall_ms": round(stall / n * 1e3, 3) if n else 0.0,
            }
