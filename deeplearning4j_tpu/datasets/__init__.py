from .dataset import DataSet, MultiDataSet
from .iterators import (
    DataSetIterator,
    ListDataSetIterator,
    AsyncDataSetIterator,
    MultipleEpochsIterator,
    EarlyTerminationDataSetIterator,
    SamplingDataSetIterator,
)
from .records import (
    CSVRecordReader,
    CSVSequenceRecordReader,
    ImageRecordReader,
    ImageRecordReaderDataSetIterator,
    RecordReaderDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)
