from .dataset import DataSet, MultiDataSet
from .device_prefetch import DevicePrefetchIterator, device_put_batch
from .iterators import (
    DataSetIterator,
    ListDataSetIterator,
    AsyncDataSetIterator,
    MultipleEpochsIterator,
    EarlyTerminationDataSetIterator,
    SamplingDataSetIterator,
    KFoldIterator,
)
from .records import (
    CSVRecordReader,
    CSVSequenceRecordReader,
    ImageRecordReader,
    ImageRecordReaderDataSetIterator,
    RecordReaderDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)
from .normalizers import (
    ImagePreProcessingScaler,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
)
from .remote import (
    LocalProvider,
    RemoteDataSetIterator,
    S3Provider,
    StorageProvider,
    load_dataset,
    register_provider,
    save_dataset,
)
