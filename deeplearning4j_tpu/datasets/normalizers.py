"""Data normalizers — fit statistics on an iterator, apply per batch.

Parity targets (nd4j dataset API, used by every reference example):
``NormalizerStandardize`` (zero-mean/unit-variance, optional label
normalization), ``NormalizerMinMaxScaler`` (range scaling),
``ImagePreProcessingScaler`` (pixel [0,255] → [min,max]), the
``DataSetIterator.setPreProcessor`` hook, ``revert*`` inverses, and
``NormalizerSerializer`` persistence (a model shipped for inference needs
its training-time statistics).

TPU inversion: normalizers here are FUNCTIONAL — ``pre_process`` returns
a new DataSet (the reference mutates INDArrays in place).  Statistics are
accumulated with a streaming one-pass sum/sum-of-squares in f64, so
fitting an iterator never materializes the corpus.  Transforms run as
plain numpy on host by default (the setPreProcessor path, overlapped
with device compute by AsyncDataSetIterator) — or as a jitted ON-DEVICE
op via ``device_transform()`` when attached to a
``DevicePrefetchIterator`` (docs/INPUT_PIPELINE.md), where the batch
uploads raw/narrow and normalizes on chip.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from .dataset import DataSet


class _Stats:
    """Streaming mean/std + min/max accumulator over [N, ...] batches,
    reduced over all axes except the trailing feature axes pattern used by
    DL4J: statistics are PER-FEATURE for rank-2 [mb, f], per-feature over
    (mb, t) for rank-3 [mb, t, f], and per-channel for rank-4 [mb, h, w, c].
    """

    def __init__(self):
        self.n = 0
        self.s1 = None
        self.s2 = None
        self.mn = None
        self.mx = None

    @staticmethod
    def _axes(arr: np.ndarray):
        return tuple(range(arr.ndim - 1))

    def update(self, arr: np.ndarray, mask: Optional[np.ndarray] = None) -> None:
        """``mask`` [mb, t] (variable-length sequences, rank-3 data):
        zero-padded timesteps are EXCLUDED from the statistics — the
        reference's DistributionStats masked-fit semantics; padding zeros
        would otherwise pull means toward 0 and lock minima at 0."""
        a = np.asarray(arr, np.float64)
        axes = self._axes(a)
        if (mask is not None and a.ndim == 3
                and np.asarray(mask).shape == a.shape[:2]):
            w = np.asarray(mask, np.float64)[..., None]
            cnt = int(w.sum())
            s1 = (a * w).sum(axis=axes)
            s2 = (a * a * w).sum(axis=axes)
            live = w != 0
            mn = np.where(live, a, np.inf).min(axis=axes)
            mx = np.where(live, a, -np.inf).max(axis=axes)
        else:
            cnt = int(np.prod([a.shape[i] for i in axes])) if axes else 1
            s1 = a.sum(axis=axes)
            s2 = (a * a).sum(axis=axes)
            mn = a.min(axis=axes)
            mx = a.max(axis=axes)
        if self.s1 is None:
            self.n, self.s1, self.s2, self.mn, self.mx = cnt, s1, s2, mn, mx
        else:
            self.n += cnt
            self.s1 += s1
            self.s2 += s2
            self.mn = np.minimum(self.mn, mn)
            self.mx = np.maximum(self.mx, mx)

    @property
    def mean(self) -> np.ndarray:
        return self.s1 / max(self.n, 1)

    @property
    def std(self) -> np.ndarray:
        var = self.s2 / max(self.n, 1) - self.mean ** 2
        return np.sqrt(np.maximum(var, 0.0))


class AbstractNormalizer:
    """Shared fit/pre_process plumbing.  ``fit`` accepts a DataSet or any
    DataSetIterator; ``pre_process`` returns a NEW DataSet."""

    def __init__(self, fit_labels: bool = False):
        self.fit_labels = fit_labels
        self._feat: Optional[_Stats] = None
        self._lab: Optional[_Stats] = None

    # -- fitting -----------------------------------------------------------

    def fit(self, data) -> "AbstractNormalizer":
        self._feat, self._lab = _Stats(), _Stats()
        for ds in self._iterate(data):
            self._feat.update(ds.features, ds.features_mask)
            if self.fit_labels and ds.labels is not None:
                self._lab.update(ds.labels, ds.labels_mask)
        if self._feat.s1 is None:
            raise ValueError("fit() saw no data")
        self._finalize()
        return self

    @staticmethod
    def _iterate(data):
        if isinstance(data, DataSet):
            yield data
            return
        # statistics must come from RAW data: if the source iterator
        # already has a normalizer attached (re-fit after more data, or a
        # second normalizer over the same iterator), suspend it for the
        # scan — fitting on transformed batches would yield a near-identity
        # normalizer silently
        pp = getattr(data, "pre_processor", None)
        if pp is not None:
            data.pre_processor = None
        try:
            for ds in data:
                yield ds
        finally:
            if pp is not None:
                data.pre_processor = pp

    def _finalize(self) -> None:
        pass

    def _check_fitted(self) -> None:
        if self._feat is None:
            raise ValueError(f"{type(self).__name__}: fit() before use "
                             "(or load() saved statistics)")

    # -- application -------------------------------------------------------

    def transform(self, arr: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def revert_features(self, arr: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform_labels(self, arr: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def revert_labels(self, arr: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def pre_process(self, ds: DataSet) -> DataSet:
        """DataSet → normalized DataSet (the setPreProcessor hook target)."""
        self._check_fitted()
        labels = ds.labels
        if self.fit_labels and labels is not None:
            labels = self.transform_labels(np.asarray(labels))
        return DataSet(self.transform(np.asarray(ds.features)), labels,
                       ds.features_mask, ds.labels_mask)

    __call__ = pre_process

    def device_transform(self):
        """Jit-compiled DataSet→DataSet transform for DEVICE-resident
        batches (the DevicePrefetchIterator hook): the fitted statistics
        become constants of a jitted on-chip op, so normalization runs on
        the TPU instead of host numpy — and overlaps with training via the
        prefetch ring.  Masks pass through; labels transform only when
        ``fit_labels`` was set.

        Caveats (docs/INPUT_PIPELINE.md): statistics are still FITTED on
        host (``fit`` scans raw numpy batches in f64) — re-fitting after
        more data requires building a fresh device transform; and the
        on-chip math runs in f32 (host numpy upcasts to f64 before the
        final f32 cast), so outputs can differ from ``pre_process`` by
        ~1 ulp unless the transform is exact (e.g. power-of-two pixel
        scales)."""
        self._check_fitted()
        import jax

        jx = jax.jit(self.transform)
        jy = jax.jit(self.transform_labels) if self.fit_labels else None

        def apply(ds: DataSet) -> DataSet:
            labels = ds.labels
            if jy is not None and labels is not None:
                labels = jax.tree_util.tree_map(jy, labels)
            return DataSet(jx(ds.features), labels,
                           ds.features_mask, ds.labels_mask)

        return apply

    def revert(self, ds: DataSet) -> DataSet:
        self._check_fitted()
        labels = ds.labels
        if self.fit_labels and labels is not None:
            labels = self.revert_labels(np.asarray(labels))
        return DataSet(self.revert_features(np.asarray(ds.features)), labels,
                       ds.features_mask, ds.labels_mask)

    # -- persistence (NormalizerSerializer parity) -------------------------

    _SAVE_KEYS = ()

    def save(self, path: str) -> None:
        self._check_fitted()
        np.savez(path if path.endswith(".npz") else path + ".npz",
                 __class__=np.bytes_(type(self).__name__),
                 fit_labels=np.asarray(self.fit_labels),
                 **{k: getattr(self, k) for k in self._SAVE_KEYS})

    @classmethod
    def load(cls, path: str) -> "AbstractNormalizer":
        with np.load(path if path.endswith(".npz") else path + ".npz") as z:
            saved_cls = z["__class__"].item().decode()
            if cls is AbstractNormalizer:
                # polymorphic restore (reference NormalizerSerializer.restore
                # reads the type header and dispatches); walk the whole
                # subclass tree so user classes deriving from a concrete
                # normalizer restore too
                def walk(c):
                    for s in c.__subclasses__():
                        yield s
                        yield from walk(s)
                by_name = {c.__name__: c for c in walk(cls)}
                if saved_cls not in by_name:
                    raise ValueError(f"{path} holds unknown normalizer "
                                     f"{saved_cls}")
                cls = by_name[saved_cls]
            if saved_cls != cls.__name__:
                raise ValueError(f"{path} holds a {saved_cls}, not {cls.__name__}")
            # bypass subclass __init__ (signatures differ — e.g.
            # ImagePreProcessingScaler takes no fit_labels); every field a
            # transform needs is in _SAVE_KEYS
            obj = cls.__new__(cls)
            AbstractNormalizer.__init__(obj, fit_labels=bool(z["fit_labels"]))
            obj._feat = _Stats()  # mark fitted
            for k in cls._SAVE_KEYS:
                setattr(obj, k, z[k])
        return obj


class NormalizerStandardize(AbstractNormalizer):
    """Zero-mean / unit-variance per feature (reference
    NormalizerStandardize; rank-3 stats pool over time, rank-4 per channel).
    """

    _SAVE_KEYS = ("mean", "std", "label_mean", "label_std")

    def __init__(self, fit_labels: bool = False):
        super().__init__(fit_labels)
        self.mean = self.std = self.label_mean = self.label_std = None

    def _finalize(self) -> None:
        self.mean = self._feat.mean
        self.std = np.maximum(self._feat.std, 1e-8)
        if self.fit_labels and self._lab.s1 is not None:
            self.label_mean = self._lab.mean
            self.label_std = np.maximum(self._lab.std, 1e-8)
        else:
            self.label_mean = np.zeros(1)
            self.label_std = np.ones(1)

    def transform(self, arr):
        return ((arr - self.mean) / self.std).astype(np.float32)

    def revert_features(self, arr):
        return (arr * self.std + self.mean).astype(np.float32)

    def transform_labels(self, arr):
        return ((arr - self.label_mean) / self.label_std).astype(np.float32)

    def revert_labels(self, arr):
        return (arr * self.label_std + self.label_mean).astype(np.float32)


class NormalizerMinMaxScaler(AbstractNormalizer):
    """Scale features to [min_range, max_range] per feature (reference
    NormalizerMinMaxScaler, default [0, 1])."""

    _SAVE_KEYS = ("data_min", "data_max", "label_min", "label_max",
                  "min_range", "max_range")

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0,
                 fit_labels: bool = False):
        super().__init__(fit_labels)
        if max_range <= min_range:
            raise ValueError(f"max_range {max_range} <= min_range {min_range}")
        self.min_range = np.float64(min_range)
        self.max_range = np.float64(max_range)
        self.data_min = self.data_max = None
        self.label_min = self.label_max = None

    def _finalize(self) -> None:
        self.data_min = self._feat.mn
        self.data_max = self._feat.mx
        if self.fit_labels and self._lab.s1 is not None:
            self.label_min, self.label_max = self._lab.mn, self._lab.mx
        else:
            self.label_min, self.label_max = np.zeros(1), np.ones(1)

    @staticmethod
    def _scale(arr, lo, hi, a, b):
        span = np.maximum(hi - lo, 1e-12)
        return ((arr - lo) / span * (b - a) + a).astype(np.float32)

    @staticmethod
    def _unscale(arr, lo, hi, a, b):
        span = np.maximum(hi - lo, 1e-12)
        return ((arr - a) / (b - a) * span + lo).astype(np.float32)

    def transform(self, arr):
        return self._scale(arr, self.data_min, self.data_max,
                           self.min_range, self.max_range)

    def revert_features(self, arr):
        return self._unscale(arr, self.data_min, self.data_max,
                             self.min_range, self.max_range)

    def transform_labels(self, arr):
        return self._scale(arr, self.label_min, self.label_max,
                           self.min_range, self.max_range)

    def revert_labels(self, arr):
        return self._unscale(arr, self.label_min, self.label_max,
                             self.min_range, self.max_range)


class ImagePreProcessingScaler(AbstractNormalizer):
    """Pixels [0, max_pixel] → [min_range, max_range] (reference
    ImagePreProcessingScaler; stateless — no fit required)."""

    _SAVE_KEYS = ("min_range", "max_range", "max_pixel")

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0,
                 max_pixel: float = 255.0):
        super().__init__(fit_labels=False)
        if max_range <= min_range:
            raise ValueError(f"max_range {max_range} <= min_range {min_range}")
        self.min_range = np.float64(min_range)
        self.max_range = np.float64(max_range)
        self.max_pixel = np.float64(max_pixel)
        self._feat = _Stats()  # stateless: always "fitted"

    def fit(self, data):  # fit is a no-op (kept for API parity)
        return self

    def transform(self, arr):
        return (arr / self.max_pixel
                * (self.max_range - self.min_range)
                + self.min_range).astype(np.float32)

    def revert_features(self, arr):
        return ((arr - self.min_range)
                / (self.max_range - self.min_range)
                * self.max_pixel).astype(np.float32)
